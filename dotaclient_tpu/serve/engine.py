"""Continuous-batching inference engine: the serving plane's hot loop.

Training's batched-env idiom (TF Agents, PAPERS.md) inverted: instead of one
program stepping N resident envs, N remote games each want ONE action at
tight latency. The engine collects per-game step requests into preallocated
staging lanes until ``serve.batch_window_ms`` elapses or ``serve.max_batch``
requests are staged (whichever first), runs ONE jitted dispatch over the
padded batch, and scatters sampled actions back per requester — Podracer's
one-program-per-dispatch discipline (PAPERS.md) applied to serving.

Carry residency: recurrent state never rides the wire. Each attached game
owns a server-resident carry SLOT; the dispatch gathers the batch's slot
rows from the carry store, steps the core, and scatters the new rows back —
all inside the one compiled program. Row ``max_slots`` is a scratch slot:
padding rows of a partial batch gather it (reset-zeroed) and scatter into
it, so they can never touch a live game's state, and duplicate scatter
indices cannot occur (a window never holds two requests for one slot — the
second waits for the next window, preserving per-game request order).

Weight swaps are hot and atomic at dispatch granularity: ``submit_weights``
parks a (version, host params) pair in a latest-wins slot (monotonic —
stale versions are dropped); the batcher commits it to device BETWEEN
dispatches, so every action in one batch is sampled by exactly one weights
version (the version rides each reply). Slot releases are marshalled the
same way: ``release_slot`` enqueues, the batcher zeroes the carry row
between dispatches — every carry mutation happens on the batcher thread.

Sampling determinism: dispatch ``i`` samples with ``fold_in(key(seed), i)``.
The parity digest (bench.py serve stage) replays the same request stream
through this same compiled function in-process and requires bitwise-equal
actions — the transport and batching machinery must be invisible to the
policy.

Telemetry (eager-created; ``check_telemetry_schema.py --require-serve``):
``serve/requests_total``, ``serve/replies_total``, ``serve/reply_errors_total``,
``serve/dispatches_total``, ``serve/batch_window_hits``,
``serve/max_batch_hits``, ``serve/batch_fill``, ``serve/p99_latency_ms``,
``serve/weights_version``, ``serve/weight_swaps_total``, and the
``serve/request`` span (arrival→reply wall time per request).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dotaclient_tpu.config import RunConfig
from dotaclient_tpu.models import distributions as D
from dotaclient_tpu.models.policy import Policy, dummy_obs_batch, mask_carry
from dotaclient_tpu.utils import telemetry, utilization

logger = logging.getLogger(__name__)

# reply callback: (packed_actions [5] int32, logp, weights_version,
# request_id, dispatch_index). Must never block for long — it runs on the
# batcher thread (socket replies enqueue to a per-connection writer).
# Carry-shadow engines (ISSUE 19) additionally pass carry=<wire dict> by
# keyword; default-mode callbacks never see the kwarg.
ReplyFn = Callable[[np.ndarray, float, int, int, int], None]


@dataclasses.dataclass
class _Request:
    slot: int
    obs: Dict[str, np.ndarray]
    reset: float
    t0: float
    reply: ReplyFn
    request_id: int


class ServeEngine:
    """One batcher thread + preallocated staging lanes + a carry store."""

    def __init__(
        self,
        config: RunConfig,
        policy: Policy,
        params: Any,
        version: int = 0,
        registry: Optional[telemetry.Registry] = None,
    ) -> None:
        scfg = config.serve
        self._config = config
        self._scfg = scfg
        self._policy = policy
        self._tel = (
            registry if registry is not None else telemetry.get_registry()
        )
        B, S = scfg.max_batch, scfg.max_slots
        self._scratch_slot = S  # padding rows gather/scatter here, never a game
        # Preallocated staging lanes: one [max_batch, ...] host row block
        # per obs leaf (the PR 2 buffer staging idiom — request arrays are
        # copied in, never stacked fresh per window).
        template = dummy_obs_batch(1, config.obs, config.actions)
        self._lanes: Dict[str, np.ndarray] = {
            name: np.zeros((B,) + arr.shape[1:], arr.dtype)
            for name, arr in template.items()
        }
        # per-leaf row shapes/element counts for submit-time validation: a
        # decodable request whose obs tree does not fit the lanes must be
        # rejected at the door (the READER's thread), never reach the
        # batcher — one shape-skewed client must not kill dispatch for
        # everyone. A separate immutable dict: validation runs on
        # submitting threads and must not touch the batcher-owned lanes.
        self._row_shapes: Dict[str, Tuple[int, ...]] = {
            name: arr.shape[1:] for name, arr in template.items()
        }
        self._slots_np = np.full((B,), self._scratch_slot, np.int32)
        self._reset_np = np.ones((B,), np.float32)
        # Server-resident carries: one row per attached game + the scratch
        # row. Committed to device once; every later mutation happens
        # inside the donated dispatch (or the donated slot-zero program).
        self._carries = jax.tree.map(
            jnp.asarray, policy.initial_state(S + 1)
        )
        self._params = jax.device_put(params)
        self._version = version
        self._rng0 = jax.random.PRNGKey(scfg.seed)
        self._dispatch_idx = 0
        self._cond = threading.Condition()
        self._pending: Deque[_Request] = deque()
        self._reset_slots: Set[int] = set()
        self._stopped = False
        self._weights_lock = threading.Lock()
        self._pending_weights: Optional[Tuple[int, Any]] = None
        # Carry-shadow plane (ISSUE 19): when enabled, every reply also
        # hands the requester its updated carry ROW (host numpy), and a
        # re-homed client resends that row so its session resumes
        # bit-exact on a fresh backend. Inbound rows park here (slot →
        # host row tree) and the batcher installs them BETWEEN dispatches
        # — the same marshalling discipline as slot zeroes.
        self._carry_shadow = bool(scfg.carry_shadow)
        self._install_carries: Dict[int, Any] = {}
        # one carry ROW's pytree shape: the wire flatten/unflatten template
        # (leaves keyed c0..cN in jax.tree order)
        row_template = policy.initial_state(1)
        self._carry_row_treedef = jax.tree_util.tree_structure(row_template)
        self._carry_row_shapes = [
            np.asarray(leaf).shape[1:]
            for leaf in jax.tree_util.tree_leaves(row_template)
        ]

        def _dispatch_impl(params, obs, slots, reset, carries, rng):
            carry = jax.tree.map(lambda c: c[slots], carries)   # [B, ...]
            # reset rows (fresh episodes AND padding rows) start from zeros
            carry = mask_carry(carry, 1.0 - reset)
            logits, _, carry2 = self._policy.apply(
                params, obs, carry, method="step"
            )
            acts, logp = D.sample(rng, logits, obs)
            packed = jnp.stack(
                [acts[h] for h in D.HEADS], axis=1
            ).astype(jnp.int32)
            new_carries = jax.tree.map(
                lambda store, new: store.at[slots].set(new), carries, carry2
            )
            # carry2 (the batch's per-row NEW carries) is returned for the
            # shadow plane; the host fetch is gated on the knob, so the
            # default path never pays the transfer
            return packed, logp.astype(jnp.float32), new_carries, carry2

        # carries donated: the store updates in place in HBM every dispatch.
        # instrument_jit (ISSUE 12): serve recompiles are latency cliffs —
        # the per-program compile counters name them; the donation lint
        # unwraps the wrapper, so the call site keeps its taint tracking.
        from dotaclient_tpu.utils import tracing

        tracing.ensure_metrics(self._tel)
        self._dispatch_fn = tracing.instrument_jit(
            jax.jit(_dispatch_impl, donate_argnums=(4,)),
            "serve_dispatch",
            self._tel,
        )

        def _zero_slots_impl(carries, slots):
            return jax.tree.map(
                lambda c: c.at[slots].set(jnp.zeros_like(c[slots])), carries
            )

        self._zero_slots_fn = jax.jit(_zero_slots_impl, donate_argnums=(0,))

        def _install_carry_impl(carries, slot, row):
            # row leaves arrive [1, ...] (a one-row tree); cast to the
            # store dtype so a narrowed wire row still installs
            return jax.tree.map(
                lambda c, r: c.at[slot].set(
                    jnp.reshape(r, c.shape[1:]).astype(c.dtype)
                ),
                carries, row,
            )

        self._install_carry_fn = jax.jit(
            _install_carry_impl, donate_argnums=(0,)
        )

        # eager-create: a serve run that never falls into a state still
        # reports zeros (check_telemetry_schema.py --require-serve)
        for name in (
            "serve/requests_total",
            "serve/replies_total",
            "serve/reply_errors_total",
            "serve/dispatches_total",
            "serve/batch_window_hits",
            "serve/max_batch_hits",
            "serve/weight_swaps_total",
            "serve/dispatch_errors_total",
            "serve/carry_installs_total",
        ):
            self._tel.counter(name)
        self._tel.gauge("serve/batch_fill")
        self._tel.gauge("serve/p99_latency_ms")
        self._tel.gauge("serve/weights_version").set(float(version))
        self._tel.timer("span/serve/request")
        # Pipeline utilization plane (ISSUE 16): window_wait / dispatch /
        # reply splits of the batcher thread's wall clock. Eager keys
        # either way; None when the module knob is off (one pointer test
        # per loop turn).
        self._util = utilization.make_serve(self._tel)
        self._batcher = threading.Thread(
            target=self._batch_loop, name="serve-batcher", daemon=True
        )
        self._batcher.start()

    # -- submission (reader / weight-swap threads) ---------------------------

    @property
    def max_slots(self) -> int:
        return self._scfg.max_slots

    @property
    def version(self) -> int:
        """Weights version of the LAST committed swap. Latched int written
        by the batcher; readers (attach frames) tolerate one-dispatch-stale
        values by design."""
        return self._version

    def _validate_obs(self, obs: Dict[str, np.ndarray]) -> None:
        """Reject a request whose obs tree cannot land in the staging
        lanes — missing leaves or wrong element counts (a version-skewed
        client's config). Runs on the SUBMITTING thread, so the error
        surfaces where the wire's poison discipline can count it and the
        batcher never sees an undispatable row."""
        for name, row_shape in self._row_shapes.items():
            leaf = obs.get(name)
            if leaf is None:
                raise ValueError(f"request missing obs leaf {name!r}")
            shape = np.shape(leaf)
            if int(np.prod(shape, dtype=np.int64)) != int(
                np.prod(row_shape, dtype=np.int64)
            ):
                raise ValueError(
                    f"request obs leaf {name!r} has shape {shape} — "
                    f"incompatible with the serving lane {row_shape} "
                    f"(config skew between client and server?)"
                )

    def submit(
        self,
        slot: int,
        obs: Dict[str, np.ndarray],
        reset: bool,
        reply: ReplyFn,
        request_id: int = 0,
        carry: Optional[Dict[str, np.ndarray]] = None,
    ) -> None:
        """Queue one game's step request. ``obs`` is a single observation
        (unbatched leaves matching the staging-lane template; validated
        here, on the caller's thread); ``reset`` marks the first step of
        an episode (the slot's carry row is zeroed before the core — the
        actor-side episode-boundary discipline). ``carry`` is a re-homed
        session's shadowed row (the wire dict of :meth:`carry_row_to_wire`)
        — installed into the slot by the batcher BEFORE this request
        dispatches, so the session resumes where its dead backend left
        off. Rejected when carry_shadow is off (an unexpected carry is a
        protocol skew, and the poison discipline should see it)."""
        if not 0 <= slot < self._scfg.max_slots:
            raise ValueError(
                f"slot {slot} out of range [0, {self._scfg.max_slots})"
            )
        self._validate_obs(obs)
        row = None
        if carry is not None:
            if not self._carry_shadow:
                raise ValueError(
                    "request carries a shadow row but serve.carry_shadow "
                    "is off on this backend (fleet config skew)"
                )
            row = self.wire_to_carry_row(carry)
        req = _Request(
            slot=slot,
            obs=obs,
            reset=1.0 if reset else 0.0,
            t0=time.perf_counter(),
            reply=reply,
            request_id=request_id,
        )
        with self._cond:
            if self._stopped:
                raise RuntimeError("serve engine is stopped")
            if row is not None:
                # latest-wins per slot; ordered before the request it
                # rode in on (installs drain before the next window)
                self._install_carries[slot] = row
            self._pending.append(req)
            self._cond.notify()
        self._tel.counter("serve/requests_total").inc()

    def submit_weights(self, version: int, params: Any) -> None:
        """Latest-wins weight refresh (host params). Applied by the batcher
        BETWEEN dispatches; versions at or below the newest seen are
        dropped — published versions are monotonic on the wire, so a stale
        frame is a reorder, never a rollback."""
        with self._weights_lock:
            newest = (
                self._pending_weights[0]
                if self._pending_weights is not None
                else self._version
            )
            if version <= newest:
                return
            self._pending_weights = (version, params)
        with self._cond:
            self._cond.notify()

    def release_slot(self, slot: int) -> None:
        """A game detached (disconnect, quarantine): zero its carry row so
        the slot's next owner starts fresh even if it never sends reset.
        Marshalled to the batcher — carry mutations never race a dispatch.
        The dead game's still-pending requests are DISCARDED here: a stale
        request dispatched after the zero would scatter the old game's
        carry back into the reclaimed row (and its requester is gone
        anyway — nobody is waiting on the reply)."""
        with self._cond:
            if any(r.slot == slot for r in self._pending):
                self._pending = deque(
                    r for r in self._pending if r.slot != slot
                )
            self._reset_slots.add(slot)
            self._cond.notify()

    def stop(self, timeout: float = 30.0) -> None:
        """Serve every pending request, then stop the batcher (tests and
        bench teardown; production engines live for the process)."""
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        self._batcher.join(timeout)

    @property
    def pending(self) -> int:
        with self._cond:
            return len(self._pending)

    # -- batcher thread ------------------------------------------------------

    def _batch_loop(self) -> None:
        while True:
            with self._cond:
                while (
                    not self._pending
                    and not self._reset_slots
                    and not self._stopped
                    and self._peek_pending_weights() is None
                ):
                    # idle waiting for ANY request counts as window_wait:
                    # the batcher is request-starved either way
                    t_w = time.perf_counter()
                    self._cond.wait()
                    if self._util is not None:
                        self._util.phase(
                            "window_wait", time.perf_counter() - t_w
                        )
                if self._stopped and not self._pending:
                    return
                resets = list(self._reset_slots)
                self._reset_slots.clear()
                installs = list(self._install_carries.items())
                self._install_carries.clear()
            if resets:
                self._carries = self._zero_slots_fn(
                    self._carries, np.asarray(resets, np.int32)
                )
            for slot, row in installs:
                # after zeroes (a reclaimed slot re-attached with a shadow
                # row must keep the row), before the window that carries
                # the re-homed request
                self._carries = self._install_carry_fn(
                    self._carries, np.int32(slot), row
                )
                self._tel.counter("serve/carry_installs_total").inc()
            self._apply_pending_weights()
            rows = self._collect_window()
            if rows:
                try:
                    self._dispatch_window(rows)
                except Exception as e:  # noqa: BLE001 - the batcher must outlive any window
                    # submit-time validation makes this unreachable for
                    # request-shaped trouble; whatever remains (device
                    # error, OOM) must not silently wedge serving for
                    # every client — count it and keep dispatching
                    self._tel.counter("serve/dispatch_errors_total").inc()
                    logger.warning(
                        "serve dispatch failed (%s: %s) — window of %d "
                        "request(s) dropped; batcher continues",
                        type(e).__name__, e, len(rows),
                    )
            if self._util is not None:
                self._util.maybe_fold()

    def _peek_pending_weights(self) -> Optional[Tuple[int, Any]]:
        with self._weights_lock:
            return self._pending_weights

    def _apply_pending_weights(self) -> None:
        with self._weights_lock:
            pending, self._pending_weights = self._pending_weights, None
        if pending is None:
            return
        version, params = pending
        # one commit per swap; the next dispatch reads the new tree. The
        # old params buffers free once the last dispatch using them lands.
        self._params = jax.device_put(params)
        self._version = version
        self._tel.gauge("serve/weights_version").set(float(version))
        self._tel.counter("serve/weight_swaps_total").inc()

    def _collect_window(self) -> List[_Request]:
        scfg = self._scfg
        window_s = scfg.batch_window_ms / 1e3
        rows: List[_Request] = []
        slots: Set[int] = set()
        deadline: Optional[float] = None
        while True:
            with self._cond:
                held: List[_Request] = []
                while self._pending and len(rows) < scfg.max_batch:
                    req = self._pending.popleft()
                    if req.slot in slots:
                        # one outstanding request per slot per dispatch:
                        # a pipelining client's second request waits for
                        # the next window (duplicate scatter indices would
                        # make the carry update order-undefined)
                        held.append(req)
                        continue
                    rows.append(req)
                    slots.add(req.slot)
                for req in reversed(held):
                    self._pending.appendleft(req)
                if not rows:
                    return rows
                if deadline is None:
                    # the window opened when the FIRST request arrived,
                    # not when the batcher noticed it
                    deadline = rows[0].t0 + window_s
                if len(rows) >= scfg.max_batch:
                    self._tel.counter("serve/max_batch_hits").inc()
                    return rows
                now = time.perf_counter()
                if now >= deadline or self._stopped:
                    self._tel.counter("serve/batch_window_hits").inc()
                    return rows
                self._cond.wait(min(deadline - now, 0.05))
                if self._util is not None:
                    self._util.phase(
                        "window_wait", time.perf_counter() - now
                    )

    def _dispatch_window(self, rows: List[_Request]) -> None:
        n = len(rows)
        lanes = self._lanes
        for i, req in enumerate(rows):
            for name, lane in lanes.items():
                # the one host copy per request; reshape absorbs the wire
                # codec's 0-d→(1,) scalar normalization (zero-copy view)
                lane[i] = np.asarray(req.obs[name]).reshape(lane.shape[1:])
            self._slots_np[i] = req.slot
            self._reset_np[i] = req.reset
        self._slots_np[n:] = self._scratch_slot
        self._reset_np[n:] = 1.0            # padding gathers a zeroed carry
        rng = jax.random.fold_in(self._rng0, self._dispatch_idx)
        t_d = time.perf_counter()
        with self._tel.span("serve/dispatch"):
            packed, logp, self._carries, carry2 = self._dispatch_fn(
                self._params, lanes, self._slots_np, self._reset_np,
                self._carries, rng,
            )
            # the serving plane's one sync: replies need host actions
            packed_np = np.asarray(packed)   # host-sync-ok: serve batcher thread — replies leave the process here
            logp_np = np.asarray(logp)       # host-sync-ok: serve batcher thread
            carry2_np = (
                jax.tree.map(np.asarray, carry2)   # host-sync-ok: serve batcher thread — shadow rows ride the replies
                if self._carry_shadow
                else None
            )
        idx = self._dispatch_idx
        self._dispatch_idx += 1
        version = self._version
        t_done = time.perf_counter()
        if self._util is not None:
            self._util.phase("dispatch", t_done - t_d)
        timer = self._tel.timer("span/serve/request")
        errors = 0
        for i, req in enumerate(rows):
            timer.observe(t_done - req.t0)
            try:
                if carry2_np is None:
                    req.reply(
                        packed_np[i], float(logp_np[i]), version,
                        req.request_id, idx,
                    )
                else:
                    req.reply(
                        packed_np[i], float(logp_np[i]), version,
                        req.request_id, idx,
                        carry=self.carry_row_to_wire(
                            jax.tree.map(lambda c: c[i], carry2_np)
                        ),
                    )
            except Exception:   # noqa: BLE001 - a dead client must not kill the batcher
                errors += 1
        if self._util is not None:
            self._util.phase("reply", time.perf_counter() - t_done)
        self._tel.counter("serve/dispatches_total").inc()
        self._tel.counter("serve/replies_total").inc(n - errors)
        if errors:
            self._tel.counter("serve/reply_errors_total").inc(errors)
        self._tel.gauge("serve/batch_fill").set(n / self._scfg.max_batch)
        self._tel.gauge("serve/p99_latency_ms").set(
            timer.quantile(0.99) * 1e3
        )

    # -- parity probe --------------------------------------------------------

    def reference_step(
        self,
        obs_rows: List[Dict[str, np.ndarray]],
        slots: List[int],
        resets: List[float],
        carries: Any,
        dispatch_idx: int,
        params: Any = None,
    ) -> Tuple[np.ndarray, np.ndarray, Any]:
        """Replay one dispatch through the SAME compiled function the
        batcher runs — the in-process reference the serve parity digest
        compares server replies against (bench.py serve stage). Maintains
        its OWN carry tree (pass the previous call's return), so it never
        perturbs the live store. Returns ``(packed [B,5], logp [B],
        carries)``; rows past ``len(obs_rows)`` are padding."""
        B = self._scfg.max_batch
        lanes = {
            name: np.zeros_like(lane) for name, lane in self._lanes.items()
        }
        slots_np = np.full((B,), self._scratch_slot, np.int32)
        reset_np = np.ones((B,), np.float32)
        for i, obs in enumerate(obs_rows):
            for name, lane in lanes.items():
                lane[i] = np.asarray(obs[name]).reshape(lane.shape[1:])
            slots_np[i] = slots[i]
            reset_np[i] = resets[i]
        rng = jax.random.fold_in(self._rng0, dispatch_idx)
        # donated carries: callers thread the returned tree back in
        packed, logp, carries, _carry2 = self._dispatch_fn(
            self._params if params is None else jax.device_put(params),
            lanes, slots_np, reset_np, carries, rng,
        )
        return np.asarray(packed), np.asarray(logp), carries   # host-sync-ok: parity probe, off the serving path

    # -- carry-shadow wire form ----------------------------------------------

    def carry_row_to_wire(self, row: Any) -> Dict[str, np.ndarray]:
        """One carry row tree → the flat wire dict replies ship
        (``{"c0": leaf, ...}`` in ``jax.tree`` leaf order). The treedef
        stays server-side; clients stash and resend the dict opaquely."""
        return {
            f"c{i}": np.asarray(leaf)
            for i, leaf in enumerate(jax.tree_util.tree_leaves(row))
        }

    def wire_to_carry_row(self, wire: Dict[str, np.ndarray]) -> Any:
        """Inverse of :meth:`carry_row_to_wire`, validated on the
        SUBMITTING thread (bad structure raises → the wire's poison
        discipline counts it, the batcher never sees it)."""
        n = len(self._carry_row_shapes)
        leaves = []
        for i, shape in enumerate(self._carry_row_shapes):
            leaf = wire.get(f"c{i}")
            if leaf is None:
                raise ValueError(
                    f"shadow carry missing leaf c{i} (expected {n})"
                )
            arr = np.asarray(leaf)
            if int(np.prod(arr.shape, dtype=np.int64)) != int(
                np.prod(shape, dtype=np.int64)
            ):
                raise ValueError(
                    f"shadow carry leaf c{i} has shape {arr.shape} — "
                    f"incompatible with the carry row {shape}"
                )
            leaves.append(arr.reshape(shape))
        if len(wire) != n:
            raise ValueError(
                f"shadow carry has {len(wire)} leaves, expected {n}"
            )
        return jax.tree_util.tree_unflatten(self._carry_row_treedef, leaves)
