"""Low-latency policy-serving plane (ISSUE 11).

Everything before this package feeds training; this is the inverse
workload — many concurrent games each wanting ONE action at tight latency.
``ServeEngine`` is the continuous-batching core (staged request windows,
one jitted dispatch, server-resident carries), ``PolicyServer``/
``ServeClient`` put it on the shared CRC wire lane, and ``policy_path``
builds the inference-only param tree from training checkpoints or
published weights frames. See docs/ARCHITECTURE.md "Policy serving plane".
"""

from dotaclient_tpu.serve.client import (
    ServeClient,
    ServeDeadlineError,
    serve_request_wire_kwargs,
)
from dotaclient_tpu.serve.engine import ServeEngine
from dotaclient_tpu.serve.policy_path import (
    load_inference_params,
    make_inference_policy,
    slice_train_params,
    weights_frame_to_params,
)
from dotaclient_tpu.serve.router import SessionRouter, route_call
from dotaclient_tpu.serve.server import PolicyServer

__all__ = [
    "PolicyServer",
    "ServeClient",
    "ServeDeadlineError",
    "ServeEngine",
    "SessionRouter",
    "load_inference_params",
    "make_inference_policy",
    "route_call",
    "serve_request_wire_kwargs",
    "slice_train_params",
    "weights_frame_to_params",
]
