"""Session-affine router for a serve-backend fleet (ISSUE 19).

PR 11's single ``PolicyServer`` is three orders of magnitude short of the
ROADMAP's "millions of concurrent games"; the missing robustness half is
horizontal scale-out. The :class:`SessionRouter` is the control plane of
that scale-out: it maps each game (a *session*) to one of N backends and
keeps the map honest under failure. Data traffic never touches the router —
clients talk to their assigned backend directly over the PR 11 serve lane;
the router only answers the cheap control questions ("where do I attach?",
"where is my session now?") over two new JSON-payload frame kinds on the
shared CRC wire (``KIND_ROUTE_REQUEST``/``KIND_ROUTE_REPLY``).

Liveness is the existing heartbeat/idle discipline turned outward: the
router holds ONE persistent probe connection per backend (it occupies one
carry slot — budget ``serve.max_slots`` accordingly) and ships heartbeat
frames (kind 2, which the backend reader ignores by design) at
``serve.router_probe_s``. A SIGKILL'd backend surfaces as EOF/RST on that
connection within one probe turn; the probe then tries to reconnect for
``serve.router_dead_after_s`` before the backend is declared DEAD — a
transient blip inside the grace window is not a death.

On death the router **re-homes**: a hot spare (a normal backend process
subscribed to the same weights fanout, registered with ``--spares``) is
promoted — a routing change only, never a weight load — and every session
of the dead backend is reassigned to the least-loaded live backend, its
assignment epoch bumped so the client's next ``where`` sees the redirect.
The state contract is the client's (serve/client.py): default mode resumes
on a fresh zeroed carry slot (the reset_recurrent discipline, counted);
carry-shadow mode resends the stashed carry row so the session resumes
bit-exact (the chaos/bench parity digest pins it).

Telemetry (all ``router/*`` keys eager-created at construction;
``check_telemetry_schema.py --require-router``): session and re-home
counters, live/dead/spare gauges, per-backend session counts
(``router/backend/<i>/sessions``). The router process runs the PR 13 alert
engine over its own registry, so ``serve_peer_dead`` pages (and
``sessions_rehomed_burst`` warns) from the router's metrics JSONL with the
same ``ALERT`` event durability the learner has.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from dotaclient_tpu.transport.socket_transport import (
    FrameCorrupt,
    FramingLost,
    _recv_frame,
    _send_frame,
)
from dotaclient_tpu.utils import telemetry

# Route control frames extend the shared wire kind space (0-2 training
# transport, 3-4 serve request/reply, 5 fleet metrics). Payloads are JSON —
# control traffic is tiny and schema-fluid; the CRC trailer still applies.
KIND_ROUTE_REQUEST = 6
KIND_ROUTE_REPLY = 7

_KIND_HEARTBEAT = 2   # probe frames; the backend reader skips kind != 3


def route_call(
    sock: socket.socket, request: Dict[str, Any], timeout: float = 5.0
) -> Dict[str, Any]:
    """One control round-trip on an open router connection: send a JSON
    route request, block for the JSON reply (skipping any other kind to
    stay in sync — the client discipline of the serve lane)."""
    sock.settimeout(timeout)
    _send_frame(sock, KIND_ROUTE_REQUEST, json.dumps(request).encode())
    while True:
        frame = _recv_frame(sock)
        if frame is None:
            raise ConnectionError("router closed the connection")
        kind, payload = frame
        if kind != KIND_ROUTE_REPLY:
            continue
        return json.loads(bytes(payload).decode())


class _Backend:
    """One registered backend: address, liveness, and its session set.
    All mutable fields are guarded by the router's one lock except the
    probe thread's private socket."""

    __slots__ = (
        "index", "addr", "spare", "live", "sessions", "probe_sock",
        "last_ok",
    )

    def __init__(self, index: int, addr: Tuple[str, int], spare: bool):
        self.index = index
        self.addr = addr
        self.spare = spare          # not in the assignment pool until promoted
        self.live = False           # probe-confirmed reachability
        self.sessions: set = set()  # session ids homed here
        self.probe_sock: Optional[socket.socket] = None
        self.last_ok = 0.0


class SessionRouter:
    """Session→backend affinity map + liveness probes + re-homing."""

    def __init__(
        self,
        config: Any,
        backends: List[Tuple[str, int]],
        spares: Optional[List[Tuple[str, int]]] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        registry: Optional[telemetry.Registry] = None,
    ) -> None:
        if not backends:
            raise ValueError("a router needs at least one active backend")
        scfg = config.serve
        self._probe_s = max(0.05, scfg.router_probe_s)
        self._dead_after_s = max(self._probe_s, scfg.router_dead_after_s)
        self._tel = (
            registry if registry is not None else telemetry.get_registry()
        )
        self._lock = threading.Lock()
        self._backends: List[_Backend] = [
            _Backend(i, addr, spare=False)
            for i, addr in enumerate(backends)
        ]
        for addr in spares or []:
            self._backends.append(
                _Backend(len(self._backends), addr, spare=True)
            )
        # session id → (backend index, assignment epoch, rehomed flag).
        # Epochs are per-session and bump on every reassignment, so a
        # client holding a stale addr learns of the redirect from one
        # integer compare.
        self._sessions: Dict[int, Dict[str, Any]] = {}
        self._next_session = 1
        self._closed = threading.Event()
        # eager-create the full router key family: a router that never
        # loses a backend still reports zeros
        # (check_telemetry_schema.py --require-router)
        for name in (
            "router/sessions_attached_total",
            "router/sessions_detached_total",
            "router/sessions_rehomed_total",
            "router/carry_resets_total",
            "router/spares_promoted_total",
            "router/backend_deaths_total",
            "router/probe_reconnects_total",
            "router/route_requests_total",
            "router/route_errors_total",
        ):
            self._tel.counter(name)
        for name in (
            "router/backends_live",
            "router/backends_dead",
            "router/spares_available",
            "router/sessions_active",
        ):
            self._tel.gauge(name)
        for b in self._backends:
            self._tel.gauge(f"router/backend/{b.index}/sessions")
        self._listener = socket.create_server((host, port))
        self.address = self._listener.getsockname()
        self._probe_threads = [
            threading.Thread(
                target=self._probe_loop, args=(b,),
                name=f"router-probe-{b.index}", daemon=True,
            )
            for b in self._backends
        ]
        for t in self._probe_threads:
            t.start()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="router-accept", daemon=True
        )
        self._accept_thread.start()

    # -- liveness probes (one thread per backend) ---------------------------

    def _probe_connect(self, b: _Backend) -> Optional[socket.socket]:
        try:
            sock = socket.create_connection(b.addr, timeout=self._probe_s)
        except OSError:
            return None
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(self._probe_s)
        try:
            # drain the attach frame the backend sends every joiner (the
            # probe holds the slot for the router's lifetime)
            frame = _recv_frame(sock)
        except (OSError, FrameCorrupt, FramingLost):
            frame = None
        if frame is None:
            try:
                sock.close()
            except OSError:
                pass
            return None
        return sock

    def _probe_loop(self, b: _Backend) -> None:
        """Own b's probe socket; flip b.live and trigger re-homing. A lost
        connection gets ``router_dead_after_s`` of reconnect attempts
        before the death is declared; a dead backend that answers again
        rejoins the pool (empty — its sessions already moved on)."""
        while not self._closed.is_set():
            sock = self._probe_connect(b)
            if sock is None:
                self._tel.counter("router/probe_reconnects_total").inc()
                if b.live and (
                    time.monotonic() - b.last_ok >= self._dead_after_s
                ):
                    self._declare_dead(b)
                elif not b.live:
                    # never (or not currently) attached: keep last_ok
                    # fresh-from-zero semantics — first success arms it
                    pass
                if self._closed.wait(min(0.2, self._probe_s)):
                    return
                continue
            b.probe_sock = sock
            b.last_ok = time.monotonic()
            self._set_live(b, True)
            try:
                while not self._closed.is_set():
                    _send_frame(sock, _KIND_HEARTBEAT, b"")
                    try:
                        frame = _recv_frame(sock)
                    except socket.timeout:
                        frame = True  # no reply traffic is the steady state
                    except (FrameCorrupt, FramingLost):
                        frame = True  # probe lane carries no payloads we parse
                    if frame is None:
                        break  # EOF: the backend is gone
                    b.last_ok = time.monotonic()
            except OSError:
                pass  # send failed: the backend is gone
            finally:
                b.probe_sock = None
                try:
                    sock.close()
                except OSError:
                    pass
            # connection lost: grace loop — reconnect attempts until the
            # dead window elapses, then declare
            lost_at = time.monotonic()
            while (
                not self._closed.is_set()
                and time.monotonic() - lost_at < self._dead_after_s
            ):
                sock = self._probe_connect(b)
                if sock is not None:
                    b.probe_sock = sock
                    b.last_ok = time.monotonic()
                    self._tel.counter("router/probe_reconnects_total").inc()
                    break
                self._closed.wait(min(0.2, self._probe_s))
            else:
                if not self._closed.is_set():
                    self._declare_dead(b)
                continue
            # reconnected inside the grace window: resume the heartbeat
            # loop on the fresh socket next turn (close this one first)
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass

    def _set_live(self, b: _Backend, live: bool) -> None:
        with self._lock:
            b.live = live
            self._publish_gauges_locked()

    def _declare_dead(self, b: _Backend) -> None:
        """The failover moment: promote a spare if one is live, re-home
        every session of the dead backend, bump epochs. One lock hold —
        route requests racing this see either the old or the new world,
        never a half-moved session."""
        with self._lock:
            if not b.live and not b.sessions:
                return  # already processed (or never attached)
            b.live = False
            self._tel.counter("router/backend_deaths_total").inc()
            # promotion is a routing change: the spare already subscribes
            # to the weights fanout, so it enters the pool as-is
            for s in self._backends:
                if s.spare and s.live:
                    s.spare = False
                    self._tel.counter("router/spares_promoted_total").inc()
                    break
            moved = self._rehome_locked(b)
            self._publish_gauges_locked()
        if moved:
            self._tel.counter("router/sessions_rehomed_total").inc(moved)

    def _rehome_locked(self, dead: _Backend) -> int:
        """Reassign every session homed on ``dead`` to the least-loaded
        live non-spare backend. Sessions with no live home stay parked on
        the dead backend (epoch unchanged) — the next death/recovery or
        ``where`` retry picks them up; the client's deadline budget bounds
        how long it waits for that."""
        moved = 0
        for sid in sorted(dead.sessions):
            target = self._pick_backend_locked()
            if target is None or target is dead:
                break
            dead.sessions.discard(sid)
            target.sessions.add(sid)
            sess = self._sessions[sid]
            sess["backend"] = target.index
            sess["epoch"] += 1
            sess["rehomed"] = True
            moved += 1
        return moved

    def _pick_backend_locked(self) -> Optional[_Backend]:
        pool = [b for b in self._backends if b.live and not b.spare]
        if not pool:
            return None
        return min(pool, key=lambda b: (len(b.sessions), b.index))

    def _publish_gauges_locked(self) -> None:
        live = sum(1 for b in self._backends if b.live and not b.spare)
        dead = sum(1 for b in self._backends if not b.live and not b.spare)
        spares = sum(1 for b in self._backends if b.spare and b.live)
        self._tel.gauge("router/backends_live").set(float(live))
        self._tel.gauge("router/backends_dead").set(float(dead))
        self._tel.gauge("router/spares_available").set(float(spares))
        self._tel.gauge("router/sessions_active").set(
            float(len(self._sessions))
        )
        for b in self._backends:
            self._tel.gauge(f"router/backend/{b.index}/sessions").set(
                float(len(b.sessions))
            )

    # -- route control plane -------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return  # listener closed
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(
                target=self._conn_loop, args=(sock,),
                name="router-conn", daemon=True,
            ).start()

    def _conn_loop(self, sock: socket.socket) -> None:
        try:
            while not self._closed.is_set():
                try:
                    frame = _recv_frame(sock)
                except (FrameCorrupt, FramingLost):
                    self._tel.counter("router/route_errors_total").inc()
                    return  # control lane: no resync, the client redials
                if frame is None:
                    return  # clean disconnect
                kind, payload = frame
                if kind != KIND_ROUTE_REQUEST:
                    continue
                self._tel.counter("router/route_requests_total").inc()
                try:
                    request = json.loads(bytes(payload).decode())
                    reply = self._handle(request)
                except Exception:  # noqa: BLE001 - control plane stays up
                    self._tel.counter("router/route_errors_total").inc()
                    reply = {"error": "malformed route request"}
                _send_frame(
                    sock, KIND_ROUTE_REPLY, json.dumps(reply).encode()
                )
        except OSError:
            pass  # disposable control connection
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _handle(self, request: Dict[str, Any]) -> Dict[str, Any]:
        op = request.get("op")
        if op == "attach":
            return self.attach()
        if op == "where":
            return self.where(int(request["session"]))
        if op == "detach":
            return self.detach(int(request["session"]))
        if op == "status":
            return self.status()
        self._tel.counter("router/route_errors_total").inc()
        return {"error": f"unknown op {op!r}"}

    def attach(self) -> Dict[str, Any]:
        with self._lock:
            target = self._pick_backend_locked()
            if target is None:
                self._tel.counter("router/route_errors_total").inc()
                return {"error": "no live backend"}
            sid = self._next_session
            self._next_session += 1
            target.sessions.add(sid)
            self._sessions[sid] = {
                "backend": target.index, "epoch": 0, "rehomed": False,
            }
            self._tel.counter("router/sessions_attached_total").inc()
            self._publish_gauges_locked()
            return {
                "session": sid,
                "addr": list(target.addr),
                "epoch": 0,
            }

    def where(self, sid: int) -> Dict[str, Any]:
        """Current home of a session. A session parked on a dead backend
        re-homes HERE if a live backend has appeared since — the lazy
        half of re-homing that covers sessions stranded while no backend
        was live."""
        with self._lock:
            sess = self._sessions.get(sid)
            if sess is None:
                return {"error": f"unknown session {sid}"}
            b = self._backends[sess["backend"]]
            if not b.live:
                target = self._pick_backend_locked()
                if target is None:
                    return {"error": "no live backend"}
                b.sessions.discard(sid)
                target.sessions.add(sid)
                sess["backend"] = target.index
                sess["epoch"] += 1
                sess["rehomed"] = True
                b = target
                self._tel.counter("router/sessions_rehomed_total").inc()
                self._publish_gauges_locked()
            return {
                "session": sid,
                "addr": list(b.addr),
                "epoch": sess["epoch"],
                "rehomed": bool(sess["rehomed"]),
            }

    def detach(self, sid: int) -> Dict[str, Any]:
        with self._lock:
            sess = self._sessions.pop(sid, None)
            if sess is not None:
                self._backends[sess["backend"]].sessions.discard(sid)
                self._tel.counter("router/sessions_detached_total").inc()
                self._publish_gauges_locked()
        return {"session": sid, "detached": sess is not None}

    def status(self) -> Dict[str, Any]:
        from dotaclient_tpu.utils.fleet import peer_label

        with self._lock:
            return {
                "backends": [
                    {
                        "index": b.index,
                        "addr": list(b.addr),
                        # the PR 13 fleet row this backend publishes under
                        # (serve peers key on their listen port): the
                        # operator joins router liveness against
                        # fleet/<peer>/serve/p99_latency_ms by this name
                        "fleet_peer": peer_label(
                            "serve", b.addr[1] & 0xFFFF
                        ),
                        "live": b.live,
                        "spare": b.spare,
                        "sessions": len(b.sessions),
                    }
                    for b in self._backends
                ],
                "sessions": len(self._sessions),
            }

    def note_carry_reset(self) -> None:
        """Client-reported default-mode re-home (the carry went to zeros;
        the reset_recurrent discipline). Counted here so the honest state
        contract is observable fleet-wide, not per-client."""
        self._tel.counter("router/carry_resets_total").inc()

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        self._closed.set()
        try:
            self._listener.close()
        except OSError:
            pass
        for b in self._backends:
            sock = b.probe_sock
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
        for t in self._probe_threads:
            t.join(timeout=2)


def main(argv=None) -> int:
    """Standalone router:

        python -m dotaclient_tpu.serve.router \\
            --listen 127.0.0.1:7799 \\
            --backends 127.0.0.1:7788,127.0.0.1:7789 \\
            --spares 127.0.0.1:7790 --metrics-jsonl router.jsonl

    Runs the session router plus the PR 13 alert engine over its own
    registry; ``ALERT`` events (``serve_peer_dead``,
    ``sessions_rehomed_burst``) and periodic ``router/*`` snapshots ride
    the metrics JSONL with the learner's flush-per-emit durability.
    """
    import argparse
    import dataclasses

    p = argparse.ArgumentParser(description=main.__doc__)
    p.add_argument("--listen", type=str, default="127.0.0.1:0",
                   help="host:port of the route control lane (0 = "
                   "ephemeral, printed at startup)")
    p.add_argument("--backends", type=str, required=True,
                   help="comma-separated host:port of active backends")
    p.add_argument("--spares", type=str, default=None,
                   help="comma-separated host:port of hot spares "
                   "(subscribed to the same weights fanout; promotion is "
                   "a routing change)")
    p.add_argument("--serve", type=str, default=None, metavar="K=V,...",
                   help="ServeConfig overrides (router_probe_s, "
                   "router_dead_after_s, ...)")
    p.add_argument("--metrics-jsonl", type=str, default=None, metavar="PATH",
                   help="append router telemetry snapshots + ALERT events "
                   "to PATH — validate with check_telemetry_schema.py "
                   "--path PATH --require-router")
    p.add_argument("--interval", type=float, default=1.0,
                   help="snapshot/alert evaluation cadence in seconds")
    p.add_argument("--duration", type=float, default=0.0,
                   help="route for this many seconds then exit (0 = forever)")
    args = p.parse_args(argv)

    from dotaclient_tpu.config import ServeConfig, default_config
    from dotaclient_tpu.utils.alerts import AlertEngine
    from dotaclient_tpu.utils.overrides import parse_dataclass_overrides

    config = default_config()
    if args.serve:
        try:
            over = parse_dataclass_overrides(ServeConfig, args.serve, "--serve")
        except ValueError as e:
            p.error(str(e))
        config = dataclasses.replace(
            config, serve=dataclasses.replace(config.serve, **over)
        )

    def parse_addrs(spec: Optional[str]) -> List[Tuple[str, int]]:
        if not spec:
            return []
        out = []
        for part in spec.split(","):
            host, port = part.strip().rsplit(":", 1)
            out.append((host, int(port)))
        return out

    host, port = args.listen.rsplit(":", 1)
    tel = telemetry.get_registry()
    router = SessionRouter(
        config,
        parse_addrs(args.backends),
        spares=parse_addrs(args.spares),
        host=host,
        port=int(port),
        registry=tel,
    )
    sink = (
        telemetry.JsonlSink(args.metrics_jsonl)
        if args.metrics_jsonl
        else None
    )
    engine = AlertEngine(
        registry=tel,
        emit=(sink.emit_event if sink is not None else None),
    )
    print(
        "ROUTER_LISTENING "
        + json.dumps({
            "host": router.address[0], "port": int(router.address[1]),
        }),
        flush=True,
    )
    ticks = 0
    t_end = time.time() + args.duration if args.duration else None
    try:
        while t_end is None or time.time() < t_end:
            time.sleep(args.interval)
            ticks += 1
            counters, gauges = tel.counters_and_gauges()
            snapshot = {**counters, **gauges}
            engine.evaluate(snapshot)
            if sink is not None:
                sink.emit(ticks, snapshot)
    except KeyboardInterrupt:
        pass
    finally:
        router.close()
        if sink is not None:
            counters, gauges = tel.counters_and_gauges()
            sink.emit(ticks + 1, {**counters, **gauges})
            sink.close()
        counters, gauges = tel.counters_and_gauges()
        print(json.dumps({
            "router_sessions_attached": counters.get(
                "router/sessions_attached_total", 0.0
            ),
            "router_sessions_rehomed": counters.get(
                "router/sessions_rehomed_total", 0.0
            ),
            "router_backend_deaths": counters.get(
                "router/backend_deaths_total", 0.0
            ),
        }))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
