"""Serve-plane client: one connection, one carry slot, one game.

The protocol is intentionally dumb — a game wants exactly one action per
observation, so the client is synchronous: ``step(obs)`` ships one request
frame and blocks until the echoing reply arrives. Recurrent state never
crosses the wire: the server keeps this game's carry in the slot it
assigned at attach (the first frame on the connection names it), and
``reset=True`` on the first step of each episode zeroes that slot before
the core — the same episode-boundary discipline the actors apply.

Request payloads ride the rollout codec, so
``serve.request_wire_dtype="bfloat16"`` narrows observation leaves through
the ISSUE 7 cast-plan machinery (``__wire_cast__`` marker, config-bounded
exact int casts); CRC trailers and the quarantine discipline come with the
shared framing. Corrupt inbound replies raise — the client is disposable
(its slot reclaims server-side) and whoever owns the game reconnects.
"""

from __future__ import annotations

import socket
import time
from typing import Any, Dict, Tuple

import numpy as np

from dotaclient_tpu.config import RunConfig
from dotaclient_tpu.models.distributions import HEADS
from dotaclient_tpu.serve.server import (
    ATTACH_REQUEST_ID,
    KIND_SERVE_REPLY,
    KIND_SERVE_REQUEST,
)
from dotaclient_tpu.transport.socket_transport import (
    _recv_frame,
    _send_frame,
)
from dotaclient_tpu.transport.serialize import (
    decode_rollout_bytes,
    encode_rollout_bytes,
    rollout_int_bounds,
)
from dotaclient_tpu.utils import tracing


def serve_request_wire_kwargs(config: RunConfig) -> Dict[str, Any]:
    """Encode kwargs for the request wire — ``{}`` for full width, the
    rollout cast plan (bf16 floats, exact bounded ints) otherwise. The one
    derivation every request encoder shares (client, loadgen, tests)."""
    if config.serve.request_wire_dtype == "float32":
        return {}
    return dict(
        wire_dtype=config.serve.request_wire_dtype,
        int_bounds=rollout_int_bounds(config),
    )


class ServeClient:
    """Blocking request/reply client for one game."""

    def __init__(
        self,
        host: str,
        port: int,
        config: RunConfig,
        timeout_s: float = 30.0,
    ) -> None:
        self._sock = socket.create_connection((host, port), timeout=timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock.settimeout(timeout_s)
        self._wire_kwargs = serve_request_wire_kwargs(config)
        self._next_id = 1   # 0 is the attach frame's id
        self.last_version = 0
        self.last_logp = 0.0
        self.last_latency_s = 0.0
        self._last_packed = np.zeros((len(HEADS),), np.int32)
        # attach: the first frame names this connection's carry slot and
        # the server's current weights version. A shed joiner (every slot
        # taken → the server closes without an attach frame) must not
        # leak the fd — attach-retry loops would bleed sockets.
        try:
            meta = self._recv_reply(ATTACH_REQUEST_ID)[0]
        except BaseException:
            self.close()
            raise
        self.slot = meta["env_id"]
        self.last_version = meta["model_version"]

    def _recv_reply(self, request_id: int) -> Tuple[Dict[str, Any], Any]:
        while True:
            frame = _recv_frame(self._sock)
            if frame is None:
                raise ConnectionError("serve server closed the connection")
            kind, payload = frame
            if kind != KIND_SERVE_REPLY:
                continue   # future control kinds: skip, stay in sync
            meta, arrays = decode_rollout_bytes(payload, upcast=True)
            if meta["rollout_id"] == request_id:
                return meta, arrays
            # an out-of-order echo (attach duplicates): keep draining

    def step(
        self,
        obs: Dict[str, np.ndarray],
        reset: bool = False,
    ) -> Dict[str, int]:
        """One action for one observation (unbatched leaves). Returns the
        per-head action indices; the joint log-prob, serving weights
        version, raw packed row, and measured round-trip latency land on
        ``last_logp`` / ``last_version`` / ``last_packed`` /
        ``last_latency_s``."""
        request_id = self._next_id
        self._next_id += 1
        trace_blob = None
        tracer = tracing.get()
        if tracer is not None and tracer.should_sample():
            # request-side trace record (ISSUE 12): the server stamps
            # recv/reply and echoes it; `done` below closes the RTT
            rec = tracing.new_record(
                tracer.next_tid(self.slot), self.slot, self.last_version
            )
            tracing.append_hop(rec, "encode")
            trace_blob = tracing.record_to_blob(rec, pad=False)
        payload = encode_rollout_bytes(
            {
                "obs": obs,
                "reset": np.asarray(1.0 if reset else 0.0, np.float32),
            },
            model_version=self.last_version,
            env_id=self.slot,
            rollout_id=request_id,
            length=1,
            total_reward=0.0,
            **self._wire_kwargs,
            trace=trace_blob,
        )
        t0 = time.perf_counter()
        _send_frame(self._sock, KIND_SERVE_REQUEST, payload)
        meta, arrays = self._recv_reply(request_id)
        self.last_latency_s = time.perf_counter() - t0
        if tracer is not None and "trace_blob" in meta:
            rec = tracing.parse_blob(meta["trace_blob"])
            if rec is not None:
                tracing.append_hop(rec, "done")
                tracer.emit_chunk(rec)
        self.last_version = meta["model_version"]
        self._last_packed = np.asarray(arrays["actions"]).astype(np.int32)
        self.last_logp = float(np.asarray(arrays["logp"]).reshape(-1)[0])
        return {h: int(self._last_packed[j]) for j, h in enumerate(HEADS)}

    @property
    def last_packed(self) -> np.ndarray:
        """The raw packed ``[5]`` int32 action row of the last reply (the
        parity digest compares these bitwise)."""
        return self._last_packed

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
