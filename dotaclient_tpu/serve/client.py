"""Serve-plane client: one game, one session, bounded failure (ISSUE 19).

The protocol is intentionally dumb — a game wants exactly one action per
observation, so the client is synchronous: ``step(obs)`` ships one request
frame and blocks until the echoing reply arrives. Recurrent state stays
server-resident by default: the backend keeps this game's carry in the slot
it assigned at attach, and ``reset=True`` on the first step of each episode
zeroes that slot before the core — the same episode-boundary discipline the
actors apply.

Failure is BOUNDED, never a hang: every ``step()`` spends from a per-request
deadline budget (``serve.request_deadline_s``) across bounded resend
attempts (``serve.request_retries``), and the connect path rides
``connect_with_backoff`` — the PR 4/6 actor discipline, so a SIGTERM'd
client abandons a reconnect schedule within one backoff segment
(``should_abort``). A request that cannot be served inside its budget
raises the typed :class:`ServeDeadlineError`; whoever owns the game decides
what a missed action means.

Fleet mode (``router=True``): ``(host, port)`` names a
:class:`~dotaclient_tpu.serve.router.SessionRouter` instead of a backend.
The client attaches through the router (session-affine assignment), talks
to its backend directly, and on ANY backend failure re-asks the router
``where`` its session lives now — following the redirect to a re-homed
backend or a promoted hot spare. The re-home state contract is honest:
default mode resumes on a fresh zeroed carry (counted via
``carry_resets``/the router's ``router/carry_resets_total``); with
``serve.carry_shadow`` on, the client stashes the carry row each reply
ships back and resends it on the first post-re-home request, so the
session resumes bit-exact (the chaos/bench parity digest pins it).

Request payloads ride the rollout codec, so
``serve.request_wire_dtype="bfloat16"`` narrows observation (and shadow
carry) leaves through the ISSUE 7 cast-plan machinery; CRC trailers and
the quarantine discipline come with the shared framing.
"""

from __future__ import annotations

import socket
import time
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from dotaclient_tpu.config import RunConfig
from dotaclient_tpu.models.distributions import HEADS
from dotaclient_tpu.serve.router import route_call
from dotaclient_tpu.serve.server import (
    ATTACH_REQUEST_ID,
    KIND_SERVE_REPLY,
    KIND_SERVE_REQUEST,
)
from dotaclient_tpu.transport.socket_transport import (
    _recv_frame,
    _send_frame,
)
from dotaclient_tpu.transport.serialize import (
    decode_rollout_bytes,
    encode_rollout_bytes,
    rollout_int_bounds,
)
from dotaclient_tpu.utils import tracing


class ServeDeadlineError(ConnectionError):
    """A request's deadline budget elapsed (retries, reconnects, and
    router redirects included). The typed bounded-failure every caller can
    rely on: a ``step()`` either returns an action or raises this within
    ``serve.request_deadline_s`` — never a hang."""


def serve_request_wire_kwargs(config: RunConfig) -> Dict[str, Any]:
    """Encode kwargs for the request wire — ``{}`` for full width, the
    rollout cast plan (bf16 floats, exact bounded ints) otherwise. The one
    derivation every request encoder shares (client, loadgen, tests)."""
    if config.serve.request_wire_dtype == "float32":
        return {}
    return dict(
        wire_dtype=config.serve.request_wire_dtype,
        int_bounds=rollout_int_bounds(config),
    )


class ServeClient:
    """Blocking request/reply client for one game (direct or fleet mode)."""

    def __init__(
        self,
        host: str,
        port: int,
        config: RunConfig,
        timeout_s: float = 30.0,
        router: bool = False,
        max_reconnects: int = 6,
        should_abort: Optional[Callable[[], bool]] = None,
    ) -> None:
        scfg = config.serve
        self._timeout_s = timeout_s
        self._deadline_s = max(0.05, scfg.request_deadline_s)
        self._retries = max(0, int(scfg.request_retries))
        self._shadow = bool(scfg.carry_shadow)
        self._max_reconnects = max(1, int(max_reconnects))
        self._should_abort = should_abort
        self._wire_kwargs = serve_request_wire_kwargs(config)
        self._next_id = 1   # 0 is the attach frame's id
        self.last_version = 0
        self.last_logp = 0.0
        self.last_latency_s = 0.0
        self.last_dispatch_idx = -1
        self._last_packed = np.zeros((len(HEADS),), np.int32)
        # fleet-mode state
        self._router = bool(router)
        self._router_addr: Optional[Tuple[str, int]] = None
        self._route_sock: Optional[socket.socket] = None
        self.session: Optional[int] = None
        self._epoch = -1
        # failover bookkeeping (the honest state contract, observable)
        self.rehomed_count = 0
        self.last_rehomed = False
        self.carry_resets = 0
        self.retries_total = 0
        self._carry_stash: Optional[Dict[str, np.ndarray]] = None
        self._pending_restore = False
        self._sock: Optional[socket.socket] = None
        self.backend_addr: Tuple[str, int] = (host, port)

        deadline = time.monotonic() + self._deadline_s
        if self._router:
            self._router_addr = (host, port)
            info = self._route({"op": "attach"}, deadline)
            if "error" in info:
                raise ConnectionError(f"router attach failed: {info['error']}")
            self.session = int(info["session"])
            self._epoch = int(info["epoch"])
            self.backend_addr = (info["addr"][0], int(info["addr"][1]))
        try:
            self._connect_backend(deadline)
        except BaseException:
            self.close()
            raise

    # -- connection plumbing -------------------------------------------------

    def _abort_by(self, deadline: float) -> Callable[[], bool]:
        """The backoff/retry stop predicate: the caller's SIGTERM hook OR
        the request deadline — whichever trips first ends the schedule
        within one segment."""
        def abort() -> bool:
            if self._should_abort is not None and self._should_abort():
                return True
            return time.monotonic() >= deadline
        return abort

    def _connect_backend(self, deadline: float) -> None:
        """(Re)connect to ``backend_addr`` and read the attach frame, with
        the actor contract's bounded backoff. A RE-connect lands on a
        fresh slot — state discontinuity — so it arms the restore path
        (shadow resend or an explicit counted reset)."""
        from dotaclient_tpu.actor.__main__ import connect_with_backoff

        reconnecting = self._sock is not None
        self._close_backend()

        def factory() -> socket.socket:
            sock = socket.create_connection(
                self.backend_addr, timeout=self._timeout_s
            )
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.settimeout(self._timeout_s)
            try:
                # attach: the first frame names this connection's carry
                # slot and the server's current weights version. A shed
                # joiner (every slot taken → the server closes without an
                # attach frame) must not leak the fd.
                meta = self._recv_reply_on(
                    sock, ATTACH_REQUEST_ID, deadline
                )[0]
            except BaseException:
                try:
                    sock.close()
                except OSError:
                    pass
                raise
            self.slot = meta["env_id"]
            self.last_version = meta["model_version"]
            return sock

        self._sock = connect_with_backoff(
            factory,
            max_attempts=self._max_reconnects,
            base_delay=0.1,
            max_delay=1.0,
            should_abort=self._abort_by(deadline),
        )
        if reconnecting:
            self._pending_restore = True

    def _close_backend(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _close_route(self) -> None:
        if self._route_sock is not None:
            try:
                self._route_sock.close()
            except OSError:
                pass
            self._route_sock = None

    def _route(self, request: Dict[str, Any], deadline: float) -> Dict[str, Any]:
        """One router round-trip, redialing the control connection once if
        it went stale (bounded by the deadline either way)."""
        from dotaclient_tpu.actor.__main__ import connect_with_backoff

        assert self._router_addr is not None
        for attempt in (0, 1):
            if self._route_sock is None:
                def factory() -> socket.socket:
                    s = socket.create_connection(
                        self._router_addr, timeout=self._timeout_s
                    )
                    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                    return s

                self._route_sock = connect_with_backoff(
                    factory,
                    max_attempts=self._max_reconnects,
                    base_delay=0.1,
                    max_delay=1.0,
                    should_abort=self._abort_by(deadline),
                )
            try:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ServeDeadlineError(
                        "route round-trip would exceed the request deadline"
                    )
                return route_call(
                    self._route_sock, request,
                    timeout=min(self._timeout_s, remaining),
                )
            except ServeDeadlineError:
                raise
            except (OSError, ConnectionError, ValueError):
                self._close_route()
                if attempt:
                    raise
        raise ConnectionError("unreachable")  # pragma: no cover

    def _recover(self, deadline: float) -> None:
        """After a failed attempt: find the session's current home (fleet
        mode re-asks the router and follows the redirect — a re-homed
        session lands on a live backend or a promoted spare) and
        reconnect. Loops until connected or the deadline budget is
        spent."""
        self._close_backend()
        # recovery IS a state discontinuity (the old connection's slot is
        # gone) — arm the restore path here, not on the reconnect check:
        # _close_backend above already nulled the socket it keys on
        self._pending_restore = True
        while True:
            if self._should_abort is not None and self._should_abort():
                raise ConnectionError(
                    "serve client stopping: stop requested"
                )
            if time.monotonic() >= deadline:
                raise ServeDeadlineError(
                    "recovery exceeded the request deadline budget"
                )
            try:
                if self._router:
                    info = self._route(
                        {"op": "where", "session": self.session}, deadline
                    )
                    if "error" in info:
                        # no live backend YET: the router may be mid
                        # spare-promotion — poll inside the budget
                        time.sleep(0.05)
                        continue
                    addr = (info["addr"][0], int(info["addr"][1]))
                    epoch = int(info["epoch"])
                    if epoch != self._epoch:
                        # the redirect: the session re-homed
                        self._epoch = epoch
                        self.backend_addr = addr
                        self.rehomed_count += 1
                        self.last_rehomed = True
                self._connect_backend(deadline)
                return
            except ServeDeadlineError:
                raise
            except (OSError, ConnectionError):
                # backend refused / mid-restart: go around (deadline- and
                # abort-bounded above)
                time.sleep(0.05)

    # -- request/reply -------------------------------------------------------

    def _recv_reply_on(
        self, sock: socket.socket, request_id: int, deadline: float
    ) -> Tuple[Dict[str, Any], Any]:
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                # surfaced as a retryable timeout; step() converts to the
                # typed deadline error once the budget is truly spent
                raise socket.timeout("request deadline elapsed mid-wait")
            sock.settimeout(min(self._timeout_s, remaining))
            frame = _recv_frame(sock)
            if frame is None:
                raise ConnectionError("serve server closed the connection")
            kind, payload = frame
            if kind != KIND_SERVE_REPLY:
                continue   # future control kinds: skip, stay in sync
            meta, arrays = decode_rollout_bytes(payload, upcast=True)
            if meta["rollout_id"] == request_id:
                return meta, arrays
            # an out-of-order echo (attach duplicates): keep draining

    def _step_once(
        self,
        obs: Dict[str, np.ndarray],
        reset: bool,
        deadline: float,
    ) -> Dict[str, int]:
        request_id = self._next_id
        self._next_id += 1
        send_reset = reset
        send_carry = None
        if self._pending_restore:
            if self._shadow and self._carry_stash is not None:
                # bit-exact resume: the stashed row rides this request
                # and the backend installs it before dispatching
                send_carry = self._carry_stash
            else:
                # honest default: the fresh slot's carry is zeros — make
                # the reset explicit and COUNT the discontinuity
                send_reset = True
        trace_blob = None
        tracer = tracing.get()
        if tracer is not None and tracer.should_sample():
            # request-side trace record (ISSUE 12): the server stamps
            # recv/reply and echoes it; `done` below closes the RTT
            rec = tracing.new_record(
                tracer.next_tid(self.slot), self.slot, self.last_version
            )
            tracing.append_hop(rec, "encode")
            trace_blob = tracing.record_to_blob(rec, pad=False)
        arrays: Dict[str, Any] = {
            "obs": obs,
            "reset": np.asarray(1.0 if send_reset else 0.0, np.float32),
        }
        if send_carry is not None:
            arrays["carry"] = send_carry
        payload = encode_rollout_bytes(
            arrays,
            model_version=self.last_version,
            env_id=self.slot,
            rollout_id=request_id,
            length=1,
            total_reward=0.0,
            **self._wire_kwargs,
            trace=trace_blob,
        )
        t0 = time.perf_counter()
        _send_frame(self._sock, KIND_SERVE_REQUEST, payload)
        meta, reply = self._recv_reply_on(self._sock, request_id, deadline)
        self.last_latency_s = time.perf_counter() - t0
        if tracer is not None and "trace_blob" in meta:
            rec = tracing.parse_blob(meta["trace_blob"])
            if rec is not None:
                tracing.append_hop(rec, "done")
                tracer.emit_chunk(rec)
        if self._pending_restore:
            self._pending_restore = False
            if send_carry is None:
                self.carry_resets += 1
        self.last_version = meta["model_version"]
        self._last_packed = np.asarray(reply["actions"]).astype(np.int32)
        self.last_logp = float(np.asarray(reply["logp"]).reshape(-1)[0])
        if "dispatch_idx" in reply:
            self.last_dispatch_idx = int(
                np.asarray(reply["dispatch_idx"]).reshape(-1)[0]
            )
        if self._shadow:
            stash = reply.get("carry")
            if stash is not None:
                self._carry_stash = stash
        return {h: int(self._last_packed[j]) for j, h in enumerate(HEADS)}

    def step(
        self,
        obs: Dict[str, np.ndarray],
        reset: bool = False,
    ) -> Dict[str, int]:
        """One action for one observation (unbatched leaves). Returns the
        per-head action indices; the joint log-prob, serving weights
        version, raw packed row, and measured round-trip latency land on
        ``last_logp`` / ``last_version`` / ``last_packed`` /
        ``last_latency_s``.

        Resolves within ``serve.request_deadline_s``: transient failures
        (dead backend, dropped connection, slow window) are retried up to
        ``serve.request_retries`` times — fleet mode re-asks the router
        between attempts and follows its redirect — and budget exhaustion
        raises the typed :class:`ServeDeadlineError`, never hangs."""
        deadline = time.monotonic() + self._deadline_s
        attempts = 0
        last_err: Optional[BaseException] = None
        while True:
            if self._should_abort is not None and self._should_abort():
                raise ConnectionError(
                    "serve client stopping: stop requested"
                )
            try:
                return self._step_once(obs, reset, deadline)
            except ServeDeadlineError:
                raise
            except (OSError, ConnectionError, ValueError) as e:
                # socket.timeout is an OSError; FrameCorrupt a ValueError:
                # every transport-shaped failure rides one retry path
                last_err = e
            attempts += 1
            self.retries_total += 1
            if (
                time.monotonic() >= deadline
                or attempts > self._retries
            ):
                raise ServeDeadlineError(
                    f"serve request failed after {attempts} attempt(s) "
                    f"inside the {self._deadline_s:.1f}s budget "
                    f"({type(last_err).__name__}: {last_err})"
                ) from last_err
            self._recover(deadline)

    @property
    def last_packed(self) -> np.ndarray:
        """The raw packed ``[5]`` int32 action row of the last reply (the
        parity digest compares these bitwise)."""
        return self._last_packed

    @property
    def last_carry(self) -> Optional[Dict[str, np.ndarray]]:
        """The carry-shadow stash (opaque wire dict) from the last reply —
        ``None`` unless ``serve.carry_shadow`` is on server-side."""
        return self._carry_stash

    def close(self) -> None:
        if self._router and self.session is not None:
            try:
                self._route(
                    {"op": "detach", "session": self.session},
                    time.monotonic() + 1.0,
                )
            except (OSError, ConnectionError, ValueError):
                pass   # router gone: the probe plane will reap the session
        self._close_backend()
        self._close_route()
