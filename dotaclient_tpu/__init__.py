"""dotaclient_tpu — a TPU-native self-play deep-RL framework.

A ground-up JAX/XLA/Pallas rebuild of the capability surface of
``Nostrademous/dotaclient`` (PyTorch actor-learner PPO for Dota 2):

- ``protos``    first-party wire format (worldstate / actions / rollouts)
- ``envs``      lane sim ×3: scalar (gRPC service), numpy vectorized, pure-JAX
- ``features``  worldstate -> fixed-shape arrays (scalar/vec/jnp); action codec
- ``models``    Flax policy: unit encoders, LSTM or transformer core, masked heads
- ``train``     pjit'd PPO train step (GAE, clipped surrogate) and learner loop
- ``buffer``    sharded HBM-resident trajectory ring buffer
- ``transport`` experience/weight transport (in-proc, TCP socket, AMQP)
- ``native``    C++ runtime components (fast-path rollout wire decoder)
- ``actor``     actors: on-device rollout scan, vectorized pool, scalar pool,
                standalone process entrypoint (``python -m dotaclient_tpu.actor``)
- ``league``    self-play opponent pools and win-rate evaluation
- ``parallel``  mesh construction, TP sharding rules, ring/Ulysses sequence
                parallelism
- ``ops``       custom-kernel layer (Pallas candidates; see BASELINE.md for
                the measured keep-or-kill decisions)
- ``utils``     checkpointing (orbax, full-pipeline state), metrics
"""

__version__ = "0.1.0"
