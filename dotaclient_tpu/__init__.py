"""dotaclient_tpu — a TPU-native self-play deep-RL framework.

A ground-up JAX/XLA/Pallas rebuild of the capability surface of
``Nostrademous/dotaclient`` (PyTorch actor-learner PPO for Dota 2):

- ``protos``    first-party wire format (worldstate / actions / rollouts)
- ``envs``      lane simulator + gRPC environment service and client
- ``features``  worldstate -> fixed-shape arrays; action codec
- ``models``    Flax policy: unit encoders, LSTM(128) core, masked heads
- ``ops``       GAE, masked distributions, Pallas kernels
- ``train``     pjit'd PPO train step and learner loop
- ``buffer``    sharded HBM-resident trajectory ring buffer
- ``transport`` experience/weight transport (in-proc queue, AMQP interface)
- ``actor``     batched-on-device actor runtime multiplexing many envs
- ``league``    self-play opponent pools and evaluation
- ``parallel``  mesh construction, sharding rules, sequence parallelism
- ``utils``     checkpointing, metrics, profiling
"""

__version__ = "0.1.0"
