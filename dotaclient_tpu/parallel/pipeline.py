"""Pipeline parallelism: GPipe-style microbatched stages over a mesh axis.

The reference has no PP — its core is a single LSTM(128) (SURVEY.md §2.3
row 4) — but the rebuild ships it as a first-class library primitive for
deep cores: the layer stack is split into S stages, one per device along the
``stage`` mesh axis; microbatches stream through the pipe with activations
hopped stage→stage by ``ppermute`` (ICI neighbor traffic, SURVEY.md §5.8 —
the collective is the only communication, emitted inside ``shard_map``).

Schedule: plain GPipe fill-and-drain — M microbatches take M + S - 1 ticks,
bubble fraction (S-1)/(M+S-1). Every device computes every tick (SPMD); the
masking is in which activations are kept, not in control flow.

Correctness contract (pinned by ``tests/test_parallel.py``): identical
output to applying the S stages sequentially on one device.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from dotaclient_tpu.parallel._compat import pcast_varying, shard_map

StageFn = Callable[[Any, jnp.ndarray], jnp.ndarray]


def make_pipeline(
    stage_fn: StageFn,
    mesh: Mesh,
    axis: str,
    n_microbatches: int,
):
    """Build a jitted pipelined apply.

    ``stage_fn(stage_params, x) -> y`` must preserve ``x``'s shape (the
    classic homogeneous-stage regime). Returned callable:

        out = pipe(stacked_params, x)

    * ``stacked_params``: pytree whose leaves have a leading stage axis
      [S, ...] (stage s's params at index s);
    * ``x``: [B, ...] with B divisible by ``n_microbatches``;
    * ``out``: [B, ...] — stage S-1's outputs, replicated.
    """
    S = mesh.shape[axis]
    M = n_microbatches

    def _shard_body(params_local, x):            # params leaves [1, ...]; x [B,...]
        s = jax.lax.axis_index(axis)
        params = jax.tree.map(lambda p: p[0], params_local)
        xm = x.reshape((M, x.shape[0] // M) + x.shape[1:])   # [M, mb, ...]
        mb_shape = xm.shape[1:]

        perm_fwd = [(i, (i + 1) % S) for i in range(S)]
        # zero-constants are axis-invariant; the loop makes them varying —
        # pcast the initializers so the fori_loop carry types match
        # (identity on jax versions without varying types — _compat shim)
        out0 = pcast_varying(jnp.zeros_like(xm), (axis,))
        recv0 = pcast_varying(jnp.zeros(mb_shape, x.dtype), (axis,))

        def tick(t, carry):
            recv, out = carry
            # stage 0 ingests microbatch t (when one remains); others take
            # the activation handed over by the previous stage
            fresh = xm[jnp.minimum(t, M - 1)]
            inp = jnp.where(s == 0, fresh, recv)
            act = stage_fn(params, inp)
            # my microbatch index this tick; valid while 0 <= t - s < M
            idx = t - s
            valid = (idx >= 0) & (idx < M)
            # last stage banks finished microbatches
            take = valid & (s == S - 1)
            out = jnp.where(
                take,
                out.at[jnp.clip(idx, 0, M - 1)].set(act),
                out,
            )
            # hand activations to the next stage (ring; stage S-1 -> 0 hop
            # is discarded by stage 0 reading fresh input)
            act = jnp.where(valid, act, jnp.zeros_like(act))
            recv = jax.lax.ppermute(act, axis, perm_fwd)
            return recv, out

        _, out = jax.lax.fori_loop(0, M + S - 1, tick, (recv0, out0))
        # outputs exist only on the last stage: replicate via psum of
        # one-hot contributions (correctness-first; a production variant
        # would keep them stage-sharded for the next pipelined consumer)
        out = jax.lax.psum(jnp.where(s == S - 1, out, jnp.zeros_like(out)), axis)
        return out.reshape(x.shape)

    wrapped = shard_map(
        _shard_body,
        mesh=mesh,
        in_specs=(P(axis), P()),   # params stage-sharded, inputs replicated
        out_specs=P(),
    )
    return jax.jit(wrapped)


def stack_stage_params(params_list) -> Any:
    """[per-stage pytrees] → one pytree with a leading stage axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *params_list)
