"""Device-mesh construction and canonical shardings.

The reference's only learner-side parallelism was (at most) NCCL data-parallel
(SURVEY.md §2.3); here every distribution decision is a sharding annotation on
a `jax.sharding.Mesh` and XLA emits the collectives over ICI/DCN
(SURVEY.md §2.4, §5.8) — no hand-written communication.

Axes:
  * ``data``  — batch dimension; gradients psum over it.
  * ``model`` — tensor-parallel axis for widened cores (unused at LSTM(128)
    scale but first-class per SURVEY.md §2.3).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dotaclient_tpu.config import MeshConfig


def make_mesh(
    config: MeshConfig, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Build a (data, model) mesh over ``devices`` (default: all)."""
    devices = list(devices if devices is not None else jax.devices())
    model = max(1, config.model_parallel)
    if len(devices) % model:
        raise ValueError(
            f"{len(devices)} devices not divisible by model_parallel={model}"
        )
    data = config.data_parallel
    if data == -1:
        data = len(devices) // model
    if data * model != len(devices):
        raise ValueError(
            f"mesh {data}x{model} != {len(devices)} devices"
        )
    arr = np.asarray(devices).reshape(data, model)
    return Mesh(arr, (config.data_axis, config.model_axis))


def data_sharding(mesh: Mesh, config: MeshConfig) -> NamedSharding:
    """Batch-sharded over the data axis (leading dimension)."""
    return NamedSharding(mesh, P(config.data_axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
