"""Device-mesh construction and canonical shardings.

The reference's only learner-side parallelism was (at most) NCCL data-parallel
(SURVEY.md §2.3); here every distribution decision is a sharding annotation on
a `jax.sharding.Mesh` and XLA emits the collectives over ICI/DCN
(SURVEY.md §2.4, §5.8) — no hand-written communication.

Axes:
  * ``dcn``   — multi-slice axis: each index is one ICI-connected TPU slice;
    traffic over this axis rides the data-center network. Present only when
    ``dcn_slices > 1``.
  * ``data``  — batch dimension; gradients psum over it (and over ``dcn``
    when present — XLA lowers that to the hierarchical pattern:
    reduce-scatter/all-gather over ICI inside each slice, a slice-count
    all-reduce over DCN between them).
  * ``model`` — tensor-parallel axis for widened cores (unused at LSTM(128)
    scale but first-class per SURVEY.md §2.3). TP collectives must stay on
    ICI, so the model axis is always innermost (fastest-varying device
    order) and never crosses a slice boundary.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dotaclient_tpu.config import MeshConfig


def make_mesh(
    config: MeshConfig, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Build a (data, model) — or (dcn, data, model) — mesh over
    ``devices`` (default: all).

    Device order: JAX's ``jax.devices()`` enumerates multi-slice systems
    slice-major (all of slice 0, then slice 1, ...), so reshaping to
    ``(dcn, data, model)`` puts each slice's devices in one dcn index and
    keeps the model axis on ICI neighbors.

    An EXPLICIT layout (``data_parallel > 0``) smaller than the visible
    device set takes the first ``dcn×data×model`` devices: a 1-device mesh
    in an 8-device process is the degenerate case of the one sharded code
    path (`--mesh data_parallel=1`), not a separate fork — the parity
    probes in bench.py's multichip stage and tests/test_multichip.py
    depend on both sizes coexisting in one process.
    """
    devices = list(devices if devices is not None else jax.devices())
    model = max(1, config.model_parallel)
    dcn = max(1, config.dcn_slices)
    data = config.data_parallel
    if data > 0 and dcn * data * model < len(devices):
        devices = devices[: dcn * data * model]
    if len(devices) % (model * dcn):
        raise ValueError(
            f"{len(devices)} devices not divisible by "
            f"dcn_slices×model_parallel={dcn}x{model}"
        )
    if data == -1:
        data = len(devices) // (model * dcn)
    if dcn * data * model != len(devices):
        raise ValueError(
            f"mesh {dcn}x{data}x{model} != {len(devices)} devices"
        )
    if dcn > 1:
        arr = np.asarray(devices).reshape(dcn, data, model)
        return Mesh(
            arr, (config.dcn_axis, config.data_axis, config.model_axis)
        )
    arr = np.asarray(devices).reshape(data, model)
    return Mesh(arr, (config.data_axis, config.model_axis))


def batch_axes(mesh: Mesh, config: MeshConfig) -> Tuple[str, ...]:
    """Mesh axes the batch dimension shards over: (dcn?, data)."""
    axes = []
    if config.dcn_axis in mesh.shape:
        axes.append(config.dcn_axis)
    axes.append(config.data_axis)
    return tuple(axes)


def batch_shard_count(mesh: Mesh, config: MeshConfig) -> int:
    """How many ways the batch dimension splits over this mesh — the
    divisibility unit for batch sizes, buffer capacity, and ingest-group
    padding. Shared by the learner and the trajectory buffer so their
    checks cannot drift."""
    n = 1
    for a in batch_axes(mesh, config):
        n *= mesh.shape[a]
    return n


def data_sharding(mesh: Mesh, config: MeshConfig) -> NamedSharding:
    """Batch-sharded over the (dcn×)data axes (leading dimension)."""
    return NamedSharding(mesh, P(batch_axes(mesh, config)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def row_sharding(mesh: Mesh, config: MeshConfig, n_rows: int) -> NamedSharding:
    """Leading-axis sharding for an ``n_rows``-row array: data-sharded when
    the rows split evenly over the batch shards, replicated otherwise.

    The single divisibility rule behind the lane-sharded actor state
    (actor.device_rollout.actor_state_sharding): a game/lane axis that
    divides the (dcn×)data shard count lives partitioned, anything else —
    true scalars, the sim's batch-wide PRNG key, degenerate tiny layouts —
    stays replicated rather than failing mid-compile."""
    n = batch_shard_count(mesh, config)
    if n_rows > 0 and n_rows % n == 0:
        return data_sharding(mesh, config)
    return replicated(mesh)


def collective_probe_ms(mesh: Mesh, config: MeshConfig) -> float:
    """Measure one cross-mesh all-reduce round trip (dispatch → replicated
    result on the host), in milliseconds.

    A one-time STARTUP probe (the ``learner/psum_ms`` gauge): the train
    path itself never blocks on its gradient psum — XLA fuses it into the
    dispatched step — so the per-step collective cost is not separably
    observable without a profiler. This measures the same collective shape
    (one scalar per batch shard, summed to a replicated scalar) cold-path,
    which bounds the mesh's reduce latency floor. On a 1-device mesh it
    degenerates to dispatch+fetch latency. Deliberately blocking — call it
    at construction, never from the train loop.
    """
    import time

    import jax.numpy as jnp

    n = batch_shard_count(mesh, config)
    xs = jax.device_put(
        np.ones((n,), np.float32), data_sharding(mesh, config)
    )
    fn = jax.jit(lambda x: jnp.sum(x), out_shardings=replicated(mesh))
    fn(xs).block_until_ready()   # compile outside the measurement
    t0 = time.perf_counter()
    fn(xs).block_until_ready()   # host-sync-ok: one-time startup probe
    return (time.perf_counter() - t0) * 1e3
