"""Sequence parallelism primitives: ring attention and Ulysses all-to-all.

The reference handles long horizons by truncated rollouts with carried
recurrent state — it has no sequence parallelism (SURVEY.md §2.3 row 5,
§5.7). The rebuild ships SP as first-class library modules so a transformer
core can scale context length across the mesh (SURVEY.md §7 step 8):

* **Ring attention** — K/V shards rotate around the sequence-axis ring via
  ``ppermute`` while each device accumulates its queries' attention with an
  online (log-sum-exp) softmax; memory per device stays O(T/n), and the
  rotation rides ICI neighbor links.
* **Ulysses** — ``all_to_all`` reshards [seq-sharded, all heads] →
  [full seq, head-sharded], runs dense local attention, and reshards back;
  two collectives per layer, best when heads ≥ mesh axis size.

Both are written as *per-shard* functions to be wrapped in ``shard_map``
(the ``make_*`` helpers below do so) — no hand-written comm beyond the
collectives themselves, per the SURVEY §5.8 design rule.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from dotaclient_tpu.parallel._compat import pcast_varying, shard_map

AXIS = "data"  # default mesh axis to shard the sequence over


def reference_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, causal: bool = False
) -> jnp.ndarray:
    """Plain softmax attention (single-device oracle). [B, T, h, d] in/out."""
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(jnp.asarray(d, q.dtype))
    if causal:
        T = q.shape[1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)


def _block_attend(q, k, v, bias):
    """Unnormalized block attention with running-max bookkeeping.

    Returns (o, m, l): o = sum_j exp(s - m) v_j, m = rowmax(s), l = rowsum
    of exp(s - m); shapes o [B, Tq, h, d], m/l [B, h, Tq].
    """
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(jnp.asarray(d, jnp.float32))
    s = s + bias
    m = s.max(axis=-1)
    # fully-masked rows (causal: a block entirely in the future) have
    # m = -inf; exp(s - m) would be NaN — use a finite baseline there so
    # exp(-inf - 0) = 0 and the block contributes nothing
    m_safe = jnp.where(jnp.isneginf(m), 0.0, m)
    p = jnp.exp(s - m_safe[..., None])
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    l = p.sum(axis=-1)
    return o, m, l


def ring_attention_shard(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = AXIS,
    causal: bool = False,
) -> jnp.ndarray:
    """Per-shard ring attention body (call under shard_map).

    q/k/v: the LOCAL sequence shard [B, T_local, h, d]; the global sequence
    is the concatenation over the axis in device order. Exact same math as
    full attention (online-softmax accumulation is exact, not approximate).
    """
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    B, Tl, h, d = q.shape
    q32, k32, v32 = (x.astype(jnp.float32) for x in (q, k, v))

    q_pos = my * Tl + jnp.arange(Tl)                       # global query rows

    def bias_for(block_owner):
        if not causal:
            return jnp.zeros((1, 1, Tl, Tl), jnp.float32)
        k_pos = block_owner * Tl + jnp.arange(Tl)
        allowed = q_pos[:, None] >= k_pos[None, :]
        return jnp.where(allowed, 0.0, -jnp.inf)[None, None]

    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(i, carry):
        acc_o, acc_m, acc_l, kb, vb = carry
        owner = (my - i) % n                               # whose block we hold
        o, m, l = _block_attend(q32, kb, vb, bias_for(owner))
        new_m = jnp.maximum(acc_m, m)
        # exp(-inf - -inf) guards: where both are -inf the block contributed
        # nothing; the scales become 0 via the where
        sc_old = jnp.where(
            jnp.isneginf(acc_m), 0.0, jnp.exp(acc_m - new_m)
        )
        sc_new = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - new_m))
        acc_o = (
            acc_o * sc_old.transpose(0, 2, 1)[..., None]
            + o * sc_new.transpose(0, 2, 1)[..., None]
        )
        acc_l = acc_l * sc_old + l * sc_new
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        return acc_o, new_m, acc_l, kb, vb

    def varying(x):
        # constants are axis-invariant; the loop outputs are axis-varying —
        # mark the init carries varying so the fori_loop types match
        # (identity on jax versions without varying types — _compat shim)
        return pcast_varying(x, (axis_name,))

    init = (
        varying(jnp.zeros((B, Tl, h, d), jnp.float32)),
        varying(jnp.full((B, h, Tl), -jnp.inf, jnp.float32)),
        varying(jnp.zeros((B, h, Tl), jnp.float32)),
        k32,
        v32,
    )
    acc_o, _, acc_l, _, _ = jax.lax.fori_loop(0, n, body, init)
    denom = jnp.maximum(acc_l, 1e-30).transpose(0, 2, 1)[..., None]
    return (acc_o / denom).astype(q.dtype)


def ulysses_attention_shard(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = AXIS,
    causal: bool = False,
) -> jnp.ndarray:
    """Per-shard Ulysses attention body (call under shard_map).

    q/k/v: LOCAL sequence shard [B, T_local, h, d] with h divisible by the
    axis size. all_to_all → [B, T_full, h_local, d], dense local attention,
    all_to_all back.
    """
    n = jax.lax.psum(1, axis_name)
    # [B, Tl, h, d] → heads scatter / sequence gather → [B, T, h/n, d]
    def to_seq(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def to_heads(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    qs, ks, vs = to_seq(q), to_seq(k), to_seq(v)
    out = reference_attention(
        qs.astype(jnp.float32), ks.astype(jnp.float32), vs.astype(jnp.float32),
        causal=causal,
    )
    return to_heads(out).astype(q.dtype)


def _make_sp(fn, mesh: Mesh, axis: str, causal: bool):
    spec = P(None, axis)  # [B, T(sharded), h, d]
    wrapped = shard_map(
        functools.partial(fn, axis_name=axis, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return jax.jit(wrapped)


def make_ring_attention(mesh: Mesh, axis: str = AXIS, causal: bool = False):
    """jitted [B, T, h, d] → [B, T, h, d] ring attention over ``axis``
    (inputs/outputs globally shaped; sharding handled inside)."""
    return _make_sp(ring_attention_shard, mesh, axis, causal)


def make_ulysses_attention(mesh: Mesh, axis: str = AXIS, causal: bool = False):
    """jitted Ulysses attention over ``axis`` (h must divide by axis size)."""
    return _make_sp(ulysses_attention_shard, mesh, axis, causal)
