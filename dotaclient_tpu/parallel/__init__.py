"""Parallelism library: meshes, shardings, SP/TP/PP primitives."""

from dotaclient_tpu.parallel.mesh import data_sharding, make_mesh, replicated

__all__ = ["data_sharding", "make_mesh", "replicated"]
