"""Parallelism library: meshes, shardings, and the TP/PP/SP primitives.

Coverage vs SURVEY.md §2.3: data parallelism (mesh + batch sharding, grad
psum), tensor parallelism (``sharding.state_shardings``), pipeline
parallelism (``pipeline.make_pipeline``), sequence parallelism
(``sequence``: ring + Ulysses attention). Expert parallelism is deliberately
absent — the reference has no MoE (SURVEY.md §2.3 row 6); an EP axis would
slot into ``MeshConfig`` + a shard_map'd expert dispatch the same way the
primitives here do.
"""

from dotaclient_tpu.parallel.mesh import data_sharding, make_mesh, replicated
from dotaclient_tpu.parallel.pipeline import make_pipeline, stack_stage_params
from dotaclient_tpu.parallel.sequence import (
    make_ring_attention,
    make_ulysses_attention,
)
from dotaclient_tpu.parallel.sharding import param_spec, state_shardings

__all__ = [
    "data_sharding",
    "make_mesh",
    "make_pipeline",
    "make_ring_attention",
    "make_ulysses_attention",
    "param_spec",
    "replicated",
    "stack_stage_params",
    "state_shardings",
]
