"""Parallelism library: meshes, shardings, SP/TP/PP primitives."""

from dotaclient_tpu.parallel.mesh import data_sharding, make_mesh, replicated
from dotaclient_tpu.parallel.sharding import param_spec, state_shardings

__all__ = [
    "data_sharding",
    "make_mesh",
    "param_spec",
    "replicated",
    "state_shardings",
]
