"""Parallelism library: meshes, shardings, and the TP/PP/SP primitives.

Coverage vs SURVEY.md §2.3: data parallelism (mesh + batch sharding, grad
psum), tensor parallelism (``sharding.state_shardings``), pipeline
parallelism (``pipeline.make_pipeline``), sequence parallelism
(``sequence``: ring + Ulysses attention), and expert parallelism
(``expert``: shard_map + all_to_all Switch dispatch; the GSPMD einsum form
lives in ``models.moe`` and shards via the ``"expert"`` path rule in
``sharding``). The reference has none of TP/PP/SP/EP (its core is an
LSTM(128) on one GPU); the rebuild ships them first-class per SURVEY.md §7
step 8.
"""

from dotaclient_tpu.parallel.distributed import (
    initialize_runtime,
    process_info,
)
from dotaclient_tpu.parallel.expert import make_expert_dispatch
from dotaclient_tpu.parallel.mesh import (
    batch_axes,
    batch_shard_count,
    collective_probe_ms,
    data_sharding,
    make_mesh,
    replicated,
    row_sharding,
)
from dotaclient_tpu.parallel.pipeline import make_pipeline, stack_stage_params
from dotaclient_tpu.parallel.sequence import (
    make_ring_attention,
    make_ulysses_attention,
)
from dotaclient_tpu.parallel.sharding import param_spec, state_shardings

__all__ = [
    "batch_axes",
    "batch_shard_count",
    "collective_probe_ms",
    "data_sharding",
    "initialize_runtime",
    "make_expert_dispatch",
    "process_info",
    "make_mesh",
    "make_pipeline",
    "make_ring_attention",
    "make_ulysses_attention",
    "param_spec",
    "replicated",
    "row_sharding",
    "stack_stage_params",
    "state_shardings",
]
