"""Version-compat shims shared by the parallelism modules."""

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

__all__ = ["shard_map"]
