"""Version-compat shims shared by the parallelism modules."""

import jax

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map


def pcast_varying(x, axis_names):
    """``jax.lax.pcast(x, axis_names, to="varying")`` where it exists.

    The varying-type annotation (and the carry-type checking that makes it
    necessary inside shard_map loops) only exists in newer jax; on older
    versions (this container's 0.4.x) there is nothing to annotate and the
    identity is exactly equivalent — the fori_loop carries type-check
    without it."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is None:
        return x
    return pcast(x, axis_names, to="varying")


__all__ = ["shard_map", "pcast_varying"]
