"""Tensor-parallel sharding rules for the policy parameters.

The reference has no TP — its core is an LSTM(128) on one GPU (SURVEY.md
§2.3 row 3) — but the rebuild ships it first-class so widened cores scale
over the mesh's ``model`` axis. GSPMD semantics make this purely a layout
choice: annotate the parameter (and matching optimizer-state) leaves with a
PartitionSpec and XLA emits the all-gathers/reduce-scatters over ICI; the
math is unchanged, which the 1-vs-N equivalence test pins down.

Rule (Megatron-style column sharding, applied uniformly): any parameter
whose LAST axis is divisible by the model-axis size is sharded on that axis
(Dense/LSTM-gate kernels ``[in, out]`` and their biases, embedding tables
``[vocab, dim]``); everything else — tiny heads, scalars — is replicated.
With ``model_parallel == 1`` every leaf is replicated and behavior is
bit-identical to the data-parallel-only path.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dotaclient_tpu.config import MeshConfig


def param_spec(shape, mesh: Mesh, config: MeshConfig) -> P:
    """PartitionSpec for one parameter leaf under the model axis."""
    model = config.model_axis
    n = mesh.shape[model]
    if n > 1 and len(shape) >= 1 and shape[-1] % n == 0 and shape[-1] >= n:
        return P(*((None,) * (len(shape) - 1)), model)
    return P()


def state_shardings(state: Any, mesh: Mesh, config: MeshConfig) -> Any:
    """Shardings for a full TrainState pytree: parameter-shaped leaves (the
    params and Adam's mu/nu mirrors) follow :func:`param_spec`; scalars and
    counters replicate."""

    def leaf_sharding(leaf) -> NamedSharding:
        shape = getattr(leaf, "shape", ())
        return NamedSharding(mesh, param_spec(shape, mesh, config))

    return jax.tree.map(leaf_sharding, state)
