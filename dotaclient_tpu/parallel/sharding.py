"""Tensor-parallel sharding rules for the policy parameters.

The reference has no TP — its core is an LSTM(128) on one GPU (SURVEY.md
§2.3 row 3) — but the rebuild ships it first-class so widened cores scale
over the mesh's ``model`` axis. GSPMD semantics make this purely a layout
choice: annotate the parameter (and matching optimizer-state) leaves with a
PartitionSpec and XLA emits the all-gathers/reduce-scatters over ICI; the
math is unchanged, which the 1-vs-N equivalence test pins down.

Rules, in precedence order:

1. **Expert parallelism**: any parameter whose tree path contains
   ``"expert"`` (the MoE expert-major tensors ``[E, ...]`` of
   ``models/moe.py``) is sharded on its FIRST axis over the model axis —
   each device holds ``E/n`` whole experts; GSPMD turns the dispatch/
   combine einsums into all-to-alls.
2. **Megatron-style column sharding**, applied uniformly: any parameter
   whose LAST axis is divisible by the model-axis size is sharded on that
   axis (Dense/LSTM-gate kernels ``[in, out]`` and their biases, embedding
   tables ``[vocab, dim]``).
3. Everything else — tiny heads, scalars — is replicated.

With ``model_parallel == 1`` every leaf is replicated and behavior is
bit-identical to the data-parallel-only path.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dotaclient_tpu.config import MeshConfig


def param_spec(shape, mesh: Mesh, config: MeshConfig, path: str = "") -> P:
    """PartitionSpec for one parameter leaf under the model axis."""
    model = config.model_axis
    n = mesh.shape[model]
    if n <= 1:
        return P()
    if "expert" in path and len(shape) >= 1 and shape[0] % n == 0:
        return P(model, *((None,) * (len(shape) - 1)))
    if len(shape) >= 1 and shape[-1] % n == 0 and shape[-1] >= n:
        return P(*((None,) * (len(shape) - 1)), model)
    return P()


def state_shardings(state: Any, mesh: Mesh, config: MeshConfig) -> Any:
    """Shardings for a full TrainState pytree: parameter-shaped leaves (the
    params and Adam's mu/nu mirrors) follow :func:`param_spec`; scalars and
    counters replicate."""

    def leaf_sharding(path, leaf) -> NamedSharding:
        shape = getattr(leaf, "shape", ())
        name = jax.tree_util.keystr(path)
        return NamedSharding(mesh, param_spec(shape, mesh, config, name))

    return jax.tree_util.tree_map_with_path(leaf_sharding, state)
