"""Expert parallelism: explicit shard_map + all_to_all MoE dispatch.

The library-level EP primitive, sibling to the ring/Ulysses SP modules
(SURVEY.md §2.3 row 6 — the reference has no MoE; the rebuild ships EP
first-class). Layout is the classic GShard/Switch plan:

* tokens are batch-sharded over the ``axis`` (each device holds ``B/n``);
* experts are sharded over the SAME axis (each device owns ``E/n`` whole
  expert FFNs, weights ``[E/n, D, F]`` local);
* routing is capacity-limited top-1; the dispatched token blocks cross the
  mesh twice per layer via ``all_to_all`` (token-shard → expert-shard and
  back), riding ICI.

``models/moe.py`` is the other half of the story: the same math written as
plain sharded einsums for GSPMD to partition automatically inside the
policy's ``jit``. This module is the explicit form — useful when the
schedule must be pinned by hand and as the executable spec the GSPMD path
is tested against.
"""

from __future__ import annotations

import functools
import math
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from dotaclient_tpu.parallel._compat import shard_map

AXIS = "data"


def expert_capacity(n_tokens: int, n_experts: int, capacity_factor: float) -> int:
    """Token slots per expert per routing call (shared by the shard_map and
    GSPMD MoE forms so the two can never drift)."""
    return max(1, math.ceil(n_tokens / n_experts * capacity_factor))


def route_top1(
    x: jnp.ndarray, gate_w: jnp.ndarray, n_experts: int, capacity: int
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Capacity-limited top-1 routing for local tokens ``x [Bl, D]``.

    Returns (dispatch [Bl, E, C] 0/1, combine [Bl, E, C] = dispatch ×
    gate-prob, probs [Bl, E] — the full pre-drop gate softmax, for aux
    load-balancing losses). Overflow tokens beyond ``capacity`` per expert
    are dropped (Switch semantics — static shapes for XLA).
    """
    logits = x.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)
    prob = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]
    onehot = jax.nn.one_hot(expert, n_experts, dtype=jnp.float32)
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot
    keep = pos < capacity
    dispatch = (
        onehot[..., None]
        * keep[..., None]
        * jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=jnp.float32)
    )
    combine = dispatch * prob[:, None, None]
    return dispatch, combine, probs


def moe_shard(
    x: jnp.ndarray,
    gate_w: jnp.ndarray,
    w1: jnp.ndarray,
    b1: jnp.ndarray,
    w2: jnp.ndarray,
    b2: jnp.ndarray,
    axis_name: str = AXIS,
    capacity_factor: float = 2.0,
) -> jnp.ndarray:
    """Per-shard MoE body (call under shard_map).

    x: LOCAL token shard [Bl, D]; gate_w [D, E] replicated; w1/b1/w2/b2
    LOCAL expert shard [El, ...] where El = E / axis size. Output [Bl, D]:
    sum over each token's selected expert output × gate prob (zeros for
    capacity-dropped tokens).
    """
    n = jax.lax.psum(1, axis_name)
    Bl, D = x.shape
    El = w1.shape[0]
    E = El * n
    capacity = expert_capacity(Bl, E, capacity_factor)

    dispatch, combine, _ = route_top1(x, gate_w, E, capacity)

    # [Bl, E, C] × [Bl, D] → [E, C, D]: this device's contribution to every
    # expert's queue
    xin = jnp.einsum("bec,bd->ecd", dispatch, x.astype(jnp.float32))
    # token-shard → expert-shard: each device keeps its E/n experts' queues
    # from ALL devices; [E, C, D] = [n·El, C, D] → [n, El·C? ...] — tiled
    # all_to_all splits axis 0 (experts) and concats on a fresh leading
    # device axis, giving [n·local? ...]. Concretely: split E into n groups
    # of El, exchange, concat along C: [El, n·C, D].
    xin = jax.lax.all_to_all(
        xin, axis_name, split_axis=0, concat_axis=1, tiled=True
    )                                                        # [El, n·C, D]

    h = jnp.einsum("ecd,edf->ecf", xin, w1.astype(jnp.float32)) + b1[:, None]
    h = jax.nn.gelu(h)
    out = jnp.einsum("ecf,efd->ecd", h, w2.astype(jnp.float32)) + b2[:, None]

    # expert-shard → token-shard: inverse exchange
    out = jax.lax.all_to_all(
        out, axis_name, split_axis=1, concat_axis=0, tiled=True
    )                                                        # [E, C, D]
    y = jnp.einsum("bec,ecd->bd", combine, out)
    return y.astype(x.dtype)


def make_expert_dispatch(
    mesh: Mesh, axis: str = AXIS, capacity_factor: float = 2.0
):
    """jitted MoE layer over ``axis``: (x [B, D], gate_w [D, E], w1 [E, D, F],
    b1 [E, F], w2 [E, F, D], b2 [E, D]) → [B, D], tokens batch-sharded and
    experts expert-sharded over the same mesh axis."""
    tok = P(axis)          # tokens: leading dim sharded
    exp = P(axis)          # experts: leading dim sharded
    rep = P()
    wrapped = shard_map(
        functools.partial(
            moe_shard, axis_name=axis, capacity_factor=capacity_factor
        ),
        mesh=mesh,
        in_specs=(tok, rep, exp, exp, exp, exp),
        out_specs=tok,
    )
    return jax.jit(wrapped)
