"""Multi-host / multi-slice runtime initialization.

The reference's cross-machine story was NCCL (at most, inside one learner)
plus RabbitMQ between processes (SURVEY.md §2.4); the TPU-native backend is
the XLA runtime itself: every host in a slice (and every slice in a
multi-slice job) joins one JAX distributed system, after which
``jax.devices()`` spans the whole job, a ``(dcn, data, model)`` mesh from
``make_mesh`` covers it, and every collective — gradient psum over
ICI+DCN, TP all-gathers, ring-attention ppermutes — is emitted by XLA
against the global mesh with zero user communication code (SURVEY.md §5.8).

Usage, one call per host process before any other jax op:

    from dotaclient_tpu.parallel import initialize_runtime
    initialize_runtime()                      # TPU pods: all auto-detected
    initialize_runtime("10.0.0.1:1234", 4, 2) # explicit (e.g. CPU fleets)

The learner CLI wires this as ``--multihost`` (plus ``--dcn-slices`` for the
mesh): on GKE TPU node pools the coordinator/process count/process id are
discovered from the TPU metadata server, so the no-arg form suffices on
every host; non-TPU fleets pass the three explicit values.
"""

from __future__ import annotations

from typing import Optional

import jax


def _is_initialized() -> bool:
    # jax.distributed.is_initialized is absent before jax 0.4.x-late; fall
    # back to the client handle the initialize() call populates.
    checker = getattr(jax.distributed, "is_initialized", None)
    if checker is not None:
        return bool(checker())
    try:
        from jax._src.distributed import global_state
    except ImportError:  # pragma: no cover - very old/new private layout
        return False
    return global_state.client is not None


def initialize_runtime(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join (or create) the job-wide JAX distributed system.

    No-arg on TPU pods/GKE: everything is discovered from the TPU metadata
    environment. Explicit args serve CPU fleets and tests. Idempotent —
    calling twice (e.g. test re-entry) is a no-op rather than an error.
    """
    if _is_initialized():
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def process_info() -> dict:
    """This host's coordinates in the job: {process_index, process_count,
    local_devices, global_devices}."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
