"""Generated protobuf bindings for the first-party wire format.

Regenerate with:
    protoc --python_out=dotaclient_tpu/protos -I dotaclient_tpu/protos \
        dotaclient_tpu/protos/dota.proto
"""
from dotaclient_tpu.protos import dota_pb2  # noqa: F401
