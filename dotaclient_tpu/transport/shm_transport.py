"""Same-host shared-memory transport: zero-syscall experience + weights.

The dominant split topology runs the rollout-worker processes ON the
learner host (one TPU host, N CPU actor processes — SURVEY.md §1, §2.3
row 1). Shipping bytes through loopback TCP there pays two kernel copies,
a syscall per send/recv, and a reader thread per connection for data that
never leaves the machine. This module implements the same ``Transport``
protocol over POSIX shared memory instead (ISSUE 3):

* **rollout lane** — one single-producer/single-consumer byte ring per
  actor slot. The producer (actor) writes ``u32 length + payload +
  u32 crc32`` frames (the CRC trailer is ``serialize.frame_crc32`` —
  ISSUE 4 wire integrity; the length word's HIGH BIT marks a fleet
  metrics snapshot frame, ISSUE 13 — same CRC/quarantine semantics,
  routed to ``metrics_handler`` instead of the consume path) and bumps a
  cumulative ``tail``; the consumer
  (learner) copies frames out and bumps ``head``. No locks: SPSC with
  cumulative 8-byte counters (written only by their owning side) needs
  none. A full ring drops the NEW frame (counted in the ring header — the
  actor must never block on a slow learner; cf. the socket path's
  drop-oldest). The drain verifies each frame's CRC (the fold runs at
  memory bandwidth — see serialize.py): a mismatch drops and counts the
  frame (``transport/frames_corrupt_total``), an implausible length word
  means framing is lost and the ring is resynced to its tail, and
  ``poison_frame_limit`` consecutive bad frames quarantine the slot
  (``transport/peers_quarantined``) — it is never drained again until its
  claimant goes away and the slot is reaped.
* **weights lane** — one seqlock'd slab. ``publish_weights`` bumps the
  sequence word to odd, writes version + payload, bumps it back to even;
  readers retry on a torn read (seq changed / odd). Writers never wait for
  readers and readers never wait for writers — latest-wins by
  construction, with none of the fanout's per-connection sends.

Segment layout (name = the lane's address, passed to both sides):

    <name>-w                weights slab:
        [0..8)   seq   u64  (odd while the server writes)
        [8..16)  version i64
        [16..24) length  u64
        [24..32) server pid beacon
        [32..40) payload crc32 (low 4 bytes used)
        [40..)   payload
    <name>-r<i>  i ∈ [0, slots)   rollout ring per actor slot:
        [0..8)   head  u64  cumulative bytes consumed  (learner-owned)
        [8..16)  tail  u64  cumulative bytes written   (actor-owned)
        [16..24) frames u64 cumulative frames written  (actor-owned)
        [24..32) dropped u64 frames dropped ring-full  (actor-owned)
        [32..40) claim u64  owning actor pid, 0 = free
        [64..)   data (ring_bytes)

Slot claim: an actor scans the rings and claims a free one through an
``O_CREAT|O_EXCL`` lockfile next to the segments (atomic on the
filesystem — two actors racing the same slot cannot both win), then
writes its pid into the ring's claim word for observability. Both are
released on close; the server reaps slots whose claiming pid is gone
(crashed actors never run ``close()``), so a supervisor-restarted fleet
cannot leak slots. Actors and learner must share a filesystem namespace
(/dev/shm) — same host, the lane's whole point.

Python 3.10's ``SharedMemory`` registers attachments with the resource
tracker as if it owned them, which would unlink live segments when an
actor exits; attachments here are explicitly unregistered (the server —
the creator — is the only unlinker).
"""

from __future__ import annotations

import os
import struct
import time
from multiprocessing import resource_tracker, shared_memory
from typing import List, Optional, Tuple

from dotaclient_tpu.protos import dota_pb2 as pb
from dotaclient_tpu.transport.serialize import frame_crc32
from dotaclient_tpu.utils import faults, telemetry, tracing

_U64 = struct.Struct("<Q")
_I64 = struct.Struct("<q")
_U32 = struct.Struct("<I")
_TAIL_FRAMES = struct.Struct("<QQ")   # adjacent tail+frames header words

_OFF_HEAD = 0
_OFF_TAIL = 8
_OFF_FRAMES = 16
_OFF_DROPPED = 24
_OFF_CLAIM = 32
_RING_HDR = 64

_OFF_SEQ = 0
_OFF_VERSION = 8
_OFF_LENGTH = 16
_OFF_SERVER_PID = 24   # liveness beacon: actors probe it (same host)
_OFF_CRC = 32          # weights-payload crc32 (wire integrity, ISSUE 4)
_SLAB_HDR = 40

_FRAME_OVERHEAD = 8    # u32 length prefix + u32 crc32 trailer per ring frame

# Ring frames carry no kind byte (every frame was a rollout until ISSUE
# 13); the length word's high bit marks a fleet-health metrics snapshot
# instead. Ring capacities are far below 2^31, so the bit is free, the
# length-plausibility check masks it off first, and the CRC/quarantine
# semantics are IDENTICAL for both frame kinds (pinned by test).
_METRICS_FLAG = 0x80000000
_LEN_MASK = 0x7FFFFFFF

# Slot-claim lockfiles live next to the segments. SharedMemory maps names
# into /dev/shm on Linux; the lockfile's O_CREAT|O_EXCL creation is the
# atomic mutex the claim-word write alone cannot provide.
_SHM_DIR = "/dev/shm"


def _lock_path(name: str, slot: int) -> str:
    return os.path.join(_SHM_DIR, f"{name}-claim{slot}")


def _try_lock_slot(name: str, slot: int) -> bool:
    """Atomically claim slot ``slot`` (O_EXCL). False if already claimed.
    The claimant's pid is written INTO the lockfile so the server's reaper
    can recognize a claimant that died before (or after) publishing its
    pid in the ring's claim word."""
    try:
        fd = os.open(
            _lock_path(name, slot),
            os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644,
        )
    except FileExistsError:
        return False
    try:
        os.write(fd, str(os.getpid()).encode())
    finally:
        os.close(fd)
    return True


def _lockfile_pid(name: str, slot: int) -> "int | None":
    """Pid recorded in the slot's lockfile; None if unreadable/empty."""
    try:
        with open(_lock_path(name, slot), "rb") as f:
            return int(f.read().strip() or b"0") or None
    except (OSError, ValueError):
        return None


def _unlock_slot(name: str, slot: int) -> None:
    try:
        os.unlink(_lock_path(name, slot))
    except FileNotFoundError:
        pass


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:  # pragma: no cover - exists under another uid
        return True
    return True


def _reclaim_stale_lane(name: str) -> None:
    """Unlink lane ``name``'s segments iff its server pid beacon is dead.

    Raises FileExistsError when a LIVE server still owns the lane — the
    caller must not steal it."""
    try:
        slab = _attach(f"{name}-w")
    except FileNotFoundError:
        return   # only rings/locks linger: fall through to ring reclaim
    else:
        pid = _U64.unpack_from(slab.buf, _OFF_SERVER_PID)[0]
        alive = bool(pid) and _pid_alive(int(pid))
        try:
            if not alive:
                slab.unlink()
            slab.close()
        except (OSError, BufferError):  # pragma: no cover
            pass
        if alive:
            raise FileExistsError(
                f"shm lane {name!r} is owned by live learner pid {pid}"
            )
    i = 0
    while True:
        try:
            seg = _attach(f"{name}-r{i}")
        except FileNotFoundError:
            break
        try:
            seg.unlink()
            seg.close()
        except (OSError, BufferError):  # pragma: no cover
            pass
        _unlock_slot(name, i)
        i += 1


# Segment names created by THIS process's servers: a same-process attach
# (tests, single-process topologies) shares the creator's tracker cache
# entry, and unregistering it would make the creator's unlink double-free.
_OWNED_BY_THIS_PROCESS: set = set()


def _attach(name: str) -> shared_memory.SharedMemory:
    shm = shared_memory.SharedMemory(name=name)
    if name not in _OWNED_BY_THIS_PROCESS:
        try:
            # 3.10 registers attachments like creations; without this the
            # attaching process unlinks live segments at exit
            resource_tracker.unregister(shm._name, "shared_memory")
        except Exception:  # pragma: no cover - tracker internals shifted
            pass
    return shm


def _ring_write(mv: memoryview, ring_bytes: int, pos: int, data) -> None:
    """Copy ``data`` into the ring data region at logical position ``pos``
    (mod ring_bytes), splitting across the wrap edge when needed."""
    pos %= ring_bytes
    end = pos + len(data)
    if end <= ring_bytes:
        mv[_RING_HDR + pos:_RING_HDR + end] = data
    else:
        k = ring_bytes - pos
        mv[_RING_HDR + pos:_RING_HDR + ring_bytes] = data[:k]
        mv[_RING_HDR:_RING_HDR + end - ring_bytes] = data[k:]


def _ring_read(mv: memoryview, ring_bytes: int, pos: int, n: int) -> bytes:
    pos %= ring_bytes
    end = pos + n
    if end <= ring_bytes:
        return bytes(mv[_RING_HDR + pos:_RING_HDR + end])
    k = ring_bytes - pos
    return bytes(mv[_RING_HDR + pos:_RING_HDR + ring_bytes]) + bytes(
        mv[_RING_HDR:_RING_HDR + end - ring_bytes]
    )


class ShmTransportServer:
    """Learner side: create the segments, drain every claimed ring."""

    def __init__(
        self,
        name: Optional[str] = None,
        slots: int = 16,
        ring_bytes: int = 8 * 1024 * 1024,
        weights_bytes: int = 32 * 1024 * 1024,
        poison_frame_limit: int = 8,
    ) -> None:
        if slots < 1:
            raise ValueError("shm transport needs at least one actor slot")
        self._poison_frame_limit = max(1, poison_frame_limit)
        self.name = name or f"tpu-dota-{os.getpid()}"
        self.address = self.name
        self.slots = slots
        self.ring_bytes = ring_bytes
        try:
            self._weights = shared_memory.SharedMemory(
                name=f"{self.name}-w", create=True,
                size=_SLAB_HDR + weights_bytes,
            )
        except FileExistsError:
            # fixed --shm-name + a SIGKILL'd previous learner leaves stale
            # segments: reclaim them iff their pid beacon is dead — a
            # supervisor restart must not crash-loop on its own leftovers
            _reclaim_stale_lane(self.name)
            self._weights = shared_memory.SharedMemory(
                name=f"{self.name}-w", create=True,
                size=_SLAB_HDR + weights_bytes,
            )
        _OWNED_BY_THIS_PROCESS.add(f"{self.name}-w")
        self._rings = []
        try:
            for i in range(slots):
                self._rings.append(
                    shared_memory.SharedMemory(
                        name=f"{self.name}-r{i}", create=True,
                        size=_RING_HDR + ring_bytes,
                    )
                )
                _OWNED_BY_THIS_PROCESS.add(f"{self.name}-r{i}")
        except OSError:
            # partial creation (ENOSPC on a tight /dev/shm, stale ring):
            # unlink what was created — a failed constructor must not
            # poison the name or leak tmpfs pages until reboot
            for seg in (self._weights, *self._rings):
                try:
                    seg.unlink()
                    seg.close()
                except (OSError, FileNotFoundError):
                    pass
            _OWNED_BY_THIS_PROCESS.discard(f"{self.name}-w")
            for k in range(slots):
                _OWNED_BY_THIS_PROCESS.discard(f"{self.name}-r{k}")
            raise
        for seg in (self._weights, *self._rings):
            seg.buf[:_RING_HDR] = bytes(_RING_HDR)  # zeroed headers
        # liveness beacon: shm has no connection to break, so actors probe
        # this pid to notice a dead/restarted learner (and then reconnect
        # with backoff or exit for the supervisor — actor/__main__.py)
        _U64.pack_into(self._weights.buf, _OFF_SERVER_PID, os.getpid())
        for i in range(slots):
            # fresh lane (segment creation above proved no live server owns
            # this name): any same-name lockfile is a crashed run's leftover
            _unlock_slot(self.name, i)
        self._consumed = [0] * slots      # frames drained per ring
        self._next_ring = 0               # round-robin drain fairness
        self._last_telemetry = 0.0        # ring-scan gauges are time-gated
        # Deferred release (the zero-copy contract): a drain hands back
        # memoryview slices INTO the rings; the freed space is published to
        # the producers only at the NEXT drain call, by which point the
        # caller has decoded/staged the previous batch (the learner's
        # ingest copies rows into the buffer's staging lanes before it
        # polls again).
        self._pending_head: List[Optional[int]] = [None] * slots
        self._latest_weights: Optional[pb.ModelWeights] = None
        self.bad_payloads = 0
        self._closed = False
        # Poison-frame quarantine state (ISSUE 4): consecutive corrupt
        # frames per slot, and the quarantine flag that stops draining a
        # slot whose producer ships garbage (the slot returns to service
        # when its dead claimant is reaped and a new actor claims it).
        self._bad_streak = [0] * slots
        self._quarantined = [False] * slots
        self._tel = telemetry.get_registry()
        # eager-create (schema stability — see socket_transport.py)
        self._tel.gauge("shm/ring_occupancy")
        self._tel.gauge("shm/ring_dropped_total")
        self._tel.gauge("transport/queue_depth")
        self._tel.counter("transport/frames_corrupt_total")
        self._tel.counter("transport/peers_quarantined")
        # quantized experience plane (ISSUE 7) — pinned by
        # check_telemetry_schema.py --require-wire
        self._tel.counter("transport/rollout_bytes_total")
        self._tel.counter("transport/rollout_raw_bytes_total")
        self._tel.gauge("transport/rollout_compression_ratio").set(1.0)
        self._rollout_totals = [0, 0]   # [wire bytes, raw bytes] consumed
        # Fleet-health snapshot sink (ISSUE 13): the learner's
        # FleetAggregator assigns its `ingest` here; the drain hands it
        # every CRC-verified metrics frame (length-word high bit).
        self.metrics_handler = None

    # -- rollout lane ------------------------------------------------------

    def _release_pending(self) -> None:
        """Publish the head positions of the previous drain's frames: their
        views are consumed by now, so the producers may reuse the space."""
        for i, h in enumerate(self._pending_head):
            if h is not None:
                _U64.pack_into(self._rings[i].buf, _OFF_HEAD, h)
                self._pending_head[i] = None

    def _poison_slot(self, i: int) -> None:
        """One corrupt frame from slot ``i``'s producer: count, bump the
        streak, and quarantine the slot at ``poison_frame_limit`` — it is
        skipped by every later drain until its claimant is reaped and a
        fresh actor claims the ring."""
        self._tel.counter("transport/frames_corrupt_total").inc()
        self._bad_streak[i] += 1
        if self._bad_streak[i] >= self._poison_frame_limit:
            self._quarantined[i] = True
            self._tel.counter("transport/peers_quarantined").inc()

    def _resync_ring(self, i: int, mv: memoryview, tail: int) -> None:
        """Framing lost (implausible length word): discard everything
        buffered by fast-forwarding ``head`` to the snapshot ``tail`` —
        the next intact frame the producer writes re-establishes framing."""
        self._pending_head[i] = tail
        # everything written so far counts as consumed (discarded), so the
        # pending_rollouts gauge doesn't drift on the skipped frames
        self._consumed[i] = _U64.unpack_from(mv, _OFF_FRAMES)[0]

    def _drain_ring(
        self, i: int, budget: int, out: List[memoryview]
    ) -> None:
        """Collect every complete frame from ring ``i`` (up to ``budget``
        total frames in ``out``) as ZERO-COPY memoryview slices into the
        ring itself — per frame: one length unpack, one slice, and the CRC
        fold (serialize.frame_crc32 — memory-bandwidth speed; the ONLY
        per-frame integrity cost, there is no fault-injection branch in
        this loop). No payload copy at all (only a frame that physically
        wraps the ring edge is copied, at most one per lap). The consumed
        space is not released here — ``head`` advances at the next drain
        (``_release_pending``), after the caller has decoded/staged these
        frames; until then the producer cannot overwrite them."""
        if self._quarantined[i]:
            return
        mv = self._rings[i].buf
        N = self.ring_bytes
        # the consume position this CALL has already reached: the shm head
        # word lags by one drain (deferred release), so a second pass within
        # the same drain — the empty-result spin, or a post-resync retry —
        # must continue from the pending position, not re-read stale frames
        head = self._pending_head[i]
        if head is None:
            head = _U64.unpack_from(mv, _OFF_HEAD)[0]
        tail = _U64.unpack_from(mv, _OFF_TAIL)[0]
        if head == tail:
            return
        consumed = 0
        while head < tail and len(out) < budget:
            pos = head % N
            if pos + 4 <= N:
                word = _U32.unpack_from(mv, _RING_HDR + pos)[0]
            else:
                word = _U32.unpack(_ring_read(mv, N, pos, 4))[0]
            # high bit = fleet metrics snapshot (ISSUE 13); the masked
            # length feeds the SAME plausibility/CRC/quarantine path
            is_metrics = bool(word & _METRICS_FLAG)
            length = word & _LEN_MASK
            if (
                length > N - _FRAME_OVERHEAD
                or _FRAME_OVERHEAD + length > tail - head
            ):
                # length word itself is garbage: framing is unrecoverable,
                # resync to the producer's tail and count the event
                self._poison_slot(i)
                self._resync_ring(i, mv, tail)
                return
            dpos = (pos + 4) % N
            if dpos + length <= N:     # common case: contiguous → view
                base = _RING_HDR + dpos
                payload = mv[base:base + length]
            else:                      # wraps the edge: one stitch copy
                payload = memoryview(_ring_read(mv, N, dpos, length))
            cpos = (dpos + length) % N
            if cpos + 4 <= N:
                crc = _U32.unpack_from(mv, _RING_HDR + cpos)[0]
            else:
                crc = _U32.unpack(_ring_read(mv, N, cpos, 4))[0]
            head += _FRAME_OVERHEAD + length
            consumed += 1
            if crc != frame_crc32(payload):
                self._poison_slot(i)   # dropped + counted, not delivered
                if self._quarantined[i]:
                    break
                continue
            self._bad_streak[i] = 0
            if is_metrics:
                # copied out (small frames) before the view's deferred
                # release; never delivered to the rollout consume path
                handler = self.metrics_handler
                if handler is not None:
                    try:
                        handler(bytes(payload))
                    except Exception:  # noqa: BLE001
                        pass   # a broken sink must never break the drain
                continue
            out.append(payload)
        if consumed:
            self._consumed[i] += consumed
            self._pending_head[i] = head

    def _drain(
        self, max_count: int, timeout: Optional[float]
    ) -> "List[Tuple[float, memoryview]]":
        """Drain complete frames as ``(recv_ts, view)`` pairs. On the shm
        lane the drain IS the receive (there is no reader thread), so one
        stamp per drain call serves every frame it collected — the `recv`
        trace hop (ISSUE 12), taken after the CRC folds like the socket
        reader's."""
        views: List[memoryview] = []
        t0 = time.perf_counter()
        deadline = None if timeout is None else t0 + timeout
        self._release_pending()
        while True:
            start = self._next_ring
            for k in range(self.slots):
                self._drain_ring((start + k) % self.slots, max_count, views)
            self._next_ring = (start + 1) % self.slots
            if views or self._closed:
                break
            if deadline is not None and time.perf_counter() >= deadline:
                break
            time.sleep(0.0005)
        recv_ts = tracing.now()
        out = [(recv_ts, v) for v in views]
        if out:
            self._tel.timer("span/transport/consume").observe(
                time.perf_counter() - t0
            )
            self._tel.counter("transport/experience_consumed").inc(len(out))
        now = time.perf_counter()
        if now - self._last_telemetry > 0.05:
            # the full ring scan costs ~a frame of time on slow hosts —
            # gauges refresh at human cadence, not per drain call
            self._last_telemetry = now
            self._publish_ring_telemetry()
        return out

    def _publish_ring_telemetry(self) -> None:
        occ = 0.0
        dropped = 0
        pending = 0
        for i, seg in enumerate(self._rings):
            mv = seg.buf
            head = _U64.unpack_from(mv, _OFF_HEAD)[0]
            tail = _U64.unpack_from(mv, _OFF_TAIL)[0]
            frames = _U64.unpack_from(mv, _OFF_FRAMES)[0]
            dropped += _U64.unpack_from(mv, _OFF_DROPPED)[0]
            occ = max(occ, (tail - head) / self.ring_bytes)
            pending += frames - self._consumed[i]
            # reap: a crashed actor never runs close(), so its slot would
            # stay claimed forever and a supervisor-restarted fleet would
            # exhaust slots. Claiming pids are same-host by construction,
            # so liveness is one signal-0 probe. (Re-check the claim word
            # right before unlocking: a fresh claimant may have raced in.)
            claim = _U64.unpack_from(mv, _OFF_CLAIM)[0]
            if claim and not _pid_alive(int(claim)):
                if _U64.unpack_from(mv, _OFF_CLAIM)[0] == claim:
                    _U64.pack_into(mv, _OFF_CLAIM, 0)
                    _unlock_slot(self.name, i)
                    self._tel.counter("shm/slots_reaped").inc()
                    # a quarantined slot returns to service with its next
                    # (fresh) claimant: discard the poisoned backlog and
                    # clear the flag — the garbage producer is gone
                    if self._quarantined[i]:
                        self._quarantined[i] = False
                        self._bad_streak[i] = 0
                        self._resync_ring(
                            i, mv, _U64.unpack_from(mv, _OFF_TAIL)[0]
                        )
            elif not claim and os.path.exists(_lock_path(self.name, i)):
                # claimant died in the window between creating its lockfile
                # and publishing its pid in the claim word — the lockfile's
                # own pid record covers it (an unreadable/empty file gets a
                # grace period: a LIVE claimant may be mid-write)
                pid = _lockfile_pid(self.name, i)
                if pid is not None:
                    if not _pid_alive(pid):
                        _unlock_slot(self.name, i)
                        self._tel.counter("shm/slots_reaped").inc()
                else:
                    try:
                        age = time.time() - os.path.getmtime(
                            _lock_path(self.name, i)
                        )
                    except OSError:
                        age = 0.0
                    if age > 5.0:
                        _unlock_slot(self.name, i)
                        self._tel.counter("shm/slots_reaped").inc()
        self._tel.gauge("shm/ring_occupancy").set(occ)
        self._tel.gauge("shm/ring_dropped_total").set(float(dropped))
        self._tel.gauge("transport/queue_depth").set(float(pending))

    def consume_rollouts(
        self, max_count: int, timeout: Optional[float] = None
    ) -> List[pb.Rollout]:
        protos = []
        for _recv_ts, payload in self._drain(max_count, timeout):
            r = pb.Rollout()
            try:
                r.ParseFromString(payload)
            except Exception:
                self.bad_payloads += 1
                continue
            protos.append(r)
        return protos

    def consume_decoded(self, max_count: int, timeout: Optional[float] = None):
        """Zero-copy drain decoded; byte accounting shared with the socket
        lane via :func:`serialize.decode_drained_payloads`."""
        from dotaclient_tpu.transport.serialize import decode_drained_payloads

        out, bad = decode_drained_payloads(
            self._drain(max_count, timeout), self._tel, self._rollout_totals
        )
        self.bad_payloads += bad
        return out

    # -- weights lane ------------------------------------------------------

    def publish_weights(self, weights: pb.ModelWeights) -> None:
        """Seqlock'd slab write (single writer by contract). With the
        learner's async snapshot engine (ISSUE 5) that writer is the
        SNAPSHOT thread; in --sync-snapshots mode it is the train thread —
        never both (the engine serializes all publishes, and the tail
        drains before any mode change). Must stay free of host↔device
        syncs (scripts/check_host_sync.py scans this function)."""
        payload = weights.SerializeToString()
        mv = self._weights.buf
        cap = self._weights.size - _SLAB_HDR
        if len(payload) > cap:
            raise ValueError(
                f"encoded weights ({len(payload)} bytes) exceed the shm "
                f"slab ({cap} bytes) — raise transport.shm_weights_bytes"
            )
        seq = _U64.unpack_from(mv, _OFF_SEQ)[0]
        _U64.pack_into(mv, _OFF_SEQ, seq + 1)            # odd: write begins
        _I64.pack_into(mv, _OFF_VERSION, weights.version)
        _U64.pack_into(mv, _OFF_LENGTH, len(payload))
        _U64.pack_into(mv, _OFF_CRC, frame_crc32(payload))
        mv[_SLAB_HDR:_SLAB_HDR + len(payload)] = payload
        _U64.pack_into(mv, _OFF_SEQ, seq + 2)            # even: stable
        self._latest_weights = weights
        self._tel.counter("transport/weights_published").inc()
        self._tel.gauge("transport/weights_version").set(weights.version)
        self._tel.gauge("transport/actors_connected").set(self.n_connected)

    def latest_weights(self) -> Optional[pb.ModelWeights]:
        return self._latest_weights

    def publish_rollout(self, rollout: pb.Rollout) -> None:
        raise RuntimeError(
            "ShmTransportServer is the learner side; actors publish"
        )

    # -- bookkeeping -------------------------------------------------------

    @property
    def n_connected(self) -> int:
        n = 0
        for seg in self._rings:
            if _U64.unpack_from(seg.buf, _OFF_CLAIM)[0]:
                n += 1
        return n

    @property
    def pending_rollouts(self) -> int:
        pending = 0
        for i, seg in enumerate(self._rings):
            frames = _U64.unpack_from(seg.buf, _OFF_FRAMES)[0]
            pending += frames - self._consumed[i]
        return pending

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for seg in (self._weights, *self._rings):
            try:
                seg.unlink()
            except (OSError, FileNotFoundError):
                pass
            try:
                seg.close()
            except OSError:
                pass
            except BufferError:
                # a caller still holds zero-copy frame views: the mapping
                # must outlive them (unlink above already removed the
                # name). Disarm the destructor's re-close so GC does not
                # print "Exception ignored" noise at teardown.
                seg.close = lambda: None
        _OWNED_BY_THIS_PROCESS.discard(f"{self.name}-w")
        for i in range(self.slots):
            _OWNED_BY_THIS_PROCESS.discard(f"{self.name}-r{i}")
            _unlock_slot(self.name, i)   # lane is gone: clear stale locks


class ShmTransport:
    """Actor side: claim a ring slot, publish rollouts, read weights."""

    def __init__(self, name: str, slots: Optional[int] = None) -> None:
        """Attach to lane ``name``; probe every existing ring segment (the
        server decides how many exist — ``slots`` only bounds the probe for
        tests) and claim the first free one via its O_EXCL lockfile."""
        self.name = name
        self._weights_shm = _attach(f"{name}-w")
        # a SIGKILL'd learner leaves its segments behind: attaching them
        # must fail like a refused TCP connect, or the reconnect loop in
        # actor/__main__.py would "succeed" against a corpse forever
        server_pid = _U64.unpack_from(self._weights_shm.buf, _OFF_SERVER_PID)[0]
        if server_pid and not _pid_alive(int(server_pid)):
            self._weights_shm.close()
            raise ConnectionError(
                f"shm lane {name!r}: learner process {server_pid} is gone"
            )
        self._ring: Optional[shared_memory.SharedMemory] = None
        self.slot = -1
        pid = os.getpid()
        i = 0
        while slots is None or i < slots:
            try:
                seg = _attach(f"{name}-r{i}")
            except FileNotFoundError:
                break   # past the last ring the server created
            if _try_lock_slot(name, i):   # atomic: a race has ONE winner
                _U64.pack_into(seg.buf, _OFF_CLAIM, pid)
                self._ring = seg
                self.slot = i
                break
            seg.close()
            i += 1
        if self._ring is None:
            self._weights_shm.close()
            raise ConnectionError(
                f"no free shm actor slot on lane {name!r} (all claimed)"
            )
        self.ring_bytes = self._ring.size - _RING_HDR
        self._mv = self._ring.buf          # cached: .buf re-wraps per access
        self._seen_version: Optional[int] = None
        self._corrupt_version: Optional[int] = None
        self._cached: Optional[pb.ModelWeights] = None
        self._last_liveness = time.monotonic()
        self._tel = telemetry.get_registry()
        # Producer-owned header words mirrored as host ints: the producer is
        # the only writer of tail/frames/dropped, so the hot path never
        # re-reads them from shared memory (a struct.unpack_from costs µs on
        # slow hosts — per frame, that is the difference between winning
        # and losing to loopback TCP).
        mv = self._ring.buf
        self._tail = _U64.unpack_from(mv, _OFF_TAIL)[0]
        self._frames = _U64.unpack_from(mv, _OFF_FRAMES)[0]
        self._dropped = _U64.unpack_from(mv, _OFF_DROPPED)[0]
        self._faults = faults.get()   # None when chaos injection is off
        self._pub_counter = self._tel.counter("transport/experience_published")
        self._drop_counter = self._tel.counter("transport/experience_dropped")

    def _check_learner_alive(self) -> None:
        """Shared memory has no connection to break: probe the server's pid
        beacon (time-gated — one signal-0 every couple of seconds) so a
        dead/restarted learner surfaces as ConnectionError and the actor's
        reconnect-with-backoff / exit-for-supervisor machinery engages."""
        now = time.monotonic()
        if now - self._last_liveness < 2.0:
            return
        self._last_liveness = now
        pid = _U64.unpack_from(self._weights_shm.buf, _OFF_SERVER_PID)[0]
        if pid and not _pid_alive(int(pid)):
            raise ConnectionError(
                f"shm lane {self.name!r}: learner process {pid} is gone"
            )

    # -- rollouts ----------------------------------------------------------

    def publish_rollout(self, rollout: pb.Rollout) -> None:
        self.publish_rollout_bytes(rollout.SerializeToString())

    def publish_metrics_bytes(self, payload) -> bool:
        """One fleet-health snapshot frame (ISSUE 13): identical ring
        framing with the length word's high bit set — same CRC trailer,
        same drop-when-full, same quarantine exposure on the drain side."""
        return self.publish_rollout_bytes(payload, _word_flag=_METRICS_FLAG)

    def publish_rollout_bytes(self, payload, _word_flag: int = 0) -> bool:
        """One frame into the SPSC ring; returns False (counted drop) when
        full — the actor never blocks on a slow learner.

        Hot path: ONE shared-memory read (the consumer-owned ``head``), one
        payload memcpy, one combined tail+frames header write. Everything
        the producer owns lives in host ints."""
        self._check_learner_alive()
        mv = self._mv
        N = self.ring_bytes
        n = len(payload)
        need = _FRAME_OVERHEAD + n
        if need > N:
            raise ValueError(
                f"rollout frame ({need} bytes) exceeds the shm ring "
                f"({N} bytes) — raise transport.shm_ring_bytes"
            )
        tail = self._tail
        head = _U64.unpack_from(mv, _OFF_HEAD)[0]
        if need > N - (tail - head):
            self._dropped += 1
            _U64.pack_into(mv, _OFF_DROPPED, self._dropped)
            self._drop_counter.inc()
            return False
        crc = frame_crc32(payload)
        f = self._faults
        if f is not None:  # chaos hooks; one None test when faults are off
            delay = f.value("transport.delay_send")
            if delay:
                time.sleep(delay)
            if f.fire("transport.corrupt_frame"):
                crc ^= 0xDEADBEEF
        word = n | _word_flag
        pos = tail % N
        if pos + need <= N:        # common case: no wrap, three direct writes
            base = _RING_HDR + pos
            _U32.pack_into(mv, base, word)
            mv[base + 4:base + 4 + n] = payload
            _U32.pack_into(mv, base + 4 + n, crc)
        else:
            _ring_write(mv, N, pos, _U32.pack(word))
            _ring_write(mv, N, pos + 4, payload)
            _ring_write(mv, N, pos + 4 + n, _U32.pack(crc))
        # tail moves only after the payload is in place: the consumer never
        # sees a half-written frame (tail and frames are adjacent — one
        # packed write publishes both)
        self._tail = tail + need
        self._frames += 1
        _TAIL_FRAMES.pack_into(mv, _OFF_TAIL, self._tail, self._frames)
        self._pub_counter.inc()
        return True

    # -- weights -----------------------------------------------------------

    def latest_weights(self) -> Optional[pb.ModelWeights]:
        self._check_learner_alive()
        mv = self._weights_shm.buf
        for _ in range(64):   # seqlock retry budget; writes are µs-scale
            s1 = _U64.unpack_from(mv, _OFF_SEQ)[0]
            if s1 == 0:
                return None          # nothing published yet
            if s1 & 1:
                time.sleep(0.0002)   # server mid-write
                continue
            version = _I64.unpack_from(mv, _OFF_VERSION)[0]
            if version == self._seen_version:
                return self._cached  # no re-parse for an unchanged slab
            if version == self._corrupt_version:
                # known-corrupt slab: counted ONCE when discovered; skip
                # the multi-MB copy + CRC fold on every poll until the
                # server publishes a new version over it
                return self._cached
            length = _U64.unpack_from(mv, _OFF_LENGTH)[0]
            crc = _U64.unpack_from(mv, _OFF_CRC)[0]
            payload = bytes(mv[_SLAB_HDR:_SLAB_HDR + length])
            if _U64.unpack_from(mv, _OFF_SEQ)[0] != s1:
                continue             # torn read: writer raced us, retry
            if frame_crc32(payload) != crc:
                # stable seq + bad CRC = real corruption, not a torn read:
                # count it and keep serving the last good weights
                self._tel.counter("transport/frames_corrupt_total").inc()
                self._corrupt_version = version
                return self._cached
            msg = pb.ModelWeights()
            msg.ParseFromString(payload)
            self._seen_version = version
            self._cached = msg
            return msg
        return self._cached

    def consume_rollouts(
        self, max_count: int, timeout: Optional[float] = None
    ) -> List[pb.Rollout]:
        raise RuntimeError("ShmTransport is the actor side; learner consumes")

    def publish_weights(self, weights: pb.ModelWeights) -> None:
        raise RuntimeError("actors do not publish weights")

    def close(self) -> None:
        if self._ring is not None:
            try:
                _U64.pack_into(self._ring.buf, _OFF_CLAIM, 0)  # release slot
                self._mv = None
                self._ring.close()
            except (OSError, ValueError, BufferError):
                pass
            _unlock_slot(self.name, self.slot)
            self._ring = None
        try:
            self._weights_shm.close()
        except (OSError, ValueError):
            pass
