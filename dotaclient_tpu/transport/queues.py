"""Experience/weight transport behind one pluggable interface.

The reference used a RabbitMQ broker: an experience *queue* (actor→learner)
and a model fanout *exchange* (learner→actors), via pika (SURVEY.md §1
"Transport / messaging", §2.4). This sandbox has no broker and no network
(SURVEY.md §7), so the same API is served by an in-process implementation;
``AmqpTransport`` keeps the cluster path compilable and import-gated.

Semantics preserved from the reference design:
  * experience is a work queue — each rollout is consumed by exactly one
    learner;
  * weights are a fanout with replacement — actors only ever want the
    *latest* version (stale intermediate weight messages are worthless).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import List, Optional, Protocol

from dotaclient_tpu.protos import dota_pb2 as pb
from dotaclient_tpu.utils import telemetry


class Transport(Protocol):
    """Both directions of the actor↔learner channel."""

    def publish_rollout(self, rollout: pb.Rollout) -> None: ...
    def consume_rollouts(
        self, max_count: int, timeout: Optional[float] = None
    ) -> List[pb.Rollout]: ...
    def publish_weights(self, weights: pb.ModelWeights) -> None: ...
    def latest_weights(self) -> Optional[pb.ModelWeights]: ...


class InProcTransport:
    """Thread-safe in-process transport (dev/test/single-host production).

    The actor multiplexer and learner run in one process on the TPU host
    (SURVEY.md §7 "Minimum end-to-end slice"); this is the zero-copy path —
    protos are passed by reference, never serialized to bytes.
    """

    def __init__(
        self,
        max_rollouts: int = 4096,
        registry: Optional[telemetry.Registry] = None,
    ) -> None:
        self._rollouts: "queue.Queue[pb.Rollout]" = queue.Queue(max_rollouts)
        self._publish_lock = threading.Lock()
        self._weights_lock = threading.Lock()
        self._weights: Optional[pb.ModelWeights] = None
        self.dropped = 0
        self._tel = registry if registry is not None else telemetry.get_registry()

    def publish_rollout(self, rollout: pb.Rollout) -> None:
        # Actors must never block on a slow learner (the reference relies on
        # RMQ buffering; here backpressure = drop-oldest). The lock makes the
        # evict-then-put atomic across concurrent publishers.
        with self._publish_lock:
            while True:
                try:
                    self._rollouts.put_nowait(rollout)
                    break
                except queue.Full:
                    try:
                        self._rollouts.get_nowait()
                        self.dropped += 1
                        self._tel.counter("transport/experience_dropped").inc()
                    except queue.Empty:
                        pass
        self._tel.counter("transport/experience_published").inc()
        self._tel.gauge("transport/queue_depth").set(self._rollouts.qsize())

    def consume_rollouts(
        self, max_count: int, timeout: Optional[float] = None
    ) -> List[pb.Rollout]:
        # timed explicitly, recorded only when something drained: a polling
        # learner's empty 1 ms timeouts must not dominate the consume stage
        # stats (they measure idle waiting, not drain cost)
        out: List[pb.Rollout] = []
        t0 = time.perf_counter()
        try:
            out.append(self._rollouts.get(timeout=timeout))
        except queue.Empty:
            return out
        while len(out) < max_count:
            try:
                out.append(self._rollouts.get_nowait())
            except queue.Empty:
                break
        self._tel.timer("span/transport/consume").observe(time.perf_counter() - t0)
        self._tel.counter("transport/experience_consumed").inc(len(out))
        self._tel.gauge("transport/queue_depth").set(self._rollouts.qsize())
        return out

    def publish_weights(self, weights: pb.ModelWeights) -> None:
        with self._weights_lock:
            self._weights = weights
        self._tel.counter("transport/weights_published").inc()
        self._tel.gauge("transport/weights_version").set(weights.version)

    def latest_weights(self) -> Optional[pb.ModelWeights]:
        with self._weights_lock:
            return self._weights

    @property
    def pending_rollouts(self) -> int:
        return self._rollouts.qsize()


class AmqpTransport:
    """RabbitMQ-backed transport with the reference's topology: a durable
    experience queue and a fanout weights exchange.

    Import-gated: pika (and a broker) exist on a cluster, not in this sandbox
    (SURVEY.md §7). The class compiles here; connecting raises a clear error
    without pika.
    """

    EXPERIENCE_QUEUE = "experience"
    WEIGHTS_EXCHANGE = "weights"

    def __init__(self, host: str, port: int = 5672) -> None:
        try:
            import pika  # type: ignore[import-not-found]
        except ImportError as e:  # pragma: no cover - sandbox has no pika
            raise RuntimeError(
                "AmqpTransport requires pika (and a reachable RabbitMQ "
                "broker); use InProcTransport in broker-less environments"
            ) from e
        self._pika = pika
        self._tel = telemetry.get_registry()
        self._params = pika.ConnectionParameters(host=host, port=port)
        self._conn = pika.BlockingConnection(self._params)
        self._ch = self._conn.channel()
        self._ch.queue_declare(queue=self.EXPERIENCE_QUEUE, durable=True)
        self._ch.exchange_declare(
            exchange=self.WEIGHTS_EXCHANGE, exchange_type="fanout"
        )
        res = self._ch.queue_declare(queue="", exclusive=True)
        self._weights_queue = res.method.queue
        self._ch.queue_bind(
            exchange=self.WEIGHTS_EXCHANGE, queue=self._weights_queue
        )

    def publish_rollout(self, rollout: pb.Rollout) -> None:
        self.publish_rollout_bytes(rollout.SerializeToString())

    def publish_rollout_bytes(self, payload) -> None:
        """Ship pre-serialized wire bytes (the native-encoder fast path)."""
        self._ch.basic_publish(
            exchange="",
            routing_key=self.EXPERIENCE_QUEUE,
            body=bytes(payload),  # pika requires real bytes
        )
        self._tel.counter("transport/experience_published").inc()

    def consume_rollouts(
        self, max_count: int, timeout: Optional[float] = None
    ) -> List[pb.Rollout]:  # pragma: no cover
        out: List[pb.Rollout] = []
        t0 = time.perf_counter()
        for method, _props, body in self._ch.consume(
            self.EXPERIENCE_QUEUE, inactivity_timeout=timeout
        ):
            if body is None:
                break
            r = pb.Rollout()
            r.ParseFromString(body)
            out.append(r)
            self._ch.basic_ack(method.delivery_tag)
            if len(out) >= max_count:
                break
        self._ch.cancel()
        if out:  # empty inactivity timeouts are idle waiting, not drain cost
            self._tel.timer("span/transport/consume").observe(
                time.perf_counter() - t0
            )
            self._tel.counter("transport/experience_consumed").inc(len(out))
        return out

    def publish_weights(self, weights: pb.ModelWeights) -> None:  # pragma: no cover
        self._ch.basic_publish(
            exchange=self.WEIGHTS_EXCHANGE,
            routing_key="",
            body=weights.SerializeToString(),
        )
        self._tel.counter("transport/weights_published").inc()
        self._tel.gauge("transport/weights_version").set(weights.version)

    @property
    def pending_rollouts(self) -> int:  # pragma: no cover
        """Broker-side experience backlog (one passive declare round trip —
        read at log boundaries, not per step)."""
        res = self._ch.queue_declare(queue=self.EXPERIENCE_QUEUE, passive=True)
        return int(res.method.message_count)

    def latest_weights(self) -> Optional[pb.ModelWeights]:  # pragma: no cover
        latest: Optional[bytes] = None
        while True:
            method, _props, body = self._ch.basic_get(
                self._weights_queue, auto_ack=True
            )
            if body is None:
                break
            latest = body
        if latest is None:
            return None
        msg = pb.ModelWeights()
        msg.ParseFromString(latest)
        return msg
