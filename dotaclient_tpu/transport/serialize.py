"""Rollout wire-format codec: host pytrees ↔ ``Rollout`` protos.

The reference shipped experience as protobuf payloads over RabbitMQ but left
the payload schema implicit (SURVEY.md §2.1 "Transport", §7 step 1); here it
is first-party: a flat ``name → TensorProto`` map whose names are the
slash-joined paths of the training-batch pytree (``obs/units``,
``actions/move_x``, ``carry0/h``, ...). The same codec serves the learner→
actor weights direction (``ModelWeights``).

Decode is the hot ingestion path; a C++ fast-path decoder with the same wire
format backs ``decode_rollout`` when built (SURVEY.md §2.2).
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Tuple

import numpy as np

try:  # bfloat16 arrays cross the wire when actors run bf16 inference
    import ml_dtypes

    _BFLOAT16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    _BFLOAT16 = None

from dotaclient_tpu.protos import dota_pb2 as pb


def _np_dtype(name: str) -> np.dtype:
    if name == "bfloat16":
        if _BFLOAT16 is None:
            raise ValueError("bfloat16 payload but ml_dtypes unavailable")
        return _BFLOAT16
    return np.dtype(name)


def _dtype_name(dtype: np.dtype) -> str:
    if _BFLOAT16 is not None and dtype == _BFLOAT16:
        return "bfloat16"
    return dtype.name


def flatten_tree(tree: Any, prefix: str = "") -> Dict[str, np.ndarray]:
    """Flatten a nested dict/tuple pytree of arrays to slash-joined names."""
    out: Dict[str, np.ndarray] = {}
    if isinstance(tree, Mapping):
        items = tree.items()
    elif isinstance(tree, (tuple, list)):
        items = ((str(i), v) for i, v in enumerate(tree))
    else:
        out[prefix.rstrip("/")] = np.asarray(tree)
        return out
    for k, v in items:
        out.update(flatten_tree(v, f"{prefix}{k}/"))
    return out


def unflatten_tree(flat: Mapping[str, np.ndarray]) -> Dict[str, Any]:
    """Inverse of :func:`flatten_tree` (all-numeric levels become tuples)."""
    nested: Dict[str, Any] = {}
    for name, value in flat.items():
        parts = name.split("/")
        node = nested
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value

    def fix(node):
        if not isinstance(node, dict):
            return node
        if node and all(k.isdigit() for k in node):
            return tuple(fix(node[str(i)]) for i in range(len(node)))
        return {k: fix(v) for k, v in node.items()}

    return fix(nested)


def tensor_to_proto(arr: np.ndarray) -> pb.TensorProto:
    arr = np.ascontiguousarray(arr)
    return pb.TensorProto(
        shape=list(arr.shape), dtype=_dtype_name(arr.dtype), data=arr.tobytes()
    )


def proto_to_tensor(t: pb.TensorProto) -> np.ndarray:
    arr = np.frombuffer(t.data, dtype=_np_dtype(t.dtype))
    return arr.reshape(tuple(t.shape)).copy()


def encode_rollout(
    arrays: Any,
    model_version: int,
    env_id: int,
    rollout_id: int,
    length: int,
    total_reward: float,
) -> pb.Rollout:
    """Serialize one rollout's pytree of host arrays."""
    r = pb.Rollout(
        model_version=model_version,
        env_id=env_id,
        rollout_id=rollout_id,
        length=length,
        total_reward=total_reward,
    )
    for name, arr in flatten_tree(arrays).items():
        r.arrays[name].CopyFrom(tensor_to_proto(arr))
    return r


def decode_rollout(r: pb.Rollout) -> Tuple[Dict[str, Any], Any]:
    """Deserialize → (meta dict, pytree of arrays)."""
    meta = {
        "model_version": r.model_version,
        "env_id": r.env_id,
        "rollout_id": r.rollout_id,
        "length": r.length,
        "total_reward": r.total_reward,
    }
    flat = {name: proto_to_tensor(t) for name, t in r.arrays.items()}
    return meta, unflatten_tree(flat)


def encode_weights(params: Any, version: int) -> pb.ModelWeights:
    msg = pb.ModelWeights(version=version)
    for name, arr in flatten_tree(params).items():
        msg.params[name].CopyFrom(tensor_to_proto(np.asarray(arr)))
    return msg


def decode_weights(msg: pb.ModelWeights) -> Tuple[int, Any]:
    flat = {name: proto_to_tensor(t) for name, t in msg.params.items()}
    return msg.version, unflatten_tree(flat)
