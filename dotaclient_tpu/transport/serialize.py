"""Rollout wire-format codec: host pytrees ↔ ``Rollout`` protos.

The reference shipped experience as protobuf payloads over RabbitMQ but left
the payload schema implicit (SURVEY.md §2.1 "Transport", §7 step 1); here it
is first-party: a flat ``name → TensorProto`` map whose names are the
slash-joined paths of the training-batch pytree (``obs/units``,
``actions/move_x``, ``carry0/h``, ...). The same codec serves the learner→
actor weights direction (``ModelWeights``).

Decode is the hot ingestion path; ``decode_rollout_bytes`` uses the
first-party C++ wire parser (``dotaclient_tpu/native/rollout_codec.cc``,
single pass, zero-copy numpy views) when the native library is built, with
a pure-protobuf fallback otherwise (SURVEY.md §2.2 row 3).
"""

from __future__ import annotations

import ctypes
import threading
import zlib
from typing import Any, Dict, List, Mapping, Tuple

import numpy as np

try:  # bfloat16 arrays cross the wire when actors run bf16 inference
    import ml_dtypes

    _BFLOAT16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    _BFLOAT16 = None

from dotaclient_tpu.protos import dota_pb2 as pb


def _np_dtype(name: str) -> np.dtype:
    if name == "bfloat16":
        if _BFLOAT16 is None:
            raise ValueError("bfloat16 payload but ml_dtypes unavailable")
        return _BFLOAT16
    return np.dtype(name)


def _dtype_name(dtype: np.dtype) -> str:
    if _BFLOAT16 is not None and dtype == _BFLOAT16:
        return "bfloat16"
    return dtype.name


# -- wire-frame integrity (ISSUE 4) ------------------------------------------
#
# Every rollout/weights frame on the socket and shm lanes carries a 4-byte
# CRC32 trailer so both readers can drop (and count) corrupt frames instead
# of feeding garbage to the decoder or crashing the reader thread. Computing
# byte-serial zlib CRC over large frames would dominate the zero-copy shm
# drain (~1 GiB/s vs the ~8 GiB/s ring memcpy on this host), so frames
# larger than _CRC_FOLD_THRESHOLD are first folded to a 64-bit digest with a
# vectorized XOR over 8-byte lanes (memory-bandwidth speed, measured ~11
# GiB/s even unaligned) and the CRC32 covers (digest || unaligned tail).
# Detection: any single-bit flip, any torn/partial write, and any burst
# shorter than 8 bytes changes the digest; the only blind spot is a
# corruption pattern that repeats identically at the same lane offset in an
# even number of words — vanishingly unlikely for real wire/DMA faults.
# Small frames (heartbeats, control, short rollouts) get plain CRC32.

FRAME_CRC = zlib.crc32  # exposed for tests asserting the small-frame path
_CRC_FOLD_THRESHOLD = 4096
CRC_SIZE = 4


def frame_crc32(payload) -> int:
    """32-bit integrity trailer for one wire frame (bytes-like, zero-copy:
    memoryview slices fold in place)."""
    n = len(payload)
    if n <= _CRC_FOLD_THRESHOLD:
        return zlib.crc32(payload) & 0xFFFFFFFF
    m = n & ~7
    fold = int(
        np.bitwise_xor.reduce(np.frombuffer(payload, "<u8", count=m >> 3))
    )
    c = zlib.crc32(fold.to_bytes(8, "little"), n & 0xFFFFFFFF)
    if m != n:
        c = zlib.crc32(payload[m:], c)
    return c & 0xFFFFFFFF


def flatten_tree(tree: Any, prefix: str = "") -> Dict[str, np.ndarray]:
    """Flatten a nested dict/tuple pytree of arrays to slash-joined names."""
    out: Dict[str, np.ndarray] = {}
    if isinstance(tree, Mapping):
        items = tree.items()
    elif isinstance(tree, (tuple, list)):
        items = ((str(i), v) for i, v in enumerate(tree))
    else:
        # array-likes (numpy AND device arrays) pass through untouched:
        # np.asarray on a device array is a per-leaf host↔device sync —
        # encode_weights batches its fetch over the whole tree instead
        # (ISSUE 5); plain scalars/lists still materialize here
        out[prefix.rstrip("/")] = (
            tree
            if hasattr(tree, "dtype") and hasattr(tree, "shape")
            else np.asarray(tree)
        )
        return out
    for k, v in items:
        out.update(flatten_tree(v, f"{prefix}{k}/"))
    return out


def unflatten_tree(flat: Mapping[str, np.ndarray]) -> Dict[str, Any]:
    """Inverse of :func:`flatten_tree` (all-numeric levels become tuples)."""
    nested: Dict[str, Any] = {}
    for name, value in flat.items():
        parts = name.split("/")
        node = nested
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value

    def fix(node):
        if not isinstance(node, dict):
            return node
        # tuple levels are exactly what flatten_tree emits: UNPADDED
        # indices 0..n-1. Zero-padded digit keys ("00", "01" — e.g. the
        # outcome plane's histogram bucket names riding a fleet snapshot)
        # are ordinary dict keys, not tuple indices; treating them as
        # indices KeyError'd the whole decode (ISSUE 15 bugfix sweep).
        if node and all(
            k.isdigit() and str(int(k)) == k for k in node
        ) and set(node) == {str(i) for i in range(len(node))}:
            return tuple(fix(node[str(i)]) for i in range(len(node)))
        return {k: fix(v) for k, v in node.items()}

    return fix(nested)


# -- rollout wire narrowing (ISSUE 7) -----------------------------------------
#
# The experience stream is the dominant byte flow at scale: every actor ships
# one encoded chunk per finished lane, and PR 3's bf16 weights discipline left
# rollout payloads full-width f32. ``TransportConfig.rollout_wire_dtype``
# extends the same in-band ``__wire_cast__`` marker discipline to rollouts:
# f32 observation/feature leaves narrow to bf16 at encode, bounded
# integer-like leaves (action indices, hero ids — the producer's config
# bounds their range) narrow to int8/int16 where the cast is exact, and the
# marker entry names exactly what was narrowed (``name=orig_dtype`` lines).
# Decode keeps the narrow dtypes by default — the trajectory buffer stores
# them narrow and upcasts on-device at consume time — and ``upcast=True``
# restores the original dtypes on host (bf16→f32 and int8→int32 are exact,
# so the restored batch is bit-identical to what an f32 wire would have
# carried for bf16-representable inputs).
#
# Precision-critical leaves are PINNED f32 by the allowlist below and cross
# the wire byte-identical: behavior_logp feeds the PPO importance ratio
# exp(logp - behavior_logp) where bf16's 8 mantissa bits would inject
# O(0.4%) multiplicative noise into every surrogate term; rewards/values
# accumulate over the GAE scan (quantization noise compounds across T);
# dones gates the recursion; the LSTM initial carries (carry0/*) seed the
# whole sequence forward.

ROLLOUT_WIRE_DTYPES = ("float32", "bfloat16")
_ROLLOUT_PINNED_NAMES = frozenset(
    {"behavior_logp", "rewards", "dones", "values"}
)
_ROLLOUT_PINNED_PREFIXES = ("carry0/",)


def rollout_leaf_pinned(name: str) -> bool:
    """True iff this rollout leaf must cross the wire at full width."""
    return name in _ROLLOUT_PINNED_NAMES or name.startswith(
        _ROLLOUT_PINNED_PREFIXES
    )


def rollout_int_bounds(config) -> Dict[str, int]:
    """Max values the producer's config guarantees for integer-like rollout
    leaves — the input that licenses exact int8/int16 narrowing. Computed
    from the SAME RunConfig on both ends (actor encode, learner buffer
    template), so the dtypes agree wherever the configs do (the buffer's
    skew check already requires that)."""
    bounds = {
        f"actions/{head}": size - 1
        for head, size in config.actions.head_sizes.items()
    }
    bounds["obs/hero_id"] = config.model.n_hero_ids - 1
    # Unit handles are sim-assigned identities: the vectorized/device sims
    # use slot permutations (≤ max_units), the scalar sim increments per
    # spawn (~hundreds over a 600 s game). int16 is exact for both with
    # orders of magnitude of headroom, and the encode path VERIFIES the
    # range before casting (a handle source that ever outgrew the bound
    # fails loudly instead of wrapping).
    bounds["obs/unit_handles"] = np.iinfo(np.int16).max
    return bounds


def decode_drained_payloads(
    payloads, tel, totals: List[int]
) -> "Tuple[list, int]":
    """Decode a transport drain's wire payloads with the SHARED wire/raw
    byte accounting (ISSUE 7) — the one copy of the accounting both the
    socket and shm consume paths run, so the ``--require-wire`` telemetry
    can never diverge between lanes. ``totals`` is the server's mutable
    ``[wire_total, raw_total]`` pair (updated in place). Returns
    ``(decoded (meta, arrays) pairs, malformed-payload count)`` —
    malformed payloads (version-skewed actors, port scanners) are counted
    and dropped, the disposable-actor failure model (SURVEY.md §5.3).

    Items may be bare payloads or ``(recv_ts, payload)`` pairs — both
    transports ship the pair (ISSUE 12: the receive timestamp is the
    ``recv`` trace hop; receive and CRC verify share it, both lanes
    verify in the same pass). Trace stamping runs ONLY when this process
    has a tracer configured — an untracing learner pays one pointer test
    per drain."""
    from dotaclient_tpu.utils import tracing

    tracer = tracing.get()
    out = []
    bad = 0
    wire = raw = 0
    for item in payloads:
        recv_ts, p = item if isinstance(item, tuple) else (None, item)
        try:
            meta, arrays = decode_rollout_bytes(p)
        except Exception:
            bad += 1
            continue
        if tracer is not None and "trace_blob" in meta:
            tracing.stamp_wire_hops(meta, recv_ts)
        # actual bytes consumed vs what the same payloads would have cost
        # full-width — the decoder computed both from the in-band cast
        # marker (host ints only)
        wire += meta.get("wire_bytes", len(p))
        raw += meta.get("raw_bytes", len(p))
        out.append((meta, arrays))
    if out:
        totals[0] += wire
        totals[1] += raw
        tel.counter("transport/rollout_bytes_total").inc(wire)
        tel.counter("transport/rollout_raw_bytes_total").inc(raw)
        if totals[0]:   # zero-length payloads leave the gauge at its floor
            tel.gauge("transport/rollout_compression_ratio").set(
                totals[1] / totals[0]
            )
    return out, bad


def rollout_wire_kwargs(config) -> Dict[str, Any]:
    """The encode-call kwargs this config's rollout wire needs — ``{}``
    for a full-width wire. The ONE derivation every encoder shares
    (actor pools, bench): a change to the encode contract (a new bound
    source, say) lands here once instead of drifting across hand-rolled
    copies."""
    if config.transport.rollout_wire_dtype == "float32":
        return {}
    return dict(
        wire_dtype=config.transport.rollout_wire_dtype,
        int_bounds=rollout_int_bounds(config),
    )


def rollout_cast_plan(
    specs: Mapping[str, Any],
    wire_dtype: str,
    int_bounds: "Mapping[str, int] | None" = None,
) -> Dict[str, np.dtype]:
    """``leaf name → narrow dtype`` for the leaves that change on the wire.

    ``specs`` maps flat leaf names to dtypes (anything ``np.dtype``
    accepts). Only f32 leaves off the pinned allowlist narrow to bf16;
    signed-integer leaves narrow to int8/int16 only when ``int_bounds``
    names them with a config-guaranteed max value that fits — exact by
    construction, never value-sniffed (a value-dependent plan would make
    one actor's chunks dtype-unstable and trip the buffer's skew check).
    """
    if wire_dtype not in ROLLOUT_WIRE_DTYPES:
        raise ValueError(
            f"unknown rollout_wire_dtype {wire_dtype!r} "
            f"(expected one of {ROLLOUT_WIRE_DTYPES})"
        )
    if wire_dtype == "float32":
        return {}
    if _BFLOAT16 is None:
        raise ValueError(
            "rollout_wire_dtype=bfloat16 but ml_dtypes unavailable"
        )
    plan: Dict[str, np.dtype] = {}
    for name, dtype in specs.items():
        dtype = np.dtype(dtype)
        if rollout_leaf_pinned(name):
            continue
        if dtype == np.float32:
            plan[name] = _BFLOAT16
        elif dtype.kind == "i" and int_bounds and name in int_bounds:
            bound = int(int_bounds[name])
            if 0 <= bound <= np.iinfo(np.int8).max and dtype.itemsize > 1:
                plan[name] = np.dtype(np.int8)
            elif 0 <= bound <= np.iinfo(np.int16).max and dtype.itemsize > 2:
                plan[name] = np.dtype(np.int16)
    return plan


def apply_cast_plan(
    flat: Mapping[str, Any], plan: "Mapping[str, np.dtype]"
) -> Dict[str, Any]:
    """Apply a :func:`rollout_cast_plan` to a flat leaf dict — the ONE
    place the cast lands. The host encode path, the buffer's narrow
    template, and the device collect program all route through here, so a
    new narrowed kind changes dtype in lockstep at every site (three
    hand-rolled copies would let the actor, ring, and wire silently
    disagree and trip the buffer's skew check). Works on numpy arrays and
    jax tracers alike (both carry ``astype``)."""
    return {
        n: (a.astype(plan[n]) if n in plan else a) for n, a in flat.items()
    }


_CAST_PLAN_CACHE: Dict[tuple, tuple] = {}


def _narrow_rollout_flat(
    flat: Dict[str, Any],
    wire_dtype: str,
    int_bounds: "Mapping[str, int] | None",
) -> "Tuple[Dict[str, Any], bytes | None]":
    """Apply the cast plan to a flat leaf dict; returns ``(flat', marker
    blob)`` where the blob is the newline-joined ``name=orig_dtype`` record
    the decoder needs to restore the original dtypes (None when nothing
    narrowed — an f32 wire carries no marker).

    The plan and marker are pure functions of (leaf names, dtypes,
    wire_dtype, bounds) and rollout structure is fixed across an actor's
    lifetime (the ``_SPEC_CACHE`` premise), so both are memoized — the
    per-chunk ship path pays only the int range verification and the
    casts themselves."""
    if wire_dtype == "float32":
        # feature off (the default): skip even the memo-key build — this
        # is every actor's per-chunk ship path
        return flat, None
    key = (
        tuple((n, _dtype_name(np.dtype(a.dtype))) for n, a in flat.items()),
        wire_dtype,
        tuple(sorted(int_bounds.items())) if int_bounds else None,
    )
    cached = _CAST_PLAN_CACHE.get(key)
    if cached is None:
        plan = rollout_cast_plan(
            {n: a.dtype for n, a in flat.items()}, wire_dtype, int_bounds
        )
        marker = (
            "\n".join(
                f"{name}={_dtype_name(np.dtype(flat[name].dtype))}"
                for name in plan
            ).encode()
            if plan
            else None
        )
        _CAST_PLAN_CACHE[key] = cached = (plan, marker)
    plan, marker = cached
    if not plan:
        return flat, None
    for name, narrow in plan.items():
        arr = flat[name]
        if np.dtype(narrow).kind == "i" and isinstance(arr, np.ndarray):
            # exactness guard: the int bound is a config PROMISE — verify
            # it on the host path before a silent wrap could corrupt the
            # stream (the device path casts in-graph and relies on the
            # sim's by-construction bounds)
            info = np.iinfo(narrow)
            if arr.size and (
                arr.min() < info.min or arr.max() > info.max
            ):
                raise ValueError(
                    f"rollout leaf {name!r} exceeds its declared int bound "
                    f"({info.max}): observed range "
                    f"[{arr.min()}, {arr.max()}] does not fit {info.dtype} "
                    f"— fix rollout_int_bounds or widen the cast"
                )
    return apply_cast_plan(flat, plan), marker


def _parse_cast_marker(blob: bytes) -> Dict[str, str]:
    """Marker blob → ``{leaf name: original dtype name}``."""
    cast: Dict[str, str] = {}
    for line in blob.decode().split("\n"):
        if not line:
            continue
        name, _, orig = line.partition("=")
        cast[name] = orig
    return cast


def _upcast_flat(
    flat: Dict[str, np.ndarray], cast: Mapping[str, str]
) -> Dict[str, np.ndarray]:
    """Restore narrowed leaves to their original dtypes (exact: every bf16
    value is representable in f32, every int8/int16 in int32)."""
    for name, orig in cast.items():
        arr = flat.get(name)
        if arr is not None:
            flat[name] = arr.astype(_np_dtype(orig))
    return flat


def tensor_to_proto(arr: np.ndarray) -> pb.TensorProto:
    arr = np.ascontiguousarray(arr)
    return pb.TensorProto(
        shape=list(arr.shape), dtype=_dtype_name(arr.dtype), data=arr.tobytes()
    )


def proto_to_tensor(t: pb.TensorProto) -> np.ndarray:
    arr = np.frombuffer(t.data, dtype=_np_dtype(t.dtype))
    return arr.reshape(tuple(t.shape)).copy()


def encode_rollout(
    arrays: Any,
    model_version: int,
    env_id: int,
    rollout_id: int,
    length: int,
    total_reward: float,
    wire_dtype: str = "float32",
    int_bounds: "Mapping[str, int] | None" = None,
    trace: "bytes | None" = None,
) -> pb.Rollout:
    """Serialize one rollout's pytree of host arrays.

    ``wire_dtype="bfloat16"`` narrows the experience leaves per
    :func:`rollout_cast_plan` (pinned leaves stay byte-identical f32) and
    records the casts in the in-band ``__wire_cast__`` marker entry.
    ``trace`` (ISSUE 12) is a pipeline-tracing record blob
    (``utils/tracing.record_to_blob``) that rides as one more in-band
    marker entry (``__trace__``) on sampled chunks."""
    r = pb.Rollout(
        model_version=model_version,
        env_id=env_id,
        rollout_id=rollout_id,
        length=length,
        total_reward=total_reward,
    )
    flat = flatten_tree(arrays)
    flat, marker = _narrow_rollout_flat(flat, wire_dtype, int_bounds)
    n_entries = (
        len(flat)
        + (1 if marker is not None else 0)
        + (1 if trace is not None else 0)
    )
    if n_entries > _MAX_TENSORS:
        _raise_too_many_tensors(n_entries, "encode")
    for name, arr in flat.items():
        r.arrays[name].CopyFrom(tensor_to_proto(arr))
    if marker is not None:
        r.arrays[_WIRE_CAST_MARKER].CopyFrom(
            pb.TensorProto(shape=[len(marker)], dtype="marker", data=marker)
        )
    if trace is not None:
        r.arrays[_TRACE_MARKER].CopyFrom(
            pb.TensorProto(shape=[len(trace)], dtype="marker", data=trace)
        )
    return r


def decode_rollout(
    r: pb.Rollout, upcast: bool = False
) -> Tuple[Dict[str, Any], Any]:
    """Deserialize → (meta dict, pytree of arrays).

    Narrowed leaves come back in their WIRE dtypes by default (the
    trajectory buffer stores them narrow and upcasts on-device at consume
    time); the marker record lands in ``meta["wire_cast"]``. ``upcast=True``
    restores the original dtypes on host (tests, non-buffer consumers)."""
    meta = {
        "model_version": r.model_version,
        "env_id": r.env_id,
        "rollout_id": r.rollout_id,
        "length": r.length,
        "total_reward": r.total_reward,
    }
    flat = {}
    cast: Dict[str, str] = {}
    for name, t in r.arrays.items():
        if name == _WIRE_CAST_MARKER:
            cast = _parse_cast_marker(t.data)
            continue
        if name == _TRACE_MARKER:
            meta["trace_blob"] = t.data
            continue
        flat[name] = proto_to_tensor(t)
    if cast:
        meta["wire_cast"] = cast
        if upcast:
            flat = _upcast_flat(flat, cast)
    return meta, unflatten_tree(flat)


_MAX_TENSORS = 64
# structured view over the C TensorEntry array — field access is vectorized
# numpy instead of per-attribute ctypes getattr
_ENTRY_DTYPE = np.dtype(
    [
        ("name_off", "<u4"), ("name_len", "<u4"),
        ("dtype_off", "<u4"), ("dtype_len", "<u4"),
        ("data_off", "<u4"), ("data_len", "<u4"),
        ("shape", "<i4", (8,)), ("ndim", "<i4"),
    ]
)
# encoder-side mirror of the C EncodeTensor struct (align=True matches the
# C++ compiler's layout; asserted against ctypes.sizeof at first use)
_ENC_DTYPE = np.dtype(
    [
        ("name_off", "<u4"), ("name_len", "<u4"),
        ("dtype_off", "<u4"), ("dtype_len", "<u4"),
        ("data_ptr", "<u8"), ("data_len", "<u8"),
        ("shape", "<i4", (8,)), ("ndim", "<i4"),
    ],
    align=True,
)
_DTYPE_CACHE: Dict[bytes, np.dtype] = {}
_SPEC_CACHE: Dict[tuple, tuple] = {}
_tls = threading.local()


def _entry_buffer():
    buf = getattr(_tls, "entries", None)
    if buf is None:
        buf = np.zeros(_MAX_TENSORS, _ENTRY_DTYPE)
        _tls.entries = buf
    return buf


def _raise_too_many_tensors(n_entries: int, side: str) -> None:
    raise ValueError(
        f"rollout payload carries {n_entries} tensor entries at {side}; the "
        f"native wire codec's entry table holds at most {_MAX_TENSORS} — a "
        f"silent fallback here would walk a truncated entry buffer (decode) "
        f"or pin the learner to the slow proto parser forever (encode). "
        f"Flatten fewer leaves or raise _MAX_TENSORS in "
        f"transport/serialize.py"
    )


def decode_rollout_bytes(
    payload: bytes, native: bool = True, upcast: bool = False
) -> Tuple[Dict[str, Any], Any]:
    """Decode a serialized ``Rollout`` from raw bytes.

    The learner-ingest fast path: with the native library built (see
    ``dotaclient_tpu.native``), one C pass locates every tensor and the
    arrays are materialized as zero-copy ``np.frombuffer`` views into
    ``payload``; otherwise falls back to python-protobuf. ``payload`` may
    be bytes OR a read-only buffer (the shm lane hands memoryview slices
    of its drain snapshots — no copy on the way in either). Views are
    read-only — callers that mutate must copy (the trajectory buffer only
    uploads, so the hot path never does).

    Wire-narrowed payloads (``rollout_wire_dtype``, ISSUE 7) decode to
    their NARROW dtypes by default — the trajectory buffer keeps them
    narrow and the upcast happens on-device at consume time. The marker
    record lands in ``meta["wire_cast"]`` and the byte accounting in
    ``meta["wire_bytes"]`` / ``meta["raw_bytes"]`` (what the same payload
    would have cost full-width — the transports' compression telemetry).
    ``upcast=True`` restores original dtypes on host (a copy; tests and
    non-buffer consumers).

    A payload with more tensor entries than the native table holds raises
    ``ValueError`` naming the count (the transports' consume paths count
    it as a bad payload) — never a silent fall-through that would leave a
    truncated entry walk or a permanent slow-path downgrade.
    """
    if not isinstance(payload, (bytes, bytearray, memoryview)):
        payload = bytes(payload)  # exotic bytes-like in
    if native:
        from dotaclient_tpu.native.build import (
            RolloutHeader,
            TensorEntry,
            load_library,
        )

        lib = load_library()
        if lib is not None:
            if isinstance(payload, bytes):
                src = payload          # c_void_p accepts bytes directly
            else:
                # raw pointer into the buffer — kept alive by `payload`
                src = ctypes.c_void_p(
                    np.frombuffer(payload, np.uint8).ctypes.data
                )
            hdr = RolloutHeader()
            entries = _entry_buffer()
            n = lib.dota_decode_rollout(
                src, len(payload), ctypes.byref(hdr),
                entries.ctypes.data_as(ctypes.POINTER(TensorEntry)),
                _MAX_TENSORS,
            )
            if n == -2:
                # entry-table overflow: loud, with the real count (the
                # payload is well-formed proto — count it; if it is NOT
                # parseable either, fall through to the proto path's own
                # parse error)
                try:
                    r = pb.Rollout()
                    r.ParseFromString(bytes(payload))
                except Exception:
                    pass
                else:
                    _raise_too_many_tensors(len(r.arrays), "decode")
            if n >= 0:
                flat = {}
                cast: Dict[str, str] = {}
                trace_blob: "bytes | None" = None
                # one C-level conversion: rows become plain python tuples
                for (
                    name_off, name_len, dtype_off, dtype_len,
                    data_off, data_len, shape, ndim,
                ) in entries[:n].tolist():
                    name = bytes(payload[name_off:name_off + name_len]).decode()
                    if name == _WIRE_CAST_MARKER:
                        cast = _parse_cast_marker(
                            bytes(payload[data_off:data_off + data_len])
                        )
                        continue
                    if name == _TRACE_MARKER:
                        trace_blob = bytes(
                            payload[data_off:data_off + data_len]
                        )
                        continue
                    dkey = bytes(payload[dtype_off:dtype_off + dtype_len])
                    dtype = _DTYPE_CACHE.get(dkey)
                    if dtype is None:
                        dtype = _np_dtype(dkey.decode())
                        _DTYPE_CACHE[dkey] = dtype
                    count = data_len // dtype.itemsize
                    arr = np.frombuffer(
                        payload, dtype=dtype, count=count, offset=data_off
                    )
                    if ndim != 1 or shape[0] != count:
                        arr = arr.reshape(shape[:ndim])
                    flat[name] = arr
                meta = {
                    "model_version": hdr.model_version,
                    "env_id": hdr.env_id,
                    "rollout_id": hdr.rollout_id,
                    "length": hdr.length,
                    "total_reward": hdr.total_reward,
                }
                if trace_blob is not None:
                    meta["trace_blob"] = trace_blob
                if cast:
                    # narrowed payloads carry their byte accounting; plain
                    # f32 frames keep the historical meta shape exactly
                    # (consume telemetry falls back to len(payload))
                    meta["wire_cast"] = cast
                    _attach_wire_accounting(meta, flat, cast, len(payload))
                    if upcast:
                        flat = _upcast_flat(flat, cast)
                return meta, unflatten_tree(flat)
            # n == -1 (malformed): fall through to the proto parser
    r = pb.Rollout()
    r.ParseFromString(
        payload if isinstance(payload, bytes) else bytes(payload)
    )
    if len(r.arrays) > _MAX_TENSORS:
        _raise_too_many_tensors(len(r.arrays), "decode")
    meta, arrays = decode_rollout(r, upcast=upcast)
    if meta.get("wire_cast"):
        meta["wire_bytes"] = len(payload)
        raw = len(payload) - _wire_cast_overhead(meta["wire_cast"])
        for name, orig in meta["wire_cast"].items():
            # `in` before indexing: protobuf map __getitem__ auto-inserts
            if name in r.arrays:
                t = r.arrays[name]
                raw += _leaf_raw_delta(
                    name, tuple(t.shape), _np_dtype(t.dtype), orig
                )
        meta["raw_bytes"] = raw
    return meta, arrays


def _varint_size(n: int) -> int:
    """Bytes a proto3 varint of ``n`` occupies."""
    size = 1
    while n > 0x7F:
        n >>= 7
        size += 1
    return size


def _wire_cast_overhead(cast: Mapping[str, str]) -> int:
    """Exact wire footprint of the ``__wire_cast__`` marker map entry —
    an f32 payload carries NO marker, so ``raw_bytes`` must exclude it or
    the compression ratio overstates the saving (~0.4% on the default
    config). Both codecs emit canonical proto3 (asserted equal in tests),
    so the size is computable from the blob length alone: TensorProto
    {shape=[blob_len] packed, dtype="marker", data=blob} wrapped in a map
    entry wrapped in Rollout field 6 (all tags are one byte)."""
    blob_len = sum(
        len(n) + 1 + len(o) for n, o in cast.items()
    ) + max(0, len(cast) - 1)   # name=orig lines, newline-joined
    packed = _varint_size(blob_len)
    tensor = (
        1 + _varint_size(packed) + packed
        + 1 + 1 + len("marker")
        + 1 + _varint_size(blob_len) + blob_len
    )
    key = len(_WIRE_CAST_MARKER)
    entry = 1 + _varint_size(key) + key + 1 + _varint_size(tensor) + tensor
    return 1 + _varint_size(entry) + entry


def _leaf_raw_delta(
    name: str, shape, narrow: np.dtype, orig_name: str
) -> int:
    """Exact wire-byte difference between this leaf's full-width and
    narrow map entries: the data blob halves, but the dtype STRING also
    changes length ("bfloat16" vs "float32" is +1, "int8" vs "int32" is
    -1) and every length varint can change width — sub-byte effects that
    would otherwise leave raw_bytes a few bytes off per payload."""
    n = 1
    for d in shape:
        n *= int(d)
    packed = sum(_varint_size(int(d)) for d in shape)
    shape_field = (
        (1 + _varint_size(packed) + packed) if len(shape) else 0
    )
    key = len(name)

    def entry_total(dtype_name: str, itemsize: int) -> int:
        dlen = n * itemsize
        ds = len(dtype_name)
        tensor = (
            shape_field
            + 1 + _varint_size(ds) + ds
            + 1 + _varint_size(dlen) + dlen
        )
        e = 1 + _varint_size(key) + key + 1 + _varint_size(tensor) + tensor
        return 1 + _varint_size(e) + e

    return entry_total(orig_name, _np_dtype(orig_name).itemsize) - (
        entry_total(_dtype_name(narrow), narrow.itemsize)
    )


def _attach_wire_accounting(
    meta: Dict[str, Any],
    flat: Mapping[str, np.ndarray],
    cast: Mapping[str, str],
    wire_bytes: int,
) -> None:
    """Record per-payload byte accounting: actual wire bytes and what the
    same payload would have cost full-width — EXACTLY: the marker entry
    exists only on the narrow wire (excluded from ``raw``), and each
    narrowed leaf's framing is re-costed at its original dtype
    (:func:`_leaf_raw_delta`). Pinned by a test asserting raw_bytes
    equals the true f32 encode's length byte-for-byte."""
    meta["wire_bytes"] = wire_bytes
    raw = wire_bytes - _wire_cast_overhead(cast)
    for name, orig in cast.items():
        arr = flat.get(name)
        if arr is not None:
            raw += _leaf_raw_delta(name, arr.shape, arr.dtype, orig)
    meta["raw_bytes"] = raw


def encode_rollout_bytes(
    arrays: Any,
    model_version: int,
    env_id: int,
    rollout_id: int,
    length: int,
    total_reward: float,
    native: bool = True,
    wire_dtype: str = "float32",
    int_bounds: "Mapping[str, int] | None" = None,
    trace: "bytes | None" = None,
) -> "bytes | memoryview":
    """Serialize one rollout straight to wire bytes (bytes-like).

    The actor-ship fast path, mirror of :func:`decode_rollout_bytes`: with
    the native library built, one C pass writes the proto3 wire format
    directly from the numpy buffers (one memcpy per tensor, no
    python-protobuf object tree — the reference paid this cost through
    protobuf's C++ runtime, SURVEY.md §2.2 row 3). Output parses
    identically to ``encode_rollout(...).SerializeToString()``; falls back
    to that when the library is unavailable (or a tensor exceeds 8 dims).

    ``wire_dtype="bfloat16"`` (TransportConfig.rollout_wire_dtype) narrows
    the experience leaves per :func:`rollout_cast_plan` before encoding —
    roughly half the wire bytes per chunk — and ships the ``__wire_cast__``
    marker entry naming exactly what was narrowed. The narrowed arrays ride
    the same ``_SPEC_CACHE`` template path (their dtypes are part of the
    cache key, so f32 and bf16 encodes of the same layout never share a
    template). A rollout with more leaves than the native entry table
    (``_MAX_TENSORS``) raises ``ValueError`` naming the count — encoding
    it would produce payloads the native parser can never decode.
    """
    if native:
        from dotaclient_tpu.native.build import (
            EncodeTensor,
            RolloutHeader,
            load_library,
        )

        lib = load_library()
        if lib is not None and hasattr(lib, "dota_encode_rollout"):
            if _ENC_DTYPE.itemsize != ctypes.sizeof(EncodeTensor):
                # load-bearing ABI check (a bare assert would vanish under
                # python -O and let the C writer read garbage offsets)
                raise ValueError(
                    f"EncodeTensor ABI mismatch: numpy spec row is "
                    f"{_ENC_DTYPE.itemsize} bytes, C struct is "
                    f"{ctypes.sizeof(EncodeTensor)}"
                )
            flat = flatten_tree(arrays)
            flat, marker = _narrow_rollout_flat(flat, wire_dtype, int_bounds)
            n_entries = (
                len(flat)
                + (1 if marker is not None else 0)
                + (1 if trace is not None else 0)
            )
            if n_entries > _MAX_TENSORS:
                _raise_too_many_tensors(n_entries, "encode")
            if all(a.ndim <= 8 for a in flat.values()):
                names = list(flat)
                arrs = [np.ascontiguousarray(a) for a in flat.values()]
                dnames = [_dtype_name(a.dtype) for a in arrs]
                if marker is not None:
                    # the marker rides as one more entry: uint8 blob bytes,
                    # dtype string "marker" (decode intercepts by NAME, so
                    # the string only needs to match the proto path's)
                    names.append(_WIRE_CAST_MARKER)
                    arrs.append(np.frombuffer(marker, np.uint8))
                    dnames.append("marker")
                if trace is not None:
                    # trace blobs are padded to tracing.TRACE_WIRE_LEN, so
                    # the _SPEC_CACHE layout key below stays ONE key per
                    # rollout structure, traced or not
                    names.append(_TRACE_MARKER)
                    arrs.append(np.frombuffer(trace, np.uint8))
                    dnames.append("marker")
                n = len(names)
                # Rollout structure is fixed across an actor's lifetime, so
                # everything but the data pointers — the EncodeTensor table,
                # the names/dtypes blob, the size bound — is cached per
                # (names, dtypes, shapes) key; the steady-state cost per call
                # is one column write plus the C pass. Narrowed layouts get
                # their own key (the dtypes differ), so toggling
                # rollout_wire_dtype can never serve a stale template.
                key = tuple(
                    (name, dname, a.shape)
                    for name, dname, a in zip(names, dnames, arrs)
                )
                cached = _SPEC_CACHE.get(key)
                if cached is None:
                    specs = np.zeros(n, _ENC_DTYPE)
                    pieces = []
                    pos = 0
                    cap = 64
                    for i, (name, dtype_name, shape) in enumerate(key):
                        nb, db = name.encode(), dtype_name.encode()
                        pieces += [nb, db]
                        specs["name_off"][i] = pos
                        specs["name_len"][i] = len(nb)
                        specs["dtype_off"][i] = pos + len(nb)
                        specs["dtype_len"][i] = len(db)
                        pos += len(nb) + len(db)
                        specs["data_len"][i] = arrs[i].nbytes
                        specs["shape"][i, : len(shape)] = shape
                        specs["ndim"][i] = len(shape)
                        cap += arrs[i].nbytes + len(nb) + len(db) + 128
                    cached = (specs, b"".join(pieces), cap)
                    _SPEC_CACHE[key] = cached
                template, strings, cap = cached
                specs = template.copy()  # concurrent encoders don't share
                specs["data_ptr"] = [
                    a.__array_interface__["data"][0] for a in arrs
                ]
                hdr = RolloutHeader(
                    model_version, env_id, rollout_id, length, total_reward
                )
                spec_ptr = specs.ctypes.data_as(ctypes.POINTER(EncodeTensor))
                out = np.empty(cap, np.uint8)
                written = lib.dota_encode_rollout(
                    ctypes.byref(hdr), strings, spec_ptr, n,
                    out.ctypes.data, cap,
                )
                if written > cap:  # estimate too small: size back, retry once
                    out = np.empty(written, np.uint8)
                    written = lib.dota_encode_rollout(
                        ctypes.byref(hdr), strings, spec_ptr, n,
                        out.ctypes.data, written,
                    )
                del arrs  # pinned the numpy buffers across the C calls
                if written >= 0:
                    # bytes-like, not bytes: a second whole-payload memcpy
                    # (`tobytes`) would halve the single-copy win; sockets,
                    # ParseFromString, and len() all take the view directly
                    return out[:written].data
    return encode_rollout(
        arrays, model_version, env_id, rollout_id, length, total_reward,
        wire_dtype=wire_dtype, int_bounds=int_bounds, trace=trace,
    ).SerializeToString()


# In-band wire-narrowing marker (the proto schemas predate wire_dtype and
# protoc is unavailable in this image to extend them): a pseudo-entry in
# the params/arrays map recording exactly what the encoder narrowed.
# Weights fanout: ``data`` lists the leaf names cast f32→bf16,
# newline-joined — decode upcasts ONLY those, so a natively-bf16 param
# (model.param_dtype="bfloat16") is never silently widened. Rollout
# payloads (ISSUE 7): ``data`` lists ``name=orig_dtype`` lines (mixed
# bf16/int8/int16 casts need the original dtype to restore exactly). The
# "/"-free dunder name cannot collide with real leaves (flax param paths
# always nest at least one module level; rollout leaves all nest under
# obs/actions/carry0 or are known scalar-track names).
_WIRE_CAST_MARKER = "__wire_cast__"

# Pipeline-tracing marker (ISSUE 12): the same in-band pseudo-entry
# discipline carries a compact trace record (utils/tracing.py blob —
# origin pid/actor, trace id, weights version at collect, hop
# timestamps) on sampled rollout chunks, every weights-publish frame a
# tracing learner emits, and serve request/reply frames. Decode
# intercepts it by name into ``meta["trace_blob"]`` (rollouts) or via
# :func:`weights_trace` (weights) — it is never a data leaf.
_TRACE_MARKER = "__trace__"


def weights_trace(msg: pb.ModelWeights) -> "bytes | None":
    """The raw trace blob a tracing learner attached to this weights
    frame (None when absent). ``in`` before indexing: protobuf map
    ``__getitem__`` auto-inserts."""
    if _TRACE_MARKER in msg.params:
        return msg.params[_TRACE_MARKER].data
    return None


def encode_weights(
    params: Any, version: int, wire_dtype: str = "float32",
    trace: "bytes | None" = None,
) -> pb.ModelWeights:
    """Serialize a param pytree for the weights fanout.

    ``wire_dtype="bfloat16"`` casts float32 leaves to bf16 at encode —
    half the fanout bytes per publish (TransportConfig.wire_dtype); the
    decode side upcasts exactly those leaves on apply (recorded in an
    in-band marker entry). Non-f32 leaves (int counters, natively-bf16
    params) pass through unchanged in both directions.

    Device-resident params are fetched with ONE batched ``jax.device_get``
    over the whole tree — one host↔device sync per publish instead of one
    per leaf (ISSUE 5); host arrays pass through untouched. The async
    snapshot engine already hands this function host arrays, so its calls
    never sync at all.
    """
    if wire_dtype not in ("float32", "bfloat16"):
        raise ValueError(f"unknown wire_dtype {wire_dtype!r}")
    cast = None
    if wire_dtype == "bfloat16":
        if _BFLOAT16 is None:
            raise ValueError("wire_dtype=bfloat16 but ml_dtypes unavailable")
        cast = _BFLOAT16
    msg = pb.ModelWeights(version=version)
    cast_names = []
    flat = flatten_tree(params)
    if any(not isinstance(a, np.ndarray) for a in flat.values()):
        import jax  # deferred: the codec itself stays importable jax-free

        flat = jax.device_get(flat)  # host-sync-ok: ONE batched fetch per publish
    for name, arr in flat.items():
        a = np.asarray(arr)
        if cast is not None and a.dtype == np.float32:
            a = a.astype(cast)
            cast_names.append(name)
        msg.params[name].CopyFrom(tensor_to_proto(a))
    if cast_names:
        msg.params[_WIRE_CAST_MARKER].CopyFrom(
            pb.TensorProto(dtype="marker", data="\n".join(cast_names).encode())
        )
    if trace is not None:
        # publish-side trace record (ISSUE 12): origin pid + publish hop,
        # so the actor's apply event can attribute fanout latency without
        # any clock handshake beyond the shared epoch alignment
        msg.params[_TRACE_MARKER].CopyFrom(
            pb.TensorProto(dtype="marker", data=trace)
        )
    return msg


def decode_weights(msg: pb.ModelWeights, upcast: bool = True) -> Tuple[int, Any]:
    """Decode a weights fanout message → ``(version, param pytree)``.

    With ``upcast`` (the apply-side default) the leaves the encoder
    narrowed to bf16 come back as float32 — the lossless inverse of the
    ``wire_dtype="bfloat16"`` cast (every bf16 value is exactly
    representable in f32). Leaves that were bf16 BEFORE encode carry no
    marker and keep their dtype. ``upcast=False`` returns the raw wire
    dtypes (tests, inspection)."""
    cast_names = frozenset()
    # `in` before indexing: protobuf message-map __getitem__ auto-inserts
    if _WIRE_CAST_MARKER in msg.params:
        cast_names = frozenset(
            msg.params[_WIRE_CAST_MARKER].data.decode().split("\n")
        )
    flat = {}
    for name, t in msg.params.items():
        if name in (_WIRE_CAST_MARKER, _TRACE_MARKER):
            continue
        arr = proto_to_tensor(t)
        if (
            upcast
            and name in cast_names
            and _BFLOAT16 is not None
            and arr.dtype == _BFLOAT16
        ):
            arr = arr.astype(np.float32)
        flat[name] = arr
    return msg.version, unflatten_tree(flat)
