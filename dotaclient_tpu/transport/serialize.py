"""Rollout wire-format codec: host pytrees ↔ ``Rollout`` protos.

The reference shipped experience as protobuf payloads over RabbitMQ but left
the payload schema implicit (SURVEY.md §2.1 "Transport", §7 step 1); here it
is first-party: a flat ``name → TensorProto`` map whose names are the
slash-joined paths of the training-batch pytree (``obs/units``,
``actions/move_x``, ``carry0/h``, ...). The same codec serves the learner→
actor weights direction (``ModelWeights``).

Decode is the hot ingestion path; ``decode_rollout_bytes`` uses the
first-party C++ wire parser (``dotaclient_tpu/native/rollout_codec.cc``,
single pass, zero-copy numpy views) when the native library is built, with
a pure-protobuf fallback otherwise (SURVEY.md §2.2 row 3).
"""

from __future__ import annotations

import ctypes
import threading
import zlib
from typing import Any, Dict, Mapping, Tuple

import numpy as np

try:  # bfloat16 arrays cross the wire when actors run bf16 inference
    import ml_dtypes

    _BFLOAT16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    _BFLOAT16 = None

from dotaclient_tpu.protos import dota_pb2 as pb


def _np_dtype(name: str) -> np.dtype:
    if name == "bfloat16":
        if _BFLOAT16 is None:
            raise ValueError("bfloat16 payload but ml_dtypes unavailable")
        return _BFLOAT16
    return np.dtype(name)


def _dtype_name(dtype: np.dtype) -> str:
    if _BFLOAT16 is not None and dtype == _BFLOAT16:
        return "bfloat16"
    return dtype.name


# -- wire-frame integrity (ISSUE 4) ------------------------------------------
#
# Every rollout/weights frame on the socket and shm lanes carries a 4-byte
# CRC32 trailer so both readers can drop (and count) corrupt frames instead
# of feeding garbage to the decoder or crashing the reader thread. Computing
# byte-serial zlib CRC over large frames would dominate the zero-copy shm
# drain (~1 GiB/s vs the ~8 GiB/s ring memcpy on this host), so frames
# larger than _CRC_FOLD_THRESHOLD are first folded to a 64-bit digest with a
# vectorized XOR over 8-byte lanes (memory-bandwidth speed, measured ~11
# GiB/s even unaligned) and the CRC32 covers (digest || unaligned tail).
# Detection: any single-bit flip, any torn/partial write, and any burst
# shorter than 8 bytes changes the digest; the only blind spot is a
# corruption pattern that repeats identically at the same lane offset in an
# even number of words — vanishingly unlikely for real wire/DMA faults.
# Small frames (heartbeats, control, short rollouts) get plain CRC32.

FRAME_CRC = zlib.crc32  # exposed for tests asserting the small-frame path
_CRC_FOLD_THRESHOLD = 4096
CRC_SIZE = 4


def frame_crc32(payload) -> int:
    """32-bit integrity trailer for one wire frame (bytes-like, zero-copy:
    memoryview slices fold in place)."""
    n = len(payload)
    if n <= _CRC_FOLD_THRESHOLD:
        return zlib.crc32(payload) & 0xFFFFFFFF
    m = n & ~7
    fold = int(
        np.bitwise_xor.reduce(np.frombuffer(payload, "<u8", count=m >> 3))
    )
    c = zlib.crc32(fold.to_bytes(8, "little"), n & 0xFFFFFFFF)
    if m != n:
        c = zlib.crc32(payload[m:], c)
    return c & 0xFFFFFFFF


def flatten_tree(tree: Any, prefix: str = "") -> Dict[str, np.ndarray]:
    """Flatten a nested dict/tuple pytree of arrays to slash-joined names."""
    out: Dict[str, np.ndarray] = {}
    if isinstance(tree, Mapping):
        items = tree.items()
    elif isinstance(tree, (tuple, list)):
        items = ((str(i), v) for i, v in enumerate(tree))
    else:
        # array-likes (numpy AND device arrays) pass through untouched:
        # np.asarray on a device array is a per-leaf host↔device sync —
        # encode_weights batches its fetch over the whole tree instead
        # (ISSUE 5); plain scalars/lists still materialize here
        out[prefix.rstrip("/")] = (
            tree
            if hasattr(tree, "dtype") and hasattr(tree, "shape")
            else np.asarray(tree)
        )
        return out
    for k, v in items:
        out.update(flatten_tree(v, f"{prefix}{k}/"))
    return out


def unflatten_tree(flat: Mapping[str, np.ndarray]) -> Dict[str, Any]:
    """Inverse of :func:`flatten_tree` (all-numeric levels become tuples)."""
    nested: Dict[str, Any] = {}
    for name, value in flat.items():
        parts = name.split("/")
        node = nested
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value

    def fix(node):
        if not isinstance(node, dict):
            return node
        if node and all(k.isdigit() for k in node):
            return tuple(fix(node[str(i)]) for i in range(len(node)))
        return {k: fix(v) for k, v in node.items()}

    return fix(nested)


def tensor_to_proto(arr: np.ndarray) -> pb.TensorProto:
    arr = np.ascontiguousarray(arr)
    return pb.TensorProto(
        shape=list(arr.shape), dtype=_dtype_name(arr.dtype), data=arr.tobytes()
    )


def proto_to_tensor(t: pb.TensorProto) -> np.ndarray:
    arr = np.frombuffer(t.data, dtype=_np_dtype(t.dtype))
    return arr.reshape(tuple(t.shape)).copy()


def encode_rollout(
    arrays: Any,
    model_version: int,
    env_id: int,
    rollout_id: int,
    length: int,
    total_reward: float,
) -> pb.Rollout:
    """Serialize one rollout's pytree of host arrays."""
    r = pb.Rollout(
        model_version=model_version,
        env_id=env_id,
        rollout_id=rollout_id,
        length=length,
        total_reward=total_reward,
    )
    for name, arr in flatten_tree(arrays).items():
        r.arrays[name].CopyFrom(tensor_to_proto(arr))
    return r


def decode_rollout(r: pb.Rollout) -> Tuple[Dict[str, Any], Any]:
    """Deserialize → (meta dict, pytree of arrays)."""
    meta = {
        "model_version": r.model_version,
        "env_id": r.env_id,
        "rollout_id": r.rollout_id,
        "length": r.length,
        "total_reward": r.total_reward,
    }
    flat = {name: proto_to_tensor(t) for name, t in r.arrays.items()}
    return meta, unflatten_tree(flat)


_MAX_TENSORS = 64
# structured view over the C TensorEntry array — field access is vectorized
# numpy instead of per-attribute ctypes getattr
_ENTRY_DTYPE = np.dtype(
    [
        ("name_off", "<u4"), ("name_len", "<u4"),
        ("dtype_off", "<u4"), ("dtype_len", "<u4"),
        ("data_off", "<u4"), ("data_len", "<u4"),
        ("shape", "<i4", (8,)), ("ndim", "<i4"),
    ]
)
# encoder-side mirror of the C EncodeTensor struct (align=True matches the
# C++ compiler's layout; asserted against ctypes.sizeof at first use)
_ENC_DTYPE = np.dtype(
    [
        ("name_off", "<u4"), ("name_len", "<u4"),
        ("dtype_off", "<u4"), ("dtype_len", "<u4"),
        ("data_ptr", "<u8"), ("data_len", "<u8"),
        ("shape", "<i4", (8,)), ("ndim", "<i4"),
    ],
    align=True,
)
_DTYPE_CACHE: Dict[bytes, np.dtype] = {}
_SPEC_CACHE: Dict[tuple, tuple] = {}
_tls = threading.local()


def _entry_buffer():
    buf = getattr(_tls, "entries", None)
    if buf is None:
        buf = np.zeros(_MAX_TENSORS, _ENTRY_DTYPE)
        _tls.entries = buf
    return buf


def decode_rollout_bytes(
    payload: bytes, native: bool = True
) -> Tuple[Dict[str, Any], Any]:
    """Decode a serialized ``Rollout`` from raw bytes.

    The learner-ingest fast path: with the native library built (see
    ``dotaclient_tpu.native``), one C pass locates every tensor and the
    arrays are materialized as zero-copy ``np.frombuffer`` views into
    ``payload``; otherwise falls back to python-protobuf. ``payload`` may
    be bytes OR a read-only buffer (the shm lane hands memoryview slices
    of its drain snapshots — no copy on the way in either). Views are
    read-only — callers that mutate must copy (the trajectory buffer only
    uploads, so the hot path never does).
    """
    if not isinstance(payload, (bytes, bytearray, memoryview)):
        payload = bytes(payload)  # exotic bytes-like in
    if native:
        from dotaclient_tpu.native.build import (
            RolloutHeader,
            TensorEntry,
            load_library,
        )

        lib = load_library()
        if lib is not None:
            if isinstance(payload, bytes):
                src = payload          # c_void_p accepts bytes directly
            else:
                # raw pointer into the buffer — kept alive by `payload`
                src = ctypes.c_void_p(
                    np.frombuffer(payload, np.uint8).ctypes.data
                )
            hdr = RolloutHeader()
            entries = _entry_buffer()
            n = lib.dota_decode_rollout(
                src, len(payload), ctypes.byref(hdr),
                entries.ctypes.data_as(ctypes.POINTER(TensorEntry)),
                _MAX_TENSORS,
            )
            if n >= 0:
                flat = {}
                # one C-level conversion: rows become plain python tuples
                for (
                    name_off, name_len, dtype_off, dtype_len,
                    data_off, data_len, shape, ndim,
                ) in entries[:n].tolist():
                    name = bytes(payload[name_off:name_off + name_len]).decode()
                    dkey = bytes(payload[dtype_off:dtype_off + dtype_len])
                    dtype = _DTYPE_CACHE.get(dkey)
                    if dtype is None:
                        dtype = _np_dtype(dkey.decode())
                        _DTYPE_CACHE[dkey] = dtype
                    count = data_len // dtype.itemsize
                    arr = np.frombuffer(
                        payload, dtype=dtype, count=count, offset=data_off
                    )
                    if ndim != 1 or shape[0] != count:
                        arr = arr.reshape(shape[:ndim])
                    flat[name] = arr
                meta = {
                    "model_version": hdr.model_version,
                    "env_id": hdr.env_id,
                    "rollout_id": hdr.rollout_id,
                    "length": hdr.length,
                    "total_reward": hdr.total_reward,
                }
                return meta, unflatten_tree(flat)
            # n == -2 (too many tensors) or malformed: fall through
    r = pb.Rollout()
    r.ParseFromString(
        payload if isinstance(payload, bytes) else bytes(payload)
    )
    return decode_rollout(r)


def encode_rollout_bytes(
    arrays: Any,
    model_version: int,
    env_id: int,
    rollout_id: int,
    length: int,
    total_reward: float,
    native: bool = True,
) -> "bytes | memoryview":
    """Serialize one rollout straight to wire bytes (bytes-like).

    The actor-ship fast path, mirror of :func:`decode_rollout_bytes`: with
    the native library built, one C pass writes the proto3 wire format
    directly from the numpy buffers (one memcpy per tensor, no
    python-protobuf object tree — the reference paid this cost through
    protobuf's C++ runtime, SURVEY.md §2.2 row 3). Output parses
    identically to ``encode_rollout(...).SerializeToString()``; falls back
    to that when the library is unavailable (or a tensor exceeds 8 dims).
    """
    if native:
        from dotaclient_tpu.native.build import (
            EncodeTensor,
            RolloutHeader,
            load_library,
        )

        lib = load_library()
        if lib is not None and hasattr(lib, "dota_encode_rollout"):
            if _ENC_DTYPE.itemsize != ctypes.sizeof(EncodeTensor):
                # load-bearing ABI check (a bare assert would vanish under
                # python -O and let the C writer read garbage offsets)
                raise ValueError(
                    f"EncodeTensor ABI mismatch: numpy spec row is "
                    f"{_ENC_DTYPE.itemsize} bytes, C struct is "
                    f"{ctypes.sizeof(EncodeTensor)}"
                )
            flat = flatten_tree(arrays)
            if all(a.ndim <= 8 for a in flat.values()):
                n = len(flat)
                arrs = [np.ascontiguousarray(a) for a in flat.values()]
                # Rollout structure is fixed across an actor's lifetime, so
                # everything but the data pointers — the EncodeTensor table,
                # the names/dtypes blob, the size bound — is cached per
                # (names, dtypes, shapes) key; the steady-state cost per call
                # is one column write plus the C pass.
                key = tuple(
                    (name, _dtype_name(a.dtype), a.shape)
                    for name, a in zip(flat, arrs)
                )
                cached = _SPEC_CACHE.get(key)
                if cached is None:
                    specs = np.zeros(n, _ENC_DTYPE)
                    pieces = []
                    pos = 0
                    cap = 64
                    for i, (name, dtype_name, shape) in enumerate(key):
                        nb, db = name.encode(), dtype_name.encode()
                        pieces += [nb, db]
                        specs["name_off"][i] = pos
                        specs["name_len"][i] = len(nb)
                        specs["dtype_off"][i] = pos + len(nb)
                        specs["dtype_len"][i] = len(db)
                        pos += len(nb) + len(db)
                        specs["data_len"][i] = arrs[i].nbytes
                        specs["shape"][i, : len(shape)] = shape
                        specs["ndim"][i] = len(shape)
                        cap += arrs[i].nbytes + len(nb) + len(db) + 128
                    cached = (specs, b"".join(pieces), cap)
                    _SPEC_CACHE[key] = cached
                template, strings, cap = cached
                specs = template.copy()  # concurrent encoders don't share
                specs["data_ptr"] = [
                    a.__array_interface__["data"][0] for a in arrs
                ]
                hdr = RolloutHeader(
                    model_version, env_id, rollout_id, length, total_reward
                )
                spec_ptr = specs.ctypes.data_as(ctypes.POINTER(EncodeTensor))
                out = np.empty(cap, np.uint8)
                written = lib.dota_encode_rollout(
                    ctypes.byref(hdr), strings, spec_ptr, n,
                    out.ctypes.data, cap,
                )
                if written > cap:  # estimate too small: size back, retry once
                    out = np.empty(written, np.uint8)
                    written = lib.dota_encode_rollout(
                        ctypes.byref(hdr), strings, spec_ptr, n,
                        out.ctypes.data, written,
                    )
                del arrs  # pinned the numpy buffers across the C calls
                if written >= 0:
                    # bytes-like, not bytes: a second whole-payload memcpy
                    # (`tobytes`) would halve the single-copy win; sockets,
                    # ParseFromString, and len() all take the view directly
                    return out[:written].data
    return encode_rollout(
        arrays, model_version, env_id, rollout_id, length, total_reward
    ).SerializeToString()


# In-band wire-narrowing marker (the ModelWeights schema predates
# wire_dtype and protoc is unavailable in this image to extend it): a
# pseudo-entry in the params map whose ``data`` lists exactly the leaf
# names the encoder cast f32→bf16, newline-joined. Decode upcasts ONLY
# those — a natively-bf16 param (model.param_dtype="bfloat16") is never
# silently widened. The "/"-free dunder name cannot collide with real
# leaves (flax param paths always nest at least one module level).
_WIRE_CAST_MARKER = "__wire_cast__"


def encode_weights(
    params: Any, version: int, wire_dtype: str = "float32"
) -> pb.ModelWeights:
    """Serialize a param pytree for the weights fanout.

    ``wire_dtype="bfloat16"`` casts float32 leaves to bf16 at encode —
    half the fanout bytes per publish (TransportConfig.wire_dtype); the
    decode side upcasts exactly those leaves on apply (recorded in an
    in-band marker entry). Non-f32 leaves (int counters, natively-bf16
    params) pass through unchanged in both directions.

    Device-resident params are fetched with ONE batched ``jax.device_get``
    over the whole tree — one host↔device sync per publish instead of one
    per leaf (ISSUE 5); host arrays pass through untouched. The async
    snapshot engine already hands this function host arrays, so its calls
    never sync at all.
    """
    if wire_dtype not in ("float32", "bfloat16"):
        raise ValueError(f"unknown wire_dtype {wire_dtype!r}")
    cast = None
    if wire_dtype == "bfloat16":
        if _BFLOAT16 is None:
            raise ValueError("wire_dtype=bfloat16 but ml_dtypes unavailable")
        cast = _BFLOAT16
    msg = pb.ModelWeights(version=version)
    cast_names = []
    flat = flatten_tree(params)
    if any(not isinstance(a, np.ndarray) for a in flat.values()):
        import jax  # deferred: the codec itself stays importable jax-free

        flat = jax.device_get(flat)  # host-sync-ok: ONE batched fetch per publish
    for name, arr in flat.items():
        a = np.asarray(arr)
        if cast is not None and a.dtype == np.float32:
            a = a.astype(cast)
            cast_names.append(name)
        msg.params[name].CopyFrom(tensor_to_proto(a))
    if cast_names:
        msg.params[_WIRE_CAST_MARKER].CopyFrom(
            pb.TensorProto(dtype="marker", data="\n".join(cast_names).encode())
        )
    return msg


def decode_weights(msg: pb.ModelWeights, upcast: bool = True) -> Tuple[int, Any]:
    """Decode a weights fanout message → ``(version, param pytree)``.

    With ``upcast`` (the apply-side default) the leaves the encoder
    narrowed to bf16 come back as float32 — the lossless inverse of the
    ``wire_dtype="bfloat16"`` cast (every bf16 value is exactly
    representable in f32). Leaves that were bf16 BEFORE encode carry no
    marker and keep their dtype. ``upcast=False`` returns the raw wire
    dtypes (tests, inspection)."""
    cast_names = frozenset()
    # `in` before indexing: protobuf message-map __getitem__ auto-inserts
    if _WIRE_CAST_MARKER in msg.params:
        cast_names = frozenset(
            msg.params[_WIRE_CAST_MARKER].data.decode().split("\n")
        )
    flat = {}
    for name, t in msg.params.items():
        if name == _WIRE_CAST_MARKER:
            continue
        arr = proto_to_tensor(t)
        if (
            upcast
            and name in cast_names
            and _BFLOAT16 is not None
            and arr.dtype == _BFLOAT16
        ):
            arr = arr.astype(np.float32)
        flat[name] = arr
    return msg.version, unflatten_tree(flat)
