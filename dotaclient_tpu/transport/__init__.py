"""Experience/weight transport: wire codec + pluggable queues."""

from dotaclient_tpu.transport.queues import (
    AmqpTransport,
    InProcTransport,
    Transport,
)
from dotaclient_tpu.transport.socket_transport import (
    SocketTransport,
    TransportServer,
)
from dotaclient_tpu.transport.shm_transport import (
    ShmTransport,
    ShmTransportServer,
)
from dotaclient_tpu.transport.serialize import (
    decode_rollout,
    decode_rollout_bytes,
    decode_weights,
    encode_rollout,
    encode_rollout_bytes,
    encode_weights,
    flatten_tree,
    proto_to_tensor,
    tensor_to_proto,
    unflatten_tree,
)

__all__ = [
    "AmqpTransport",
    "InProcTransport",
    "ShmTransport",
    "ShmTransportServer",
    "SocketTransport",
    "Transport",
    "TransportServer",
    "decode_rollout",
    "decode_rollout_bytes",
    "decode_weights",
    "encode_rollout",
    "encode_rollout_bytes",
    "encode_weights",
    "flatten_tree",
    "proto_to_tensor",
    "tensor_to_proto",
    "unflatten_tree",
]
