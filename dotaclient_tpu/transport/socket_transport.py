"""Cross-process transport over localhost/LAN TCP sockets.

The reference's multi-process topology is N ``agent.py`` processes → broker →
one optimizer (SURVEY.md §1). The broker there is RabbitMQ; this module
provides the same two channels (experience work-queue up, weights fanout
down) over plain length-prefixed protobuf frames so the topology runs
anywhere — including this sandbox, which has no broker — with
``AmqpTransport`` remaining the drop-in for clusters that do run one.

Wire format per frame: 1 byte kind (0 = Rollout, 1 = ModelWeights) +
4 bytes big-endian payload length + payload bytes.

* ``TransportServer`` — learner side. Owns the listening socket; every
  connected actor's rollouts funnel into one internal queue (work-queue
  semantics), and each ``publish_weights`` is fanned out to every connection
  (latest-wins on the actor side). Implements the ``Transport`` protocol so
  the learner uses it exactly like ``InProcTransport``.
* ``SocketTransport`` — actor side. Connects out, publishes rollouts,
  tracks the latest weights broadcast.

Failure model matches the reference's (SURVEY.md §5.3): actors are
stateless and disposable — a dead connection is dropped silently server-side
(its in-flight rollouts are lost, exactly like a RMQ consumer crash), and an
actor that loses the learner exits with an error for the supervisor
(k8s/systemd) to restart.
"""

from __future__ import annotations

import queue
import socket
import struct
import threading
import time
from typing import List, Optional, Tuple

from dotaclient_tpu.protos import dota_pb2 as pb
from dotaclient_tpu.utils import telemetry

_KIND_ROLLOUT = 0
_KIND_WEIGHTS = 1
_HEADER = struct.Struct(">BI")
MAX_FRAME = 512 * 1024 * 1024


def _send_frame(sock: socket.socket, kind: int, payload) -> None:
    # gather write: no header+payload concat copy (payload may be a
    # memoryview straight out of the native encoder)
    header = _HEADER.pack(kind, len(payload))
    sent = sock.sendmsg([header, payload])
    if sent < len(header) + len(payload):  # rare partial send: finish it
        if sent < len(header):
            sock.sendall(header[sent:])
            sent = len(header)
        # memoryview slice — no whole-payload copy just to send the tail
        sock.sendall(memoryview(payload)[sent - len(header):])


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket) -> Optional[Tuple[int, bytes]]:
    head = _recv_exact(sock, _HEADER.size)
    if head is None:
        return None
    kind, length = _HEADER.unpack(head)
    if length > MAX_FRAME:
        raise ValueError(f"frame of {length} bytes exceeds MAX_FRAME")
    payload = _recv_exact(sock, length)
    if payload is None:
        return None
    return kind, payload


class TransportServer:
    """Learner-side transport: accept actors, merge their experience."""

    def __init__(
        self, host: str = "127.0.0.1", port: int = 0, max_rollouts: int = 4096
    ) -> None:
        self._listener = socket.create_server((host, port))
        self.address = self._listener.getsockname()
        self._rollouts: "queue.Queue[bytes]" = queue.Queue(max_rollouts)
        self._conns: List[socket.socket] = []
        self._conns_lock = threading.Lock()
        # per-connection send locks: the accept-loop's late-joiner weights
        # frame and publish_weights may target the same socket concurrently,
        # and interleaved sendall() corrupts the framed stream
        self._send_locks: dict = {}
        self.bad_payloads = 0
        self._latest_weights: Optional[pb.ModelWeights] = None
        self._weights_lock = threading.Lock()
        self._closed = threading.Event()
        self.dropped = 0
        self._tel = telemetry.get_registry()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="transport-accept", daemon=True
        )
        self._accept_thread.start()

    # -- internals ---------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return  # listener closed
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                self._conns.append(conn)
                self._send_locks[conn] = threading.Lock()
                # late joiner gets the current weights immediately
                weights = self._latest_weights
            if weights is not None:
                if not self._locked_send(
                    conn, _KIND_WEIGHTS, weights.SerializeToString()
                ):
                    continue
            threading.Thread(
                target=self._reader_loop, args=(conn,),
                name="transport-reader", daemon=True,
            ).start()

    def _reader_loop(self, conn: socket.socket) -> None:
        try:
            while not self._closed.is_set():
                frame = _recv_frame(conn)
                if frame is None:
                    break
                kind, payload = frame
                if kind != _KIND_ROLLOUT:
                    continue
                # raw bytes are queued; parsing happens on the consumer via
                # the native fast-path decoder (consume_decoded) or protobuf
                while True:
                    try:
                        self._rollouts.put_nowait(payload)
                        break
                    except queue.Full:  # drop-oldest backpressure
                        try:
                            self._rollouts.get_nowait()
                            self.dropped += 1
                            self._tel.counter(
                                "transport/experience_dropped"
                            ).inc()
                        except queue.Empty:
                            pass
                self._tel.counter("transport/experience_published").inc()
                self._tel.gauge("transport/queue_depth").set(
                    self._rollouts.qsize()
                )
        except (OSError, ValueError):
            pass  # dead actor: stateless, just drop it (SURVEY.md §5.3)
        finally:
            self._drop(conn)

    def _locked_send(self, conn: socket.socket, kind: int, payload: bytes) -> bool:
        with self._conns_lock:
            lock = self._send_locks.get(conn)
        if lock is None:
            return False
        try:
            with lock:
                _send_frame(conn, kind, payload)
            return True
        except OSError:
            self._drop(conn)
            return False

    def _drop(self, conn: socket.socket) -> None:
        with self._conns_lock:
            if conn in self._conns:
                self._conns.remove(conn)
            self._send_locks.pop(conn, None)
        try:
            conn.close()
        except OSError:
            pass

    # -- Transport protocol (learner side) ---------------------------------

    def publish_rollout(self, rollout: pb.Rollout) -> None:
        raise RuntimeError("TransportServer is the learner side; actors publish")

    def _drain(self, max_count: int, timeout: Optional[float]) -> List[bytes]:
        # timed explicitly, recorded only when something drained: empty poll
        # timeouts measure idle waiting, not drain cost (see queues.py)
        out: List[bytes] = []
        t0 = time.perf_counter()
        try:
            out.append(self._rollouts.get(timeout=timeout))
        except queue.Empty:
            return out
        while len(out) < max_count:
            try:
                out.append(self._rollouts.get_nowait())
            except queue.Empty:
                break
        self._tel.timer("span/transport/consume").observe(time.perf_counter() - t0)
        self._tel.counter("transport/experience_consumed").inc(len(out))
        self._tel.gauge("transport/queue_depth").set(self._rollouts.qsize())
        return out

    def consume_rollouts(
        self, max_count: int, timeout: Optional[float] = None
    ) -> List[pb.Rollout]:
        protos = []
        for payload in self._drain(max_count, timeout):
            r = pb.Rollout()
            try:
                r.ParseFromString(payload)
            except Exception:  # malformed sender: drop, never kill the learner
                self.bad_payloads += 1
                continue
            protos.append(r)
        return protos

    def consume_decoded(self, max_count: int, timeout: Optional[float] = None):
        """Drain as decoded (meta, arrays) pairs via the native fast-path
        wire parser — the learner-ingest hot path (SURVEY.md §2.2 row 3).
        Malformed payloads (version-skewed actors, port scanners) are counted
        and dropped — the disposable-actor failure model, SURVEY.md §5.3."""
        from dotaclient_tpu.transport.serialize import decode_rollout_bytes

        out = []
        for p in self._drain(max_count, timeout):
            try:
                out.append(decode_rollout_bytes(p))
            except Exception:
                self.bad_payloads += 1
        return out

    def publish_weights(self, weights: pb.ModelWeights) -> None:
        payload = weights.SerializeToString()
        with self._weights_lock:
            self._latest_weights = weights
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            self._locked_send(conn, _KIND_WEIGHTS, payload)
        self._tel.counter("transport/weights_published").inc()
        self._tel.gauge("transport/weights_version").set(weights.version)
        self._tel.gauge("transport/actors_connected").set(self.n_connected)

    def latest_weights(self) -> Optional[pb.ModelWeights]:
        with self._weights_lock:
            return self._latest_weights

    @property
    def n_connected(self) -> int:
        with self._conns_lock:
            return len(self._conns)

    @property
    def pending_rollouts(self) -> int:
        return self._rollouts.qsize()

    def close(self) -> None:
        self._closed.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._conns_lock:
            for conn in self._conns:
                try:
                    conn.close()
                except OSError:
                    pass
            self._conns.clear()


class SocketTransport:
    """Actor-side transport: connect to the learner's ``TransportServer``."""

    def __init__(self, host: str, port: int, timeout: float = 30.0) -> None:
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock.settimeout(None)
        self._send_lock = threading.Lock()
        self._weights_lock = threading.Lock()
        self._latest_weights: Optional[pb.ModelWeights] = None
        self._dead: Optional[BaseException] = None
        self._reader = threading.Thread(
            target=self._reader_loop, name="weights-reader", daemon=True
        )
        self._reader.start()

    def _reader_loop(self) -> None:
        try:
            while True:
                frame = _recv_frame(self._sock)
                if frame is None:
                    raise ConnectionError("learner closed the connection")
                kind, payload = frame
                if kind != _KIND_WEIGHTS:
                    continue
                msg = pb.ModelWeights()
                msg.ParseFromString(payload)
                with self._weights_lock:
                    self._latest_weights = msg
        except BaseException as e:
            self._dead = e

    def _check(self) -> None:
        if self._dead is not None:
            raise ConnectionError(
                "transport connection lost; actor should exit and be restarted"
            ) from self._dead

    def publish_rollout(self, rollout: pb.Rollout) -> None:
        self.publish_rollout_bytes(rollout.SerializeToString())

    def publish_rollout_bytes(self, payload) -> None:
        """Ship pre-serialized wire bytes-like (the native-encoder path)."""
        self._check()
        with self._send_lock:
            _send_frame(self._sock, _KIND_ROLLOUT, payload)

    def consume_rollouts(
        self, max_count: int, timeout: Optional[float] = None
    ) -> List[pb.Rollout]:
        raise RuntimeError("SocketTransport is the actor side; learner consumes")

    def publish_weights(self, weights: pb.ModelWeights) -> None:
        raise RuntimeError("actors do not publish weights")

    def latest_weights(self) -> Optional[pb.ModelWeights]:
        self._check()
        with self._weights_lock:
            return self._latest_weights

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
