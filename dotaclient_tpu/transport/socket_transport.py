"""Cross-process transport over localhost/LAN TCP sockets.

The reference's multi-process topology is N ``agent.py`` processes → broker →
one optimizer (SURVEY.md §1). The broker there is RabbitMQ; this module
provides the same two channels (experience work-queue up, weights fanout
down) over plain length-prefixed protobuf frames so the topology runs
anywhere — including this sandbox, which has no broker — with
``AmqpTransport`` remaining the drop-in for clusters that do run one.

Wire format per frame: 1 byte kind (0 = Rollout, 1 = ModelWeights) +
4 bytes big-endian payload length + payload bytes.

* ``TransportServer`` — learner side. Owns the listening socket; every
  connected actor's rollouts funnel into one internal deque (work-queue
  semantics), and ``publish_weights`` fans out to every connection.
  Implements the ``Transport`` protocol so the learner uses it exactly like
  ``InProcTransport``.
* ``SocketTransport`` — actor side. Connects out, publishes rollouts,
  tracks the latest weights broadcast.

Fanout threading model (ISSUE 3): ``publish_weights`` never writes a
socket. It serializes ONCE, stamps a publish sequence number, and assigns
the shared payload to each connection's latest-wins slot — an O(1) enqueue
per connection. A dedicated writer thread per connection drains its slot
(vectored header+payload send); publishes that land while a send is still
in flight overwrite the unsent slot (counted in
``transport/weights_coalesced`` — actors only ever want the latest
version, and IMPACT's bounded-staleness result licenses skipping
intermediates). A connection whose writer is still stuck when
``fanout_max_lag`` newer publishes have been enqueued is over-budget:
it is dropped and counted (``transport/fanout_conns_dropped``), never
waited on — one stalled actor cannot delay the learner or its peers.

Ingest is batched (ISSUE 3): each reader thread ``recv_into``s a
preallocated buffer, parses every complete frame out of it per wakeup, and
hands the whole batch to the shared deque under one lock — no per-frame
queue round-trip. ``consume_decoded`` then drains all ready frames in one
lock acquisition and decodes them into zero-copy views that the trajectory
buffer's staging lanes copy from directly.

Failure model matches the reference's (SURVEY.md §5.3): actors are
stateless and disposable — a dead connection is dropped silently server-side
(its in-flight rollouts are lost, exactly like a RMQ consumer crash), and an
actor that loses the learner exits (after bounded reconnect attempts —
``actor/__main__.py``) for the supervisor (k8s/systemd) to restart.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from collections import deque
from typing import Deque, List, Optional, Tuple

from dotaclient_tpu.protos import dota_pb2 as pb
from dotaclient_tpu.utils import telemetry

_KIND_ROLLOUT = 0
_KIND_WEIGHTS = 1
_HEADER = struct.Struct(">BI")
MAX_FRAME = 512 * 1024 * 1024
_RECV_CHUNK = 256 * 1024


def _send_frame(sock: socket.socket, kind: int, payload) -> None:
    # gather write: no header+payload concat copy (payload may be a
    # memoryview straight out of the native encoder)
    header = _HEADER.pack(kind, len(payload))
    sent = sock.sendmsg([header, payload])
    if sent < len(header) + len(payload):  # rare partial send: finish it
        if sent < len(header):
            sock.sendall(header[sent:])
            sent = len(header)
        # memoryview slice — no whole-payload copy just to send the tail
        sock.sendall(memoryview(payload)[sent - len(header):])


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket) -> Optional[Tuple[int, bytes]]:
    head = _recv_exact(sock, _HEADER.size)
    if head is None:
        return None
    kind, length = _HEADER.unpack(head)
    if length > MAX_FRAME:
        raise ValueError(f"frame of {length} bytes exceeds MAX_FRAME")
    payload = _recv_exact(sock, length)
    if payload is None:
        return None
    return kind, payload


class _Conn:
    """One actor connection: socket + the latest-wins weights slot its
    writer thread drains. ``sent_seq`` trails ``pending_seq`` while a send
    is in flight; the gap is the connection's fanout lag."""

    __slots__ = (
        "sock", "cond", "pending", "pending_seq", "sent_seq", "dead",
    )

    def __init__(self, sock: socket.socket, seq: int) -> None:
        self.sock = sock
        self.cond = threading.Condition()
        self.pending: Optional[bytes] = None   # latest unsent weights payload
        self.pending_seq = seq
        self.sent_seq = seq      # last publish seq fully written to the wire
        self.dead = False


class TransportServer:
    """Learner-side transport: accept actors, merge their experience."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        max_rollouts: int = 4096,
        fanout_max_lag: int = 8,
    ) -> None:
        self._listener = socket.create_server((host, port))
        self.address = self._listener.getsockname()
        self._max_rollouts = max_rollouts
        self._fanout_max_lag = max(1, fanout_max_lag)
        self._rollouts: Deque[bytes] = deque()
        self._roll_cond = threading.Condition()
        self._conns: List[_Conn] = []
        self._conns_lock = threading.Lock()
        self.bad_payloads = 0
        self._latest_weights: Optional[pb.ModelWeights] = None
        self._latest_payload: Optional[bytes] = None
        self._publish_seq = 0
        self._weights_lock = threading.Lock()
        self._closed = threading.Event()
        self.dropped = 0
        self._tel = telemetry.get_registry()
        # eager-create the fanout metrics: event-driven counters must exist
        # (at 0) in every snapshot, not only after their first event —
        # scripts/check_telemetry_schema.py --require-transport pins these
        for name in (
            "transport/weights_coalesced",
            "transport/fanout_conns_dropped",
            "transport/weights_sent",
        ):
            self._tel.counter(name)
        self._tel.gauge("transport/fanout_lag_max")
        self._tel.gauge("transport/fanout_queue_depth")
        self._tel.gauge("transport/actors_connected")
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="transport-accept", daemon=True
        )
        self._accept_thread.start()

    # -- internals ---------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return  # listener closed
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._weights_lock:
                # baseline sent_seq at the CURRENT publish seq: a seq-0
                # placeholder would read as `seq` publishes of lag and get
                # a brand-new connection dropped as over-budget by a
                # racing publish
                conn = _Conn(sock, self._publish_seq)
            # ORDER MATTERS: append the connection BEFORE reading the
            # latest payload. publish_weights writes the payload before it
            # snapshots the connection list, so either its snapshot
            # includes this conn (it assigns the slot itself) or this
            # loop's later read observes the newly written payload — a
            # publish racing the accept can never be missed by both sides.
            with self._conns_lock:
                self._conns.append(conn)
            with self._weights_lock:
                payload = self._latest_payload
                seq = self._publish_seq
            with conn.cond:
                if payload is not None and (
                    conn.pending is None or conn.pending_seq < seq
                ):
                    # late joiner: current weights go through its own
                    # writer — a joiner that never reads can still never
                    # block this loop. The guard keeps a concurrent
                    # publish's NEWER assignment from being overwritten
                    # (its writer thread has not started yet, so an
                    # assigned slot is still exactly as the publish left
                    # it).
                    conn.pending = payload
                    conn.pending_seq = seq
                    conn.sent_seq = seq - 1
                    conn.cond.notify()
            threading.Thread(
                target=self._reader_loop, args=(conn,),
                name="transport-reader", daemon=True,
            ).start()
            threading.Thread(
                target=self._writer_loop, args=(conn,),
                name="transport-writer", daemon=True,
            ).start()

    def _reader_loop(self, conn: _Conn) -> None:
        """Batched ingest: ``recv_into`` a preallocated buffer, parse every
        complete frame per wakeup, hand the batch over under ONE lock."""
        recv_buf = bytearray(_RECV_CHUNK)
        recv_view = memoryview(recv_buf)
        acc = bytearray()    # partial-frame accumulator across recvs
        hdr = _HEADER.size
        try:
            while not self._closed.is_set():
                n = conn.sock.recv_into(recv_view)
                if n == 0:
                    break
                acc += recv_view[:n]
                frames: List[bytes] = []
                off = 0
                # memoryview slices are zero-copy, so bytes() is the ONE
                # copy per frame (slicing the bytearray directly would
                # copy twice). Released before the del — a live export
                # blocks resizing the bytearray.
                acc_view = memoryview(acc)
                try:
                    while len(acc) - off >= hdr:
                        kind, length = _HEADER.unpack_from(acc, off)
                        if length > MAX_FRAME:
                            raise ValueError(
                                f"frame of {length} bytes exceeds MAX_FRAME"
                            )
                        if len(acc) - off - hdr < length:
                            break   # incomplete tail: wait for more bytes
                        if kind == _KIND_ROLLOUT:
                            frames.append(
                                bytes(acc_view[off + hdr:off + hdr + length])
                            )
                        off += hdr + length
                finally:
                    acc_view.release()
                if off:
                    del acc[:off]
                if frames:
                    self._enqueue_rollouts(frames)
        except (OSError, ValueError):
            pass  # dead actor: stateless, just drop it (SURVEY.md §5.3)
        finally:
            self._drop(conn)

    def _enqueue_rollouts(self, frames: List[bytes]) -> None:
        with self._roll_cond:
            self._rollouts.extend(frames)
            over = len(self._rollouts) - self._max_rollouts
            if over > 0:  # drop-oldest backpressure
                for _ in range(over):
                    self._rollouts.popleft()
                self.dropped += over
                self._tel.counter("transport/experience_dropped").inc(over)
            depth = len(self._rollouts)
            self._roll_cond.notify()
        self._tel.counter("transport/experience_published").inc(len(frames))
        self._tel.gauge("transport/queue_depth").set(depth)

    def _writer_loop(self, conn: _Conn) -> None:
        """Per-connection weights writer: drain the latest-wins slot. Only
        this thread ever writes ``conn.sock``, so no send lock exists."""
        while True:
            with conn.cond:
                while (
                    conn.pending is None
                    and not conn.dead
                    and not self._closed.is_set()
                ):
                    conn.cond.wait(0.5)
                if conn.dead or self._closed.is_set():
                    return
                payload, seq = conn.pending, conn.pending_seq
                conn.pending = None
            try:
                _send_frame(conn.sock, _KIND_WEIGHTS, payload)
            except (OSError, ValueError):
                self._drop(conn)
                return
            conn.sent_seq = seq
            self._tel.counter("transport/weights_sent").inc()

    def _drop(self, conn: _Conn) -> None:
        with self._conns_lock:
            if conn in self._conns:
                self._conns.remove(conn)
        with conn.cond:
            conn.dead = True
            conn.pending = None
            conn.cond.notify_all()
        try:
            # shutdown (not just close) unblocks a writer stuck in sendall
            # on a stalled consumer's full socket buffer
            conn.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            conn.sock.close()
        except OSError:
            pass

    # -- Transport protocol (learner side) ---------------------------------

    def publish_rollout(self, rollout: pb.Rollout) -> None:
        raise RuntimeError("TransportServer is the learner side; actors publish")

    def _drain(self, max_count: int, timeout: Optional[float]) -> List[bytes]:
        # timed explicitly, recorded only when something drained: empty poll
        # timeouts measure idle waiting, not drain cost (see queues.py)
        out: List[bytes] = []
        t0 = time.perf_counter()
        deadline = None if timeout is None else t0 + timeout
        with self._roll_cond:
            while not self._rollouts:
                if self._closed.is_set():
                    return out
                remaining = (
                    None if deadline is None
                    else deadline - time.perf_counter()
                )
                if remaining is not None and remaining <= 0:
                    return out
                self._roll_cond.wait(remaining)
            while self._rollouts and len(out) < max_count:
                out.append(self._rollouts.popleft())
            depth = len(self._rollouts)
        self._tel.timer("span/transport/consume").observe(time.perf_counter() - t0)
        self._tel.counter("transport/experience_consumed").inc(len(out))
        self._tel.gauge("transport/queue_depth").set(depth)
        return out

    def consume_rollouts(
        self, max_count: int, timeout: Optional[float] = None
    ) -> List[pb.Rollout]:
        protos = []
        for payload in self._drain(max_count, timeout):
            r = pb.Rollout()
            try:
                r.ParseFromString(payload)
            except Exception:  # malformed sender: drop, never kill the learner
                self.bad_payloads += 1
                continue
            protos.append(r)
        return protos

    def consume_decoded(self, max_count: int, timeout: Optional[float] = None):
        """Drain as decoded (meta, arrays) pairs via the native fast-path
        wire parser — the learner-ingest hot path (SURVEY.md §2.2 row 3).
        The arrays are zero-copy views into the wire payloads; the buffer's
        staging lanes copy straight out of them (its only copy). Malformed
        payloads (version-skewed actors, port scanners) are counted and
        dropped — the disposable-actor failure model, SURVEY.md §5.3."""
        from dotaclient_tpu.transport.serialize import decode_rollout_bytes

        out = []
        for p in self._drain(max_count, timeout):
            try:
                out.append(decode_rollout_bytes(p))
            except Exception:
                self.bad_payloads += 1
        return out

    def publish_weights(self, weights: pb.ModelWeights) -> None:
        """Non-blocking fanout: serialize once, assign the shared payload to
        every connection's latest-wins slot, drop over-budget connections.
        Never writes a socket — returns in O(connections) slot assignments
        regardless of how stalled any consumer is."""
        payload = weights.SerializeToString()
        with self._weights_lock:
            self._latest_weights = weights
            self._latest_payload = payload
            self._publish_seq += 1
            seq = self._publish_seq
        with self._conns_lock:
            conns = list(self._conns)
        over_budget: List[_Conn] = []
        max_lag = 0
        pending_depth = 0
        for conn in conns:
            with conn.cond:
                if conn.pending is not None:
                    # a send is still in flight and an unsent older version
                    # just became worthless: latest wins
                    self._tel.counter("transport/weights_coalesced").inc()
                    pending_depth += 1
                conn.pending = payload
                conn.pending_seq = seq
                conn.cond.notify()
            lag = seq - conn.sent_seq
            max_lag = max(max_lag, lag)
            if lag > self._fanout_max_lag:
                over_budget.append(conn)
        for conn in over_budget:
            # stalled past the budget: cut it loose (counted), never wait
            self._tel.counter("transport/fanout_conns_dropped").inc()
            self._drop(conn)
        self._tel.counter("transport/weights_published").inc()
        self._tel.gauge("transport/weights_version").set(weights.version)
        self._tel.gauge("transport/fanout_lag_max").set(float(max_lag))
        self._tel.gauge("transport/fanout_queue_depth").set(
            float(pending_depth)
        )
        self._tel.gauge("transport/actors_connected").set(self.n_connected)

    def latest_weights(self) -> Optional[pb.ModelWeights]:
        with self._weights_lock:
            return self._latest_weights

    @property
    def n_connected(self) -> int:
        with self._conns_lock:
            return len(self._conns)

    @property
    def pending_rollouts(self) -> int:
        with self._roll_cond:
            return len(self._rollouts)

    def close(self) -> None:
        self._closed.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for conn in conns:
            with conn.cond:
                conn.dead = True
                conn.cond.notify_all()
            try:
                conn.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.sock.close()
            except OSError:
                pass
        with self._roll_cond:
            self._roll_cond.notify_all()


class SocketTransport:
    """Actor-side transport: connect to the learner's ``TransportServer``."""

    def __init__(self, host: str, port: int, timeout: float = 30.0) -> None:
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock.settimeout(None)
        self._send_lock = threading.Lock()
        self._weights_lock = threading.Lock()
        self._latest_weights: Optional[pb.ModelWeights] = None
        self._dead: Optional[BaseException] = None
        self._reader = threading.Thread(
            target=self._reader_loop, name="weights-reader", daemon=True
        )
        self._reader.start()

    def _reader_loop(self) -> None:
        try:
            while True:
                frame = _recv_frame(self._sock)
                if frame is None:
                    raise ConnectionError("learner closed the connection")
                kind, payload = frame
                if kind != _KIND_WEIGHTS:
                    continue
                msg = pb.ModelWeights()
                msg.ParseFromString(payload)
                with self._weights_lock:
                    self._latest_weights = msg
        except BaseException as e:
            self._dead = e

    def _check(self) -> None:
        if self._dead is not None:
            raise ConnectionError(
                "transport connection lost; actor should exit and be restarted"
            ) from self._dead

    def publish_rollout(self, rollout: pb.Rollout) -> None:
        self.publish_rollout_bytes(rollout.SerializeToString())

    def publish_rollout_bytes(self, payload) -> None:
        """Ship pre-serialized wire bytes-like (the native-encoder path)."""
        self._check()
        with self._send_lock:
            _send_frame(self._sock, _KIND_ROLLOUT, payload)

    def consume_rollouts(
        self, max_count: int, timeout: Optional[float] = None
    ) -> List[pb.Rollout]:
        raise RuntimeError("SocketTransport is the actor side; learner consumes")

    def publish_weights(self, weights: pb.ModelWeights) -> None:
        raise RuntimeError("actors do not publish weights")

    def latest_weights(self) -> Optional[pb.ModelWeights]:
        self._check()
        with self._weights_lock:
            return self._latest_weights

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
