"""Cross-process transport over localhost/LAN TCP sockets.

The reference's multi-process topology is N ``agent.py`` processes → broker →
one optimizer (SURVEY.md §1). The broker there is RabbitMQ; this module
provides the same two channels (experience work-queue up, weights fanout
down) over plain length-prefixed protobuf frames so the topology runs
anywhere — including this sandbox, which has no broker — with
``AmqpTransport`` remaining the drop-in for clusters that do run one.

Wire format per frame: 1 byte kind (0 = Rollout, 1 = ModelWeights,
2 = heartbeat, 5 = fleet metrics snapshot — ISSUE 13, routed to the
learner's ``metrics_handler``) + 4 bytes big-endian payload length + 4
bytes CRC32 of
those first 5 header bytes + payload bytes + 4 bytes big-endian CRC32
trailer (``serialize.frame_crc32`` over the payload; heartbeats have an
empty payload). The header carries its own CRC because the two corruption
classes need different responses: a corrupt PAYLOAD (header intact) can be
skipped frame-by-frame (the poison streak), but a corrupt LENGTH word
poisons every later byte boundary — and without the header CRC a
plausible-but-wrong length (≤ MAX_FRAME) would make the reader silently
buffer up to that many bytes of phantom payload, swallowing good frames
for minutes before the payload CRC even got a chance to fail. With it,
header corruption is detected immediately and treated as fatal framing
loss (quarantine; TCP cannot resync).

* ``TransportServer`` — learner side. Owns the listening socket; every
  connected actor's rollouts funnel into one internal deque (work-queue
  semantics), and ``publish_weights`` fans out to every connection.
  Implements the ``Transport`` protocol so the learner uses it exactly like
  ``InProcTransport``.
* ``SocketTransport`` — actor side. Connects out, publishes rollouts,
  tracks the latest weights broadcast.

Fanout threading model (ISSUE 3): ``publish_weights`` never writes a
socket. It serializes ONCE, stamps a publish sequence number, and assigns
the shared payload to each connection's latest-wins slot — an O(1) enqueue
per connection. A dedicated writer thread per connection drains its slot
(vectored header+payload send); publishes that land while a send is still
in flight overwrite the unsent slot (counted in
``transport/weights_coalesced`` — actors only ever want the latest
version, and IMPACT's bounded-staleness result licenses skipping
intermediates). A connection whose writer is still stuck when
``fanout_max_lag`` newer publishes have been enqueued is over-budget:
it is dropped and counted (``transport/fanout_conns_dropped``), never
waited on — one stalled actor cannot delay the learner or its peers.

Ingest is batched (ISSUE 3): each reader thread ``recv_into``s a
preallocated buffer, parses every complete frame out of it per wakeup, and
hands the whole batch to the shared deque under one lock — no per-frame
queue round-trip. ``consume_decoded`` then drains all ready frames in one
lock acquisition and decodes them into zero-copy views that the trajectory
buffer's staging lanes copy from directly.

Failure model (SURVEY.md §5.3, hardened in ISSUE 4): actors are stateless
and disposable — a dead connection is dropped silently server-side (its
in-flight rollouts are lost, exactly like a RMQ consumer crash), and an
actor that loses the learner exits (after bounded reconnect attempts —
``actor/__main__.py``) for the supervisor (k8s/systemd) to restart. On top
of that, every frame carries a CRC32 trailer (``serialize.frame_crc32``):
corrupt frames are dropped and counted (``transport/frames_corrupt_total``)
and a peer that ships ``poison_frame_limit`` consecutive bad frames is
quarantined (connection cut, ``transport/peers_quarantined``) instead of
crashing the reader thread. Liveness runs both directions: the learner's
per-connection writer interleaves heartbeat frames with the weights fanout,
the actor echoes them (and times out if the learner goes silent —
``idle_timeout_s``, parity with the shm lane's pid beacon), and the learner
drops connections with no inbound bytes for ``idle_timeout_s``
(``transport/conn_idle_drops``) — a half-open TCP connection can never
wedge either side.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from collections import deque
from typing import Deque, List, Optional, Tuple

from dotaclient_tpu.protos import dota_pb2 as pb
from dotaclient_tpu.transport.serialize import frame_crc32
from dotaclient_tpu.utils import faults, telemetry, tracing

_KIND_ROLLOUT = 0
_KIND_WEIGHTS = 1
_KIND_HEARTBEAT = 2
# kinds 3/4 belong to the serve request/reply lane (serve/server.py —
# its own listener, but the numbers stay disjoint so a misdirected
# client is unambiguous in a packet capture)
_KIND_METRICS = 5   # fleet-health snapshot, actor/serve → learner (ISSUE 13)
_HEADER = struct.Struct(">BI")
_CRC = struct.Struct(">I")
# header-on-wire size: kind + length + CRC32 of those 5 bytes (see the
# module docstring for why the length word carries its own CRC)
_WIRE_HDR = _HEADER.size + _CRC.size
MAX_FRAME = 512 * 1024 * 1024
_RECV_CHUNK = 256 * 1024
# echoes are rate-limited: at most one outbound liveness frame per second
# no matter how fast weights/heartbeats arrive
_ECHO_MIN_INTERVAL_S = 1.0


def _pack_header(kind: int, length: int) -> bytes:
    head = _HEADER.pack(kind, length)
    return head + _CRC.pack(frame_crc32(head))


# the full heartbeat wire frame (kind 2, empty payload, CRC of b""),
# precomputed once: heartbeat sends and echoes are a single constant write
_HEARTBEAT_FRAME = _pack_header(_KIND_HEARTBEAT, 0) + _CRC.pack(
    frame_crc32(b"")
)


class FrameCorrupt(ValueError):
    """A frame whose payload CRC trailer does not match (header intact —
    the stream stays in sync, the frame alone is dropped)."""


class FramingLost(ConnectionError):
    """A frame whose HEADER failed its CRC: the length word cannot be
    trusted, so every later byte boundary is garbage — the stream is
    unusable and the connection must be torn down."""


def _send_frame(
    sock: socket.socket, kind: int, payload, crc: Optional[int] = None
) -> None:
    # gather write: no header+payload+trailer concat copy (payload may be a
    # memoryview straight out of the native encoder). ``crc`` lets fault
    # injection write a deliberately wrong trailer.
    header = _pack_header(kind, len(payload))
    trailer = _CRC.pack(frame_crc32(payload) if crc is None else crc)
    parts = [header, payload, trailer]
    total = len(header) + len(payload) + len(trailer)
    sent = sock.sendmsg(parts)
    if sent < total:  # rare partial send: finish each part's tail
        rem = sent
        for part in parts:
            if rem >= len(part):
                rem -= len(part)
                continue
            # memoryview slice — no whole-payload copy to send the tail
            sock.sendall(memoryview(part)[rem:] if rem else part)
            rem = 0


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket) -> Optional[Tuple[int, bytes]]:
    head = _recv_exact(sock, _WIRE_HDR)
    if head is None:
        return None
    kind, length = _HEADER.unpack_from(head)
    if _CRC.unpack_from(head, _HEADER.size)[0] != frame_crc32(
        head[:_HEADER.size]
    ):
        raise FramingLost("frame header corrupt — length untrustworthy")
    if length > MAX_FRAME:
        raise ValueError(f"frame of {length} bytes exceeds MAX_FRAME")
    # payload and trailer arrive as separate exact reads so the payload
    # needs no trailing-slice copy (weights frames are tens of MB)
    payload = _recv_exact(sock, length)
    if payload is None:
        return None
    trailer = _recv_exact(sock, _CRC.size)
    if trailer is None:
        return None
    if _CRC.unpack(trailer)[0] != frame_crc32(payload):
        raise FrameCorrupt(f"frame CRC mismatch ({length} byte payload)")
    return kind, payload


class _Conn:
    """One actor connection: socket + the latest-wins weights slot its
    writer thread drains. ``sent_seq`` trails ``pending_seq`` while a send
    is in flight; the gap is the connection's fanout lag. ``last_seen``
    (monotonic, updated by the reader on any inbound bytes) drives the
    idle-drop check; ``bad_streak`` counts consecutive corrupt frames
    toward the quarantine limit."""

    __slots__ = (
        "sock", "cond", "pending", "pending_crc", "pending_seq",
        "sent_seq", "dead", "last_seen", "bad_streak",
    )

    def __init__(self, sock: socket.socket, seq: int) -> None:
        self.sock = sock
        self.cond = threading.Condition()
        self.pending: Optional[bytes] = None   # latest unsent weights payload
        self.pending_crc = 0    # frame_crc32 of pending, computed ONCE
        self.pending_seq = seq
        self.sent_seq = seq      # last publish seq fully written to the wire
        self.dead = False
        self.last_seen = time.monotonic()
        self.bad_streak = 0


class TransportServer:
    """Learner-side transport: accept actors, merge their experience."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        max_rollouts: int = 4096,
        fanout_max_lag: int = 8,
        poison_frame_limit: int = 8,
        heartbeat_interval_s: float = 5.0,
        idle_timeout_s: float = 30.0,
    ) -> None:
        self._listener = socket.create_server((host, port))
        self.address = self._listener.getsockname()
        self._max_rollouts = max_rollouts
        self._fanout_max_lag = max(1, fanout_max_lag)
        self._poison_frame_limit = max(1, poison_frame_limit)
        self._heartbeat_s = max(0.0, heartbeat_interval_s)
        self._idle_timeout_s = max(0.0, idle_timeout_s)
        # writer-loop wake granularity: fine enough to hit small heartbeat/
        # idle windows (tests), capped at the historical 0.5 s poll
        self._tick_s = min(
            0.5,
            *(v / 4 for v in (self._heartbeat_s, self._idle_timeout_s) if v),
        ) if (self._heartbeat_s or self._idle_timeout_s) else 0.5
        self._rollouts: Deque[bytes] = deque()
        self._roll_cond = threading.Condition()
        self._conns: List[_Conn] = []
        self._conns_lock = threading.Lock()
        self.bad_payloads = 0
        self._latest_weights: Optional[pb.ModelWeights] = None
        self._latest_payload: Optional[bytes] = None
        self._latest_crc = 0
        self._publish_seq = 0
        self._weights_lock = threading.Lock()
        self._closed = threading.Event()
        self.dropped = 0
        self._tel = telemetry.get_registry()
        # eager-create the fanout metrics: event-driven counters must exist
        # (at 0) in every snapshot, not only after their first event —
        # scripts/check_telemetry_schema.py --require-transport pins these
        for name in (
            "transport/weights_coalesced",
            "transport/fanout_conns_dropped",
            "transport/weights_sent",
            # fault-tolerance layer (ISSUE 4) — pinned by
            # check_telemetry_schema.py --require-faults
            "transport/frames_corrupt_total",
            "transport/peers_quarantined",
            "transport/conn_idle_drops",
            "transport/heartbeats_sent",
            "transport/reader_exits",
            # quantized experience plane (ISSUE 7) — pinned by
            # check_telemetry_schema.py --require-wire
            "transport/rollout_bytes_total",
            "transport/rollout_raw_bytes_total",
        ):
            self._tel.counter(name)
        self._tel.gauge("transport/fanout_lag_max")
        self._tel.gauge("transport/fanout_queue_depth")
        self._tel.gauge("transport/actors_connected")
        # raw/wire byte ratio over everything consumed so far; 1.0 until
        # the first frame (no data = no compression claim)
        self._tel.gauge("transport/rollout_compression_ratio").set(1.0)
        self._rollout_totals = [0, 0]   # [wire bytes, raw bytes] consumed
        # Fleet-health snapshot sink (ISSUE 13): the learner's
        # FleetAggregator assigns its `ingest` here; reader threads hand
        # it every CRC-verified kind-5 payload. None = frames dropped
        # (a fleet-less consumer owes the peers nothing).
        self.metrics_handler = None
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="transport-accept", daemon=True
        )
        self._accept_thread.start()

    # -- internals ---------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return  # listener closed
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._weights_lock:
                # baseline sent_seq at the CURRENT publish seq: a seq-0
                # placeholder would read as `seq` publishes of lag and get
                # a brand-new connection dropped as over-budget by a
                # racing publish
                conn = _Conn(sock, self._publish_seq)
            # ORDER MATTERS: append the connection BEFORE reading the
            # latest payload. publish_weights writes the payload before it
            # snapshots the connection list, so either its snapshot
            # includes this conn (it assigns the slot itself) or this
            # loop's later read observes the newly written payload — a
            # publish racing the accept can never be missed by both sides.
            with self._conns_lock:
                self._conns.append(conn)
            with self._weights_lock:
                payload = self._latest_payload
                payload_crc = self._latest_crc
                seq = self._publish_seq
            with conn.cond:
                if payload is not None and (
                    conn.pending is None or conn.pending_seq < seq
                ):
                    # late joiner: current weights go through its own
                    # writer — a joiner that never reads can still never
                    # block this loop. The guard keeps a concurrent
                    # publish's NEWER assignment from being overwritten
                    # (its writer thread has not started yet, so an
                    # assigned slot is still exactly as the publish left
                    # it).
                    conn.pending = payload
                    conn.pending_crc = payload_crc
                    conn.pending_seq = seq
                    conn.sent_seq = seq - 1
                    conn.cond.notify()
            threading.Thread(
                target=self._reader_loop, args=(conn,),
                name="transport-reader", daemon=True,
            ).start()
            threading.Thread(
                target=self._writer_loop, args=(conn,),
                name="transport-writer", daemon=True,
            ).start()

    def _poison(self, conn: _Conn, fatal: bool = False) -> None:
        """One corrupt frame from ``conn``: count it, advance the streak,
        and quarantine the peer (raise, which drops the connection) once the
        streak hits ``poison_frame_limit`` — or immediately when framing is
        unrecoverable (``fatal``: a corrupt length word means every later
        byte boundary is garbage, there is nothing to resync to on TCP)."""
        self._tel.counter("transport/frames_corrupt_total").inc()
        conn.bad_streak += 1
        if fatal or conn.bad_streak >= self._poison_frame_limit:
            self._tel.counter("transport/peers_quarantined").inc()
            raise FrameCorrupt(
                f"peer quarantined after {conn.bad_streak} consecutive "
                f"corrupt frames"
            )

    def _reader_loop(self, conn: _Conn) -> None:
        """Batched ingest: ``recv_into`` a preallocated buffer, parse every
        complete frame per wakeup, hand the batch over under ONE lock.
        Decode/parse trouble routes through the quarantine path (counted,
        connection dropped) — a malformed peer can never kill this thread
        with an unhandled exception, and a reader death is itself counted
        (``transport/reader_exits``) so a wedged fleet is diagnosable."""
        recv_buf = bytearray(_RECV_CHUNK)
        recv_view = memoryview(recv_buf)
        acc = bytearray()    # partial-frame accumulator across recvs
        hdr = _WIRE_HDR
        tail = _CRC.size
        try:
            while not self._closed.is_set():
                n = conn.sock.recv_into(recv_view)
                if n == 0:
                    break
                conn.last_seen = time.monotonic()  # any inbound bytes = alive
                acc += recv_view[:n]
                frames: List[bytes] = []
                metrics: List[bytes] = []
                off = 0
                # memoryview slices are zero-copy, so bytes() is the ONE
                # copy per frame (slicing the bytearray directly would
                # copy twice). Released before the del — a live export
                # blocks resizing the bytearray.
                acc_view = memoryview(acc)
                try:
                    while len(acc) - off >= hdr:
                        kind, length = _HEADER.unpack_from(acc, off)
                        if _CRC.unpack_from(acc, off + _HEADER.size)[
                            0
                        ] != frame_crc32(
                            acc_view[off:off + _HEADER.size]
                        ) or length > MAX_FRAME:
                            # header (so the length word) untrustworthy:
                            # framing lost, quarantine immediately (raises)
                            # BEFORE buffering a phantom payload
                            self._poison(conn, fatal=True)
                        if len(acc) - off - hdr < length + tail:
                            break   # incomplete tail: wait for more bytes
                        start = off + hdr
                        off += hdr + length + tail
                        if _CRC.unpack_from(acc, start + length)[
                            0
                        ] != frame_crc32(acc_view[start:start + length]):
                            self._poison(conn)  # dropped + counted
                            continue
                        conn.bad_streak = 0
                        if kind == _KIND_ROLLOUT:
                            frames.append(
                                bytes(acc_view[start:start + length])
                            )
                        elif (
                            kind == _KIND_METRICS
                            and self.metrics_handler is not None
                        ):
                            # fleet snapshot (ISSUE 13): same CRC/streak
                            # discipline as every frame above; handed to
                            # the aggregator OUTSIDE the view's lifetime
                            metrics.append(
                                bytes(acc_view[start:start + length])
                            )
                        # weights/heartbeat kinds from an actor are liveness
                        # traffic only (the echo path) — nothing to enqueue
                finally:
                    acc_view.release()
                if off:
                    del acc[:off]
                if frames:
                    self._enqueue_rollouts(frames)
                if metrics:
                    handler = self.metrics_handler
                    for m in metrics:
                        try:
                            handler(m)   # stamps its own receive time
                        except Exception:  # noqa: BLE001
                            pass   # a broken sink must never kill a reader
        except (OSError, ValueError):
            pass  # dead/poisoned actor: stateless, drop it (SURVEY.md §5.3)
        finally:
            if not self._closed.is_set():
                # counted only when the CONNECTION went away (actor death,
                # quarantine, clean actor exit) — a learner-side close()
                # tears every reader down and is not a peer-loss signal
                self._tel.counter("transport/reader_exits").inc()
            self._drop(conn)

    def _enqueue_rollouts(self, frames: List[bytes]) -> None:
        # one receive stamp per parse batch (ISSUE 12): these frames were
        # CRC-verified in the same wakeup, so the stamp is the `recv` trace
        # hop for every traced chunk in the batch — the queue holds
        # (recv_ts, payload) pairs and the cost is one clock read per
        # wakeup whether tracing is on or off
        ts = tracing.now()
        with self._roll_cond:
            self._rollouts.extend((ts, f) for f in frames)
            over = len(self._rollouts) - self._max_rollouts
            if over > 0:  # drop-oldest backpressure
                for _ in range(over):
                    self._rollouts.popleft()
                self.dropped += over
                self._tel.counter("transport/experience_dropped").inc(over)
            depth = len(self._rollouts)
            self._roll_cond.notify()
        self._tel.counter("transport/experience_published").inc(len(frames))
        self._tel.gauge("transport/queue_depth").set(depth)

    def _writer_loop(self, conn: _Conn) -> None:
        """Per-connection weights writer: drain the latest-wins slot. Only
        this thread ever writes ``conn.sock``, so no send lock exists.

        Liveness duty (ISSUE 4): while the slot is empty this thread also
        (a) interleaves heartbeat frames every ``heartbeat_interval_s`` so
        the actor's idle timeout sees a live learner even between weight
        publishes, and (b) drops the connection when the reader has seen no
        inbound bytes for ``idle_timeout_s`` (``transport/conn_idle_drops``)
        — the actor echoes heartbeats, so a healthy-but-quiet actor still
        refreshes ``last_seen`` and only a half-open connection trips it."""
        last_sent = time.monotonic()
        while True:
            heartbeat = False
            idle_drop = False
            payload = None
            with conn.cond:
                while (
                    conn.pending is None
                    and not conn.dead
                    and not self._closed.is_set()
                ):
                    now = time.monotonic()
                    if (
                        self._idle_timeout_s
                        and now - conn.last_seen > self._idle_timeout_s
                    ):
                        idle_drop = True
                        break
                    if (
                        self._heartbeat_s
                        and now - last_sent >= self._heartbeat_s
                    ):
                        heartbeat = True
                        break
                    conn.cond.wait(self._tick_s)
                if conn.dead or self._closed.is_set():
                    return
                if conn.pending is not None:
                    payload, seq = conn.pending, conn.pending_seq
                    payload_crc = conn.pending_crc
                    conn.pending = None
            if idle_drop:
                self._tel.counter("transport/conn_idle_drops").inc()
                self._drop(conn)
                return
            try:
                if payload is not None:
                    # crc precomputed by publish_weights: one fold per
                    # publish for the whole fleet, not one per connection
                    _send_frame(
                        conn.sock, _KIND_WEIGHTS, payload, crc=payload_crc
                    )
                elif heartbeat:
                    conn.sock.sendall(_HEARTBEAT_FRAME)
            except (OSError, ValueError):
                self._drop(conn)
                return
            last_sent = time.monotonic()
            if payload is not None:
                conn.sent_seq = seq
                self._tel.counter("transport/weights_sent").inc()
            elif heartbeat:
                self._tel.counter("transport/heartbeats_sent").inc()

    def _drop(self, conn: _Conn) -> None:
        with self._conns_lock:
            if conn in self._conns:
                self._conns.remove(conn)
        with conn.cond:
            conn.dead = True
            conn.pending = None
            conn.cond.notify_all()
        try:
            # shutdown (not just close) unblocks a writer stuck in sendall
            # on a stalled consumer's full socket buffer
            conn.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            conn.sock.close()
        except OSError:
            pass

    # -- Transport protocol (learner side) ---------------------------------

    def publish_rollout(self, rollout: pb.Rollout) -> None:
        raise RuntimeError("TransportServer is the learner side; actors publish")

    def _drain(
        self, max_count: int, timeout: Optional[float]
    ) -> List[Tuple[float, bytes]]:
        # timed explicitly, recorded only when something drained: empty poll
        # timeouts measure idle waiting, not drain cost (see queues.py).
        # Items are (recv_ts, payload) pairs — recv_ts is the reader
        # thread's post-CRC arrival stamp (the `recv` trace hop).
        out: List[Tuple[float, bytes]] = []
        t0 = time.perf_counter()
        deadline = None if timeout is None else t0 + timeout
        with self._roll_cond:
            while not self._rollouts:
                if self._closed.is_set():
                    return out
                remaining = (
                    None if deadline is None
                    else deadline - time.perf_counter()
                )
                if remaining is not None and remaining <= 0:
                    return out
                # bounded wait even for timeout=None: a close (or the last
                # reader thread dying) between the emptiness check and this
                # wait must not park the consume loop forever on a deque
                # nobody will ever refill
                self._roll_cond.wait(
                    0.5 if remaining is None else min(remaining, 0.5)
                )
            while self._rollouts and len(out) < max_count:
                out.append(self._rollouts.popleft())
            depth = len(self._rollouts)
        self._tel.timer("span/transport/consume").observe(time.perf_counter() - t0)
        self._tel.counter("transport/experience_consumed").inc(len(out))
        self._tel.gauge("transport/queue_depth").set(depth)
        return out

    def consume_rollouts(
        self, max_count: int, timeout: Optional[float] = None
    ) -> List[pb.Rollout]:
        protos = []
        for _recv_ts, payload in self._drain(max_count, timeout):
            r = pb.Rollout()
            try:
                r.ParseFromString(payload)
            except Exception:  # malformed sender: drop, never kill the learner
                self.bad_payloads += 1
                continue
            protos.append(r)
        return protos

    def consume_decoded(self, max_count: int, timeout: Optional[float] = None):
        """Drain as decoded (meta, arrays) pairs via the native fast-path
        wire parser — the learner-ingest hot path (SURVEY.md §2.2 row 3).
        The arrays are zero-copy views into the wire payloads; the buffer's
        staging lanes copy straight out of them (its only copy). Decode
        errors and the wire/raw byte accounting (ISSUE 7) live in the
        shared :func:`serialize.decode_drained_payloads`."""
        from dotaclient_tpu.transport.serialize import decode_drained_payloads

        out, bad = decode_drained_payloads(
            self._drain(max_count, timeout), self._tel, self._rollout_totals
        )
        self.bad_payloads += bad
        return out

    def publish_weights(self, weights: pb.ModelWeights) -> None:
        """Non-blocking fanout: serialize once, assign the shared payload to
        every connection's latest-wins slot, drop over-budget connections.
        Never writes a socket — returns in O(connections) slot assignments
        regardless of how stalled any consumer is.

        Caller threading (ISSUE 5): with the learner's async snapshot
        engine this runs on the SNAPSHOT thread (the train thread only
        dispatches an on-device copy); in --sync-snapshots mode it runs on
        the train thread. Either way there is exactly one publisher — the
        locks here protect against the reader/accept threads, not against
        concurrent publishers. Must stay free of host↔device syncs (the
        engine hands it host arrays already; scripts/check_host_sync.py
        scans this function)."""
        payload = weights.SerializeToString()
        payload_crc = frame_crc32(payload)   # folded ONCE for the fleet
        with self._weights_lock:
            self._latest_weights = weights
            self._latest_payload = payload
            self._latest_crc = payload_crc
            self._publish_seq += 1
            seq = self._publish_seq
        with self._conns_lock:
            conns = list(self._conns)
        over_budget: List[_Conn] = []
        max_lag = 0
        pending_depth = 0
        for conn in conns:
            with conn.cond:
                if conn.pending is not None:
                    # a send is still in flight and an unsent older version
                    # just became worthless: latest wins
                    self._tel.counter("transport/weights_coalesced").inc()
                    pending_depth += 1
                conn.pending = payload
                conn.pending_crc = payload_crc
                conn.pending_seq = seq
                conn.cond.notify()
            lag = seq - conn.sent_seq
            max_lag = max(max_lag, lag)
            if lag > self._fanout_max_lag:
                over_budget.append(conn)
        for conn in over_budget:
            # stalled past the budget: cut it loose (counted), never wait
            self._tel.counter("transport/fanout_conns_dropped").inc()
            self._drop(conn)
        self._tel.counter("transport/weights_published").inc()
        self._tel.gauge("transport/weights_version").set(weights.version)
        self._tel.gauge("transport/fanout_lag_max").set(float(max_lag))   # host-sync-ok: host ints
        self._tel.gauge("transport/fanout_queue_depth").set(
            float(pending_depth)   # host-sync-ok: host ints
        )
        self._tel.gauge("transport/actors_connected").set(self.n_connected)

    def latest_weights(self) -> Optional[pb.ModelWeights]:
        with self._weights_lock:
            return self._latest_weights

    @property
    def n_connected(self) -> int:
        with self._conns_lock:
            return len(self._conns)

    @property
    def pending_rollouts(self) -> int:
        with self._roll_cond:
            return len(self._rollouts)

    def close(self) -> None:
        self._closed.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for conn in conns:
            with conn.cond:
                conn.dead = True
                conn.cond.notify_all()
            try:
                conn.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.sock.close()
            except OSError:
                pass
        with self._roll_cond:
            self._roll_cond.notify_all()


class SocketTransport:
    """Actor-side transport: connect to the learner's ``TransportServer``.

    Liveness (ISSUE 4): the reader runs under ``idle_timeout_s`` — the
    learner heartbeats every few seconds even when it publishes nothing, so
    a recv that times out means the connection is half-open (learner host
    gone, cable pulled) and the transport declares itself dead, engaging
    the actor's reconnect/exit machinery (parity with the shm lane's pid
    beacon). Heartbeats are echoed back so the learner's idle-drop sees a
    live actor even between rollout publishes. Corrupt inbound frames are
    dropped and counted; ``poison_frame_limit`` consecutive ones declare
    the stream unusable (ConnectionError → reconnect gets a fresh one)."""

    def __init__(
        self,
        host: str,
        port: int,
        timeout: float = 30.0,
        idle_timeout_s: float = 30.0,
        poison_frame_limit: int = 8,
    ) -> None:
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # socket-level timeout doubles as the idle detector: heartbeats
        # arrive every heartbeat_interval_s << idle_timeout_s from a live
        # learner, so only a half-open connection ever trips it
        self._sock.settimeout(idle_timeout_s if idle_timeout_s > 0 else None)
        self._poison_frame_limit = max(1, poison_frame_limit)
        self._send_lock = threading.Lock()
        self._weights_lock = threading.Lock()
        self._latest_weights: Optional[pb.ModelWeights] = None
        self._dead: Optional[BaseException] = None
        self._faults = faults.get()
        self._tel = telemetry.get_registry()
        self._reader = threading.Thread(
            target=self._reader_loop, name="weights-reader", daemon=True
        )
        self._reader.start()

    def _reader_loop(self) -> None:
        bad_streak = 0
        last_echo = 0.0
        try:
            while True:
                try:
                    frame = _recv_frame(self._sock)
                except FramingLost:
                    raise   # ConnectionError: reconnect gets a fresh stream
                except FrameCorrupt:
                    self._tel.counter("transport/frames_corrupt_total").inc()
                    bad_streak += 1
                    if bad_streak >= self._poison_frame_limit:
                        raise ConnectionError(
                            f"stream unusable after {bad_streak} consecutive "
                            f"corrupt frames; reconnecting for a fresh one"
                        )
                    continue
                except socket.timeout:
                    raise ConnectionError(
                        "learner silent past the idle timeout (no weights "
                        "or heartbeats) — half-open connection"
                    ) from None
                if frame is None:
                    raise ConnectionError("learner closed the connection")
                bad_streak = 0
                # echo liveness on ANY inbound frame: the learner's
                # last-seen tracking must see this actor alive even when it
                # ships no rollouts. Heartbeats echo 1:1 (the learner paces
                # them against its own idle budget); other frames echo
                # rate-limited — a learner that publishes weights more
                # often than its heartbeat interval never sends heartbeats
                # at all, and echoing only heartbeats would get a healthy-
                # but-quiet actor idle-dropped.
                kind, payload = frame
                now = time.monotonic()
                if (
                    kind == _KIND_HEARTBEAT
                    or now - last_echo >= _ECHO_MIN_INTERVAL_S
                ):
                    last_echo = now
                    with self._send_lock:
                        self._sock.sendall(_HEARTBEAT_FRAME)
                if kind != _KIND_WEIGHTS:
                    continue
                msg = pb.ModelWeights()
                msg.ParseFromString(payload)
                with self._weights_lock:
                    self._latest_weights = msg
        except BaseException as e:
            self._dead = e

    def _check(self) -> None:
        if self._dead is not None:
            raise ConnectionError(
                "transport connection lost; actor should exit and be restarted"
            ) from self._dead

    def publish_rollout(self, rollout: pb.Rollout) -> None:
        self.publish_rollout_bytes(rollout.SerializeToString())

    def publish_rollout_bytes(self, payload) -> None:
        """Ship pre-serialized wire bytes-like (the native-encoder path)."""
        self._check()
        crc = None
        f = self._faults
        if f is not None:  # chaos hooks; one None test when faults are off
            delay = f.value("transport.delay_send")
            if delay:
                time.sleep(delay)
            if f.fire("transport.corrupt_frame"):
                crc = frame_crc32(payload) ^ 0xDEADBEEF
            if f.fire("transport.drop_conn"):
                self._sock.close()  # next send raises → reconnect machinery
        with self._send_lock:
            _send_frame(self._sock, _KIND_ROLLOUT, payload, crc=crc)

    def publish_metrics_bytes(self, payload) -> None:
        """Ship one fleet-health snapshot frame (kind 5, ISSUE 13) — the
        same CRC'd framing as rollouts, so the learner's quarantine
        discipline covers it unchanged. Raises like a rollout publish
        when the connection is gone (the caller's reconnect machinery)."""
        self._check()
        with self._send_lock:
            _send_frame(self._sock, _KIND_METRICS, payload)

    def consume_rollouts(
        self, max_count: int, timeout: Optional[float] = None
    ) -> List[pb.Rollout]:
        raise RuntimeError("SocketTransport is the actor side; learner consumes")

    def publish_weights(self, weights: pb.ModelWeights) -> None:
        raise RuntimeError("actors do not publish weights")

    def latest_weights(self) -> Optional[pb.ModelWeights]:
        self._check()
        with self._weights_lock:
            return self._latest_weights

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
