"""Pipeline tracing plane (ISSUE 12): record blobs, the lock-free writer,
in-band wire markers on both codecs, buffer hop stamping, compile/retrace
instrumentation, torn-line durability, and the trace_report merger on
canned logs (the tier-1 pin for the multi-process acceptance flow)."""

import json
import os

import jax
import numpy as np
import pytest

from dotaclient_tpu.transport import serialize as S
from dotaclient_tpu.utils import telemetry, tracing

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _schema_module():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(_REPO, "scripts", "check_telemetry_schema.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _no_leaked_tracer():
    """Every test starts and ends with tracing OFF (the process default);
    a leaked tracer would silently change other tests' hot paths."""
    tracing.configure(None)
    yield
    tracing.configure(None)


def _mk_record(tid="a-1-1", actor=1, wv=4):
    rec = tracing.new_record(tid, actor, wv)
    tracing.append_hop(rec, "collect", 10.0)
    tracing.append_hop(rec, "encode", 11.0)
    return rec


class TestRecordBlob:
    def test_round_trip_and_fixed_padding(self):
        rec = _mk_record()
        blob = tracing.record_to_blob(rec)
        # fixed width: the native encoder's template cache keys on shapes,
        # so every traced layout must present ONE blob length
        assert len(blob) == tracing.TRACE_WIRE_LEN
        blob2 = tracing.record_to_blob(_mk_record(tid="b-2-2", wv=12345))
        assert len(blob2) == tracing.TRACE_WIRE_LEN
        back = tracing.parse_blob(blob)
        assert back["tid"] == "a-1-1"
        assert back["pid"] == os.getpid()
        assert back["actor"] == 1 and back["wv"] == 4
        assert back["hops"] == [["collect", 10.0], ["encode", 11.0]]

    def test_unpadded_blob_for_off_template_paths(self):
        blob = tracing.record_to_blob(_mk_record(), pad=False)
        assert len(blob) < tracing.TRACE_WIRE_LEN
        assert tracing.parse_blob(blob)["tid"] == "a-1-1"

    def test_garbage_parses_to_none(self):
        assert tracing.parse_blob(b"not a record") is None
        assert tracing.parse_blob(b"") is None
        assert tracing.parse_blob(None) is None
        # header present but corrupt numerics
        assert tracing.parse_blob(b"tid=x pid=NaNish actor=1 wv=2") is None

    def test_weights_record(self):
        rec = tracing.weights_record(7)
        assert rec["wv"] == 7 and rec["actor"] == -1
        assert rec["hops"][0][0] == "publish"


class TestTracerAndWriter:
    def test_off_by_default(self):
        assert tracing.get() is None

    def test_sampling_cadence(self, tmp_path):
        tr = tracing.configure(str(tmp_path / "t.jsonl"), sample_n=4)
        hits = sum(tr.should_sample() for _ in range(16))
        assert hits == 4

    def test_writer_round_trip_and_close_drains(self, tmp_path):
        reg = telemetry.Registry()
        path = str(tmp_path / "t.jsonl")
        tr = tracing.configure(path, sample_n=1, registry=reg)
        tr.emit("publish", version=9)
        tr.emit_chunk(_mk_record())
        tracing.shutdown()
        events = [json.loads(l) for l in telemetry.load_jsonl(path)]
        assert [e["event"] for e in events] == ["publish", "chunk"]
        assert events[1]["origin_pid"] == os.getpid()
        assert reg.counter("trace/emitted_total").value == 2.0

    def test_emit_chunk_snapshots_hops(self, tmp_path):
        """The emitted event must not alias the live record — downstream
        hop appends (the in-proc delivery path) race the writer thread's
        serialization otherwise."""
        path = str(tmp_path / "t.jsonl")
        tr = tracing.configure(path, sample_n=1)
        rec = _mk_record()
        tr.emit_chunk(rec)
        rec["hops"].append(["admit", 12.0])   # post-emit mutation
        tracing.shutdown()
        (ev,) = [json.loads(l) for l in telemetry.load_jsonl(path)]
        assert [h[0] for h in ev["hops"]] == ["collect", "encode"]

    def test_bounded_queue_drops_and_counts(self, tmp_path):
        reg = telemetry.Registry()
        w = tracing.TraceWriter(str(tmp_path / "t.jsonl"), registry=reg)
        w.close()            # writer thread provably exited
        w._stopped = False   # re-arm enqueue with NO drainer: deterministic
        w._queue.extend(
            {"event": "x"} for _ in range(tracing.TraceWriter.MAX_QUEUE)
        )
        w.enqueue({"event": "overflow"})
        assert reg.counter("trace/dropped_total").value == 1.0
        assert len(w._queue) == tracing.TraceWriter.MAX_QUEUE

    def test_torn_trailing_line_tolerated(self, tmp_path):
        path = str(tmp_path / "t.jsonl")
        with open(path, "w") as f:
            f.write('{"event": "a"}\n{"event": "b"}\n{"event": "tor')
        lines = telemetry.load_jsonl(path)
        assert len(lines) == 2
        # and the schema validator reads through the SAME tolerant loader
        assert _schema_module().load_jsonl is telemetry.load_jsonl


class TestJsonlSinkDurability:
    def test_every_emit_is_flushed_line(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        sink = telemetry.JsonlSink(path)
        sink.emit(1, {"a": 1.0})
        # flushed WITHOUT close: a reader (or a post-SIGKILL autopsy)
        # sees the full line immediately
        assert telemetry.load_jsonl(path)
        sink.close()
        assert len(telemetry.load_jsonl(path)) == 1


class TestWireMarkers:
    def arrays(self):
        return {
            "obs": {"x": np.arange(12, dtype=np.float32).reshape(3, 4)},
            "rewards": np.ones(3, np.float32),
        }

    def test_trace_rides_both_codecs(self):
        blob = tracing.record_to_blob(_mk_record())
        arrays = self.arrays()
        native = bytes(S.encode_rollout_bytes(arrays, 1, 2, 3, 3, 0.0,
                                              trace=blob))
        proto = S.encode_rollout(arrays, 1, 2, 3, 3, 0.0,
                                 trace=blob).SerializeToString()
        for wire, native_flag in ((native, True), (proto, False)):
            meta, out = S.decode_rollout_bytes(wire, native=native_flag)
            rec = tracing.parse_blob(meta["trace_blob"])
            assert rec["tid"] == "a-1-1"
            np.testing.assert_array_equal(
                np.asarray(out["rewards"]), arrays["rewards"]
            )

    def test_untraced_frames_carry_no_marker(self):
        meta, _ = S.decode_rollout_bytes(
            bytes(S.encode_rollout_bytes(self.arrays(), 1, 2, 3, 3, 0.0))
        )
        assert "trace_blob" not in meta

    def test_weights_marker_round_trip_and_skip(self):
        blob = tracing.record_to_blob(tracing.weights_record(5), pad=False)
        msg = S.encode_weights({"w": np.ones(4, np.float32)}, 5, trace=blob)
        assert S.weights_trace(msg) == blob
        version, tree = S.decode_weights(msg)
        # the marker must never surface as a param leaf
        assert version == 5 and list(tree) == ["w"]
        assert S.weights_trace(S.encode_weights({"w": np.ones(2)}, 1)) is None

    def test_decode_drained_stamps_hops_only_when_tracing(self, tmp_path):
        blob = tracing.record_to_blob(_mk_record())
        wire = bytes(
            S.encode_rollout_bytes(self.arrays(), 1, 2, 3, 3, 0.0,
                                   trace=blob)
        )
        reg = telemetry.Registry()
        # tracing OFF: raw blob is carried but never parsed/stamped
        out, bad = S.decode_drained_payloads([(123.0, wire)], reg, [0, 0])
        assert bad == 0 and "trace" not in out[0][0]
        # tracing ON: recv + consume hops land on the host record
        tracing.configure(str(tmp_path / "t.jsonl"), sample_n=1)
        out, _ = S.decode_drained_payloads([(123.0, wire)], reg, [0, 0])
        rec = out[0][0]["trace"]
        names = [h[0] for h in rec["hops"]]
        assert names == ["collect", "encode", "recv", "consume"]
        assert rec["hops"][2][1] == 123.0
        # bare (untupled) payloads stay accepted — recv hop simply absent
        out, _ = S.decode_drained_payloads([wire], reg, [0, 0])
        assert [h[0] for h in out[0][0]["trace"]["hops"]] == [
            "collect", "encode", "consume",
        ]


class TestBufferTraceFlow:
    def _cfg(self):
        import dataclasses

        from dotaclient_tpu.config import default_config

        cfg = default_config()
        return dataclasses.replace(
            cfg,
            env=dataclasses.replace(cfg.env, n_envs=4, max_dota_time=60.0),
            ppo=dataclasses.replace(
                cfg.ppo, rollout_len=8, batch_rollouts=8
            ),
            buffer=dataclasses.replace(
                cfg.buffer, capacity_rollouts=16, min_fill=8
            ),
        )

    def _rollouts(self, cfg, n=8, traced=True):
        from dotaclient_tpu.train.ppo import example_batch

        row = jax.tree.map(
            lambda x: np.asarray(x[0]), example_batch(cfg, batch=1)
        )
        out = []
        for i in range(n):
            meta = {"model_version": 0, "env_id": 0, "rollout_id": i,
                    "length": 8, "total_reward": 0.0}
            if traced:
                meta["trace"] = _mk_record(tid=f"t-{i}", wv=0)
            out.append((meta, jax.tree.map(np.copy, row)))
        return out

    def test_admit_gather_hops_and_drain(self, tmp_path):
        from dotaclient_tpu.buffer.trajectory_buffer import TrajectoryBuffer
        from dotaclient_tpu.parallel import make_mesh

        cfg = self._cfg()
        tracing.configure(str(tmp_path / "t.jsonl"), sample_n=1)
        buf = TrajectoryBuffer(cfg, make_mesh(cfg.mesh))
        assert buf.add(self._rollouts(cfg), 0) == 8
        assert buf.take(batch_size=8) is not None
        traces = buf.drain_traces()
        assert len(traces) == 8
        for rec in traces:
            assert [h[0] for h in rec["hops"]] == [
                "collect", "encode", "admit", "gather",
            ]
        assert buf.drain_traces() == []   # drained exactly once

    def test_tracing_off_costs_one_pointer_test(self):
        """The utils/faults.py discipline, pinned: with no tracer the
        buffer allocates NO per-slot trace state and take() parks
        nothing — the hot path's entire cost is `self._tracer is None`."""
        from dotaclient_tpu.buffer.trajectory_buffer import TrajectoryBuffer
        from dotaclient_tpu.parallel import make_mesh

        assert tracing.get() is None
        cfg = self._cfg()
        buf = TrajectoryBuffer(cfg, make_mesh(cfg.mesh))
        assert buf._tracer is None and buf._slot_trace is None
        buf.add(self._rollouts(cfg, traced=False), 0)
        assert buf.take(batch_size=8) is not None
        assert buf.drain_traces() == []


class TestInstrumentJit:
    def test_compile_retrace_counters_and_cost_once(self, tmp_path):
        reg = telemetry.Registry()
        path = str(tmp_path / "t.jsonl")
        tracing.configure(path, sample_n=1, registry=reg)
        fn = tracing.instrument_jit(
            jax.jit(lambda x: x + 1), "train_step", reg
        )
        out = fn(np.zeros((3,), np.float32))
        assert np.asarray(out).shape == (3,)
        snap = reg.snapshot()
        assert snap["compile/compiles_total"] == 1.0
        assert snap["compile/retraces_total"] == 0.0
        assert snap["compile/train_step/compiles_total"] == 1.0
        fn(np.ones((3,), np.float32))   # cache hit: no new compile
        assert reg.snapshot()["compile/compiles_total"] == 1.0
        # the acceptance pin: a shape bump retraces and is COUNTED
        fn(np.zeros((4,), np.float32))
        snap = reg.snapshot()
        assert snap["compile/compiles_total"] == 2.0
        assert snap["compile/retraces_total"] == 1.0
        assert snap["compile/train_step/retraces_total"] == 1.0
        assert snap["compile/compile_time_s_total"] > 0.0
        tracing.shutdown()
        compiles = [
            json.loads(l)
            for l in telemetry.load_jsonl(path)
            if json.loads(l)["event"] == "compile"
        ]
        # cost analysis logged once PER COMPILE, never per step:
        # 3 calls, 2 compiles, exactly 2 events
        assert len(compiles) == 2
        assert all(ev["program"] == "train_step" for ev in compiles)

    def test_delegates_introspection(self):
        fn = tracing.instrument_jit(jax.jit(lambda x: x * 2), "snap_copy",
                                    telemetry.Registry())
        lowered = fn.lower(np.zeros((2,), np.float32))
        assert lowered is not None   # .lower reaches the wrapped jit

    def test_memory_gauge_degrades_on_cpu(self):
        reg = telemetry.Registry()
        peak = tracing.update_memory_gauges(reg)
        # CPU backend: no allocator stats → 0, but the key EXISTS
        assert "mem/hbm_peak_bytes" in reg.snapshot()
        assert peak >= 0.0


class TestSchemaTier:
    def test_require_trace_tier(self):
        schema = _schema_module()
        reg = telemetry.Registry()
        tracing.ensure_metrics(reg)
        scalars = dict(reg.snapshot())
        line = json.dumps({"ts": 1.0, "step": 0, "scalars": scalars})
        errs = schema.validate_lines(
            [line], extra_required=schema.TRACE_KEYS, base_required=()
        )
        assert errs == []
        scalars.pop("compile/retraces_total")
        line = json.dumps({"ts": 1.0, "step": 0, "scalars": scalars})
        errs = schema.validate_lines(
            [line], extra_required=schema.TRACE_KEYS, base_required=()
        )
        assert any("compile/retraces_total" in e for e in errs)


class TestTraceReport:
    """The tier-1 pin of the acceptance flow, on canned logs: an 'actor'
    log with partial records (one SIGKILL-torn), a 'learner' log with the
    complete timelines + publish events, one 'apply' event — the merge
    must produce the histogram, the critical path, and the staleness
    attribution."""

    def _write_canned(self, tmp_path):
        t0 = 1000.0
        actor_pid, learner_pid = 111, 222
        actor_lines = []
        learner_lines = [
            json.dumps({"ts": t0, "pid": learner_pid, "event": "publish",
                        "version": 3}),
            json.dumps({"ts": t0 + 0.01, "pid": actor_pid, "event": "apply",
                        "version": 3, "publish_ts": t0}),
        ]
        for i in range(6):
            base = t0 + 0.02 + i * 0.1
            hops = [
                ["collect", base], ["encode", base + 0.050],
            ]
            full = hops + [
                ["recv", base + 0.055], ["consume", base + 0.060],
                ["admit", base + 0.062], ["gather", base + 0.080],
                ["dispatch", base + 0.090],
            ]
            actor_lines.append(json.dumps(
                {"ts": base, "pid": actor_pid, "event": "chunk",
                 "tid": f"c-{i}", "origin_pid": actor_pid, "actor": 1,
                 "wv": 3, "hops": hops}
            ))
            learner_lines.append(json.dumps(
                {"ts": base, "pid": learner_pid, "event": "chunk",
                 "tid": f"c-{i}", "origin_pid": actor_pid, "actor": 1,
                 "wv": 3, "hops": full}
            ))
        # a serve client's round-trip record shares the log directory: it
        # carries encode/recv hops too, but must NOT contaminate the
        # rollout pipeline's wire segment or chunk counts (review fix)
        serve_pid = 333
        learner_lines.append(json.dumps(
            {"ts": t0 + 2.0, "pid": serve_pid, "event": "chunk",
             "tid": "s-0", "origin_pid": serve_pid, "actor": 0, "wv": 3,
             "hops": [["encode", t0 + 2.0], ["recv", t0 + 9.0],
                      ["reply", t0 + 9.001], ["done", t0 + 9.002]]}
        ))
        apath = tmp_path / "actor0.trace.jsonl"
        lpath = tmp_path / "learner.trace.jsonl"
        # the actor was SIGKILLed mid-line: torn tail, no newline
        apath.write_text("\n".join(actor_lines) + "\n" + '{"event": "to')
        lpath.write_text("\n".join(learner_lines) + "\n")
        return str(tmp_path), 111

    def test_merged_report(self, tmp_path):
        from scripts.trace_report import build_report

        run_dir, actor_pid = self._write_canned(tmp_path)
        rep = build_report([run_dir])
        assert rep["chunks_complete"] == 6
        assert rep["origin_pids"] == [actor_pid]
        # the SIGKILL-torn tail was dropped by the tolerant loader before
        # parsing — it neither errors nor becomes a phantom event
        assert rep["lines_skipped"] == 0 and rep["chunks_seen"] == 6
        # (a) the end-to-end histogram
        assert rep["e2e_latency_s"]["n"] == 6
        assert abs(rep["e2e_latency_s"]["mean"] - 0.090) < 1e-6
        assert rep["e2e_histogram"]
        # (b) the per-hop critical-path breakdown
        cp = rep["critical_path"]
        for segment in ("actor compute", "wire", "drain wait",
                        "admission", "ring residency", "dispatch wait"):
            assert cp[segment]["n"] == 6, segment
        assert abs(cp["actor compute"]["mean"] - 0.050) < 1e-6
        # the serve record's 7s encode→recv gap must NOT bleed into the
        # pipeline's wire segment (it is reported under serve RTTs)
        assert abs(cp["wire"]["mean"] - 0.005) < 1e-6
        assert rep["serve"]["rtt_s"]["n"] == 1
        # (c) the staleness attribution table
        st = rep["staleness"]
        # per-CHUNK attribution: every traced chunk contributes one sample
        # per component it can close (all six collected under version 3)
        assert st["components"]["publish→apply (fanout)"]["n"] == 6
        assert st["components"]["apply→encode (actor hold)"]["n"] == 6
        assert st["dominant"] is not None
        assert st["weights_age_at_dispatch_s"]["n"] == 6

    def test_cli_json_mode(self, tmp_path, capsys):
        from scripts.trace_report import main as report_main

        run_dir, _ = self._write_canned(tmp_path)
        assert report_main(["--json", run_dir]) == 0
        out = capsys.readouterr().out
        line = [l for l in out.splitlines() if l.startswith("TRACE_REPORT ")]
        assert line and json.loads(line[0][len("TRACE_REPORT "):])[
            "chunks_complete"
        ] == 6

    def test_empty_input_exits_nonzero(self, tmp_path):
        from scripts.trace_report import main as report_main

        (tmp_path / "empty.jsonl").write_text("")
        assert report_main(["--json", str(tmp_path)]) == 1
