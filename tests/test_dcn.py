"""Multi-slice (DCN) mesh tests on the forced 8-device host platform.

SURVEY.md §7 step 8 ("multi-slice DCN mesh") and §5.8: across slices the
batch/gradient traffic crosses the ``dcn`` mesh axis; GSPMD's math must be
invariant to how the devices are factored. The pin mirrors the 1-vs-8
data-parallel golden test: one train step on a (dcn=2, data=2, model=2)
mesh must equal the same step on the flat (data=4, model=2) mesh.
"""

import dataclasses

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from dotaclient_tpu.config import MeshConfig, default_config
from dotaclient_tpu.models import init_params, make_policy
from dotaclient_tpu.parallel import batch_axes, data_sharding, make_mesh
from dotaclient_tpu.train.ppo import (
    example_batch,
    init_train_state,
    make_train_step,
)


def small_cfg(mesh: MeshConfig):
    cfg = default_config()
    return dataclasses.replace(
        cfg,
        model=dataclasses.replace(cfg.model, dtype="float32"),
        ppo=dataclasses.replace(cfg.ppo, rollout_len=4, batch_rollouts=8),
        mesh=mesh,
    )


class TestDcnMesh:
    def test_mesh_shape_and_batch_axes(self):
        mc = MeshConfig(dcn_slices=2, model_parallel=2, data_parallel=-1)
        mesh = make_mesh(mc, devices=jax.devices()[:8])
        assert dict(mesh.shape) == {"dcn": 2, "data": 2, "model": 2}
        assert batch_axes(mesh, mc) == ("dcn", "data")
        assert data_sharding(mesh, mc).spec == P(("dcn", "data"))

    def test_flat_mesh_has_no_dcn_axis(self):
        mc = MeshConfig()
        mesh = make_mesh(mc, devices=jax.devices()[:8])
        assert batch_axes(mesh, mc) == ("data",)

    def test_indivisible_raises(self):
        with pytest.raises(ValueError, match="not divisible"):
            make_mesh(
                MeshConfig(dcn_slices=3), devices=jax.devices()[:8]
            )

    def test_train_step_dcn_equals_flat(self):
        """(dcn=2, data=2, model=2) ≡ (data=4, model=2): same devices, same
        math, different factorization — losses must match to fp tolerance."""
        flat_cfg = small_cfg(MeshConfig(model_parallel=2, data_parallel=-1))
        dcn_cfg = small_cfg(
            MeshConfig(dcn_slices=2, model_parallel=2, data_parallel=-1)
        )
        policy = make_policy(flat_cfg.model, flat_cfg.obs, flat_cfg.actions)
        params = init_params(policy, jax.random.PRNGKey(0))

        losses = {}
        for name, cfg in (("flat", flat_cfg), ("dcn", dcn_cfg)):
            mesh = make_mesh(cfg.mesh, devices=jax.devices()[:8])
            state = init_train_state(params, cfg.ppo)
            step = make_train_step(policy, cfg, mesh)
            batch = example_batch(cfg, batch=cfg.ppo.batch_rollouts)
            state, metrics = step(state, batch)
            # one more step so optimizer-state divergence would also show
            _, metrics = step(state, batch)
            losses[name] = float(np.asarray(metrics["loss"]))
        assert np.isfinite(losses["flat"])
        np.testing.assert_allclose(losses["flat"], losses["dcn"], rtol=1e-5)

    def test_buffer_shards_over_dcn_and_data(self):
        from dotaclient_tpu.buffer.trajectory_buffer import TrajectoryBuffer

        cfg = small_cfg(
            MeshConfig(dcn_slices=2, model_parallel=1, data_parallel=-1)
        )
        cfg = dataclasses.replace(
            cfg,
            buffer=dataclasses.replace(
                cfg.buffer, capacity_rollouts=16, min_fill=8
            ),
        )
        mesh = make_mesh(cfg.mesh, devices=jax.devices()[:8])
        buf = TrajectoryBuffer(cfg, mesh)
        leaf = jax.tree.leaves(buf._store)[0]
        assert leaf.sharding.spec == P(("dcn", "data"))


class TestInitializeRuntime:
    def test_single_process_idempotent(self):
        """Must run in a process that has not touched a backend yet (the
        production constraint), so: fresh subprocess, init twice, report."""
        import json
        import os
        import subprocess
        import sys

        port = 20000 + os.getpid() % 20000   # concurrent runs must not collide
        code = (
            "import json\n"
            "from dotaclient_tpu.parallel import initialize_runtime, process_info\n"
            f"initialize_runtime('127.0.0.1:{port}', 1, 0)\n"
            f"initialize_runtime('127.0.0.1:{port}', 1, 0)\n"
            "print(json.dumps(process_info()))\n"
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, env=env, timeout=120,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        assert out.returncode == 0, out.stderr[-2000:]
        info = json.loads(out.stdout.strip().splitlines()[-1])
        assert info["process_index"] == 0
        assert info["process_count"] == 1
