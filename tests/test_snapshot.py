"""Zero-stall snapshot engine tests (ISSUE 5).

Pins the contract of train/snapshot.py and its learner integration:
published versions stay MONOTONIC under latest-wins coalescing, a graceful
stop with a snapshot in flight still lands the forced checkpoint at the
EXACT stop step, an async write failure surfaces as a counted degrade
(checkpoint/save_failures_total) without killing the run, restored state is
identical between sync- and async-snapshot runs of the same seed, the train
thread performs no log-boundary device fetches in async mode, and the
--require-snapshot schema tier validates.
"""

import dataclasses
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dotaclient_tpu.config import LearnerConfig, ModelConfig, RunConfig
from dotaclient_tpu.train.snapshot import SnapshotEngine
from dotaclient_tpu.utils import telemetry


def tiny_config(**over) -> RunConfig:
    cfg = RunConfig()
    fields = dict(
        model=ModelConfig(unit_embed_dim=8, hidden_dim=8, hero_embed_dim=4),
        env=dataclasses.replace(cfg.env, n_envs=2, max_dota_time=30.0),
        ppo=dataclasses.replace(cfg.ppo, rollout_len=8, batch_rollouts=8),
        buffer=dataclasses.replace(
            cfg.buffer, capacity_rollouts=32, min_fill=8
        ),
        checkpoint_every=10_000,
        log_every=10_000,
    )
    fields.update(over)
    return dataclasses.replace(cfg, **fields)


def wait_until(pred, timeout=120.0, poll=0.01):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(poll)
    return pred()


class _RecordingTransport:
    """publish_weights sink that optionally sleeps (to force coalescing)."""

    def __init__(self, delay_s: float = 0.0) -> None:
        self.versions = []
        self.delay_s = delay_s

    def publish_weights(self, msg) -> None:
        if self.delay_s:
            time.sleep(self.delay_s)
        self.versions.append(int(msg.version))


class TestEngineOrdering:
    def test_monotonic_versions_under_coalescing(self):
        """A slow consumer forces the publish slot to coalesce; the wire
        must still see strictly increasing versions ending at the newest —
        never a duplicate, regression, or lost-final."""
        reg = telemetry.Registry()
        sink = _RecordingTransport(delay_s=0.02)
        eng = SnapshotEngine(transport=sink, registry=reg)
        params = {"w": jnp.ones((8,), jnp.float32)}
        try:
            for v in range(1, 40):
                eng.submit_publish(jax.tree.map(jnp.copy, params), v)
            assert eng.drain(timeout=60)
        finally:
            eng.stop()
        vs = sink.versions
        assert vs, "nothing was published"
        assert vs == sorted(set(vs)), f"non-monotonic versions: {vs}"
        assert vs[-1] == 39, "latest-wins must keep the NEWEST version"
        # 39 submissions against a 20ms consumer: some must have coalesced
        assert len(vs) < 39
        assert reg.counter("snapshot/publish_coalesced").value > 0

    def test_stale_resubmit_is_skipped(self):
        """A version at or below the last published one (a drain/tail
        overlap re-submit) must be a no-op on the wire."""
        reg = telemetry.Registry()
        sink = _RecordingTransport()
        eng = SnapshotEngine(transport=sink, registry=reg)
        params = {"w": jnp.zeros((4,), jnp.float32)}
        try:
            eng.submit_publish(jax.tree.map(jnp.copy, params), 5)
            assert eng.drain(timeout=30)
            eng.submit_publish(jax.tree.map(jnp.copy, params), 5)
            eng.submit_publish(jax.tree.map(jnp.copy, params), 3)
            assert eng.drain(timeout=30)
        finally:
            eng.stop()
        assert sink.versions == [5]

    def test_stats_backlog_never_coalesces(self):
        """Stat drains are destructive at submit (the device accumulators
        reset) — every submitted drain MUST be folded even while metrics
        log jobs coalesce around them, and before the surviving log job."""
        reg = telemetry.Registry()
        eng = SnapshotEngine(transport=_RecordingTransport(), registry=reg)
        folded = []
        logged = []
        try:
            for i in range(10):
                eng.submit_stats(
                    {"episodes": jnp.asarray(float(i))},
                    lambda s, i=i: folded.append(i),
                )
                eng.submit_metrics(
                    {"m": {}}, lambda host, i=i: logged.append(i)
                )
            assert eng.drain(timeout=60)
        finally:
            eng.stop()
        assert folded == list(range(10)), (
            f"stat windows lost or reordered: {folded}"
        )
        # the NEWEST log always survives; older ones may coalesce away
        assert logged and logged[-1] == 9

    def test_engine_survives_job_errors(self):
        """A failing publish is counted, not fatal: the next job runs."""
        reg = telemetry.Registry()

        class Exploding:
            def __init__(self):
                self.calls = 0

            def publish_weights(self, msg):
                self.calls += 1
                if self.calls == 1:
                    raise OSError("injected fanout failure")

        sink = Exploding()
        eng = SnapshotEngine(transport=sink, registry=reg)
        params = {"w": jnp.zeros((4,), jnp.float32)}
        try:
            eng.submit_publish(jax.tree.map(jnp.copy, params), 1)
            assert eng.drain(timeout=30)
            eng.submit_publish(jax.tree.map(jnp.copy, params), 2)
            assert eng.drain(timeout=30)
        finally:
            eng.stop()
        assert sink.calls == 2
        assert reg.counter("snapshot/errors_total").value == 1


class TestStopDrain:
    @pytest.mark.slow   # full-Learner train loops: > the 5s tier-1 duration budget
    def test_exact_step_checkpoint_on_stop_with_snapshots_in_flight(
        self, tmp_path
    ):
        """Graceful stop while async periodic saves are still streaming:
        the drain + forced sync save must land at the EXACT stop step
        (checkpoint_every=1 keeps a snapshot in flight essentially always,
        exercising the coalescing + drain path hard)."""
        from dotaclient_tpu.train.learner import Learner
        from dotaclient_tpu.utils.checkpoint import CheckpointManager

        cfg = tiny_config(checkpoint_every=1, log_every=1)
        ckdir = str(tmp_path / "ck")
        learner = Learner(cfg, checkpoint_dir=ckdir, actor="vec")
        assert learner._snap_engine is not None  # async is the default
        result = {}

        def run():
            result["stats"] = learner.train(500)

        t = threading.Thread(target=run, daemon=True)
        t.start()
        assert wait_until(lambda: learner._host_step >= 2, timeout=120)
        learner.request_stop()
        t.join(timeout=120)
        assert not t.is_alive(), "graceful stop did not drain"
        stopped_at = result["stats"]["optimizer_steps"]
        assert 0 < stopped_at < 500
        mgr = CheckpointManager(ckdir)
        try:
            assert mgr.latest_step() == int(stopped_at)
        finally:
            mgr.close()

    @pytest.mark.slow   # full-Learner train loops: > the 5s tier-1 duration budget
    def test_async_write_failure_surfaces_as_counted_degrade(self, tmp_path):
        """A periodic async save that hits an I/O error degrades through
        checkpoint/save_failures_total (training continues) and the forced
        end-of-run save still lands."""
        from dotaclient_tpu.train.learner import Learner
        from dotaclient_tpu.utils.checkpoint import CheckpointManager

        reg = telemetry.get_registry()
        cfg = tiny_config(checkpoint_every=1)
        learner = Learner(
            cfg, checkpoint_dir=str(tmp_path / "ck"), actor="vec"
        )
        before = reg.counter("checkpoint/save_failures_total").value
        real_save = learner.ckpt._mgr.save
        fails = {"n": 0}

        def flaky_save(step, *a, **kw):
            # exactly ONE failure: the engine's first periodic save eats it
            # (the tail drains the engine before its forced save, so the
            # forced save always comes later and must succeed)
            if fails["n"] < 1:
                fails["n"] += 1
                raise OSError("simulated full disk (async write)")
            return real_save(step, *a, **kw)

        learner.ckpt._mgr.save = flaky_save
        stats = learner.train(4)
        assert stats["optimizer_steps"] == 4, "run must survive the failure"
        after = reg.counter("checkpoint/save_failures_total").value
        assert after - before >= 1, "degrade was not counted"
        mgr = CheckpointManager(str(tmp_path / "ck"))
        try:
            # the forced tail save (sync path, monkeypatch exhausted) landed
            assert mgr.latest_step() == 4
        finally:
            mgr.close()


class TestSyncAsyncParity:
    @pytest.mark.slow   # full-Learner train loops: > the 5s tier-1 duration budget
    def test_restored_state_parity(self, tmp_path):
        """Same seed, same steps: a sync-snapshots run and an async run
        must checkpoint IDENTICAL params at the same step — async changes
        when the fetch happens, never what is saved."""
        from dotaclient_tpu.train.learner import Learner
        from dotaclient_tpu.utils.checkpoint import CheckpointManager

        steps = 3
        restored = {}
        for label, async_on in (("sync", False), ("async", True)):
            # boundaries every step: both the periodic-save and the metrics
            # paths run in their respective modes, not just the tail
            cfg = tiny_config(
                checkpoint_every=1,
                log_every=1,
                learner=LearnerConfig(async_snapshots=async_on),
            )
            ckdir = str(tmp_path / label)
            learner = Learner(cfg, checkpoint_dir=ckdir, seed=7, actor="vec")
            learner.train(steps)
            mgr = CheckpointManager(ckdir)
            try:
                params, step = mgr.restore_weights()
            finally:
                mgr.close()
            assert step == steps
            restored[label] = params
        flat_sync = jax.tree.leaves(restored["sync"])
        flat_async = jax.tree.leaves(restored["async"])
        assert len(flat_sync) == len(flat_async)
        for a, b in zip(flat_sync, flat_async):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestTrainThreadDiscipline:
    @pytest.mark.slow   # full-Learner train loops: > the 5s tier-1 duration budget
    def test_log_boundaries_do_not_sync_the_train_thread(self, monkeypatch):
        """Async mode: device fetches made ON the train thread must not
        scale with the number of log boundaries — the fetch moved to the
        snapshot thread (the per-call tail drain is a constant)."""
        from dotaclient_tpu.train.learner import Learner

        learner = Learner(tiny_config(log_every=1), actor="device")
        assert learner._snap_engine is not None
        learner.train(1)   # compile + warm

        train_thread = threading.current_thread()
        calls = {"train_thread": 0}
        real_device_get = jax.device_get

        def counting_device_get(x):
            if threading.current_thread() is train_thread:
                calls["train_thread"] += 1
            return real_device_get(x)

        monkeypatch.setattr(jax, "device_get", counting_device_get)
        learner.train(2)
        first = calls["train_thread"]
        calls["train_thread"] = 0
        learner.train(6)
        second = calls["train_thread"]
        assert first == second, (
            f"train-thread fetches scale with boundaries ({first} vs "
            f"{second}) — a boundary side effect is syncing the train thread"
        )


class TestSnapshotSchemaTier:
    def test_require_snapshot_tier_validates(self):
        import importlib.util
        import json as _json
        import os

        spec = importlib.util.spec_from_file_location(
            "check_telemetry_schema",
            os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "scripts",
                "check_telemetry_schema.py",
            ),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)

        base = {k: 0.0 for k in mod.REQUIRED_KEYS}
        # any span root present must carry the full stat leaf set
        for k in mod.REQUIRED_KEYS:
            if k.startswith("span/"):
                root = k.rsplit("/", 1)[0]
                base.update({f"{root}/{leaf}": 0.0 for leaf in mod.TIMER_LEAVES})
        line_ok = _json.dumps(
            {
                "ts": 1.0,
                "step": 1,
                "scalars": {**base, **{k: 0.0 for k in mod.SNAPSHOT_KEYS}},
            }
        )
        assert not mod.validate_lines(
            [line_ok], extra_required=mod.SNAPSHOT_KEYS
        )
        line_missing = _json.dumps({"ts": 1.0, "step": 1, "scalars": base})
        errs = mod.validate_lines(
            [line_missing], extra_required=mod.SNAPSHOT_KEYS
        )
        assert any("snapshot/pending" in e for e in errs)

    def test_learner_eager_creates_snapshot_keys_without_engine(self):
        """A clean SYNC-mode run must still report zeros for the snapshot
        keys (the --require-snapshot tier is unconditional); the engine
        side of the eager-create is covered by TestEngineOrdering's
        registry assertions."""
        from dotaclient_tpu.train.learner import Learner

        telemetry.get_registry().clear()
        Learner(
            tiny_config(learner=LearnerConfig(async_snapshots=False)),
            actor="vec",
        )
        snap = telemetry.get_registry().snapshot()
        for key in (
            "snapshot/pending",
            "snapshot/d2h_ms",
            "learner/publish_stall_ms",
            "learner/stall_fraction",
        ):
            assert key in snap, f"{key} not eager-created in sync mode"
