"""Shared-memory same-host transport lane tests (ISSUE 3).

Covers the SPSC ring (FIFO, wraparound, drop-newest-when-full, the
deferred-release zero-copy contract), the seqlock'd weights slab
(latest-wins, torn-read retry surface), slot claim/release, and the
Transport-protocol parity the learner relies on (consume_decoded feeding
the buffer's staging lanes). Everything runs in-process — attach works
within one process, and the cross-process path is exercised by bench.py's
transport stage and the producer script."""

import os

import numpy as np
import pytest

from dotaclient_tpu.transport import (
    ShmTransport,
    ShmTransportServer,
    encode_rollout,
    encode_rollout_bytes,
    encode_weights,
)


def lane_name(tag: str) -> str:
    return f"t-shm-{os.getpid()}-{tag}"


def make_lane(tag, slots=2, ring_bytes=1 << 16, weights_bytes=1 << 20):
    server = ShmTransportServer(
        name=lane_name(tag), slots=slots, ring_bytes=ring_bytes,
        weights_bytes=weights_bytes,
    )
    actor = ShmTransport(lane_name(tag), slots=slots)
    return server, actor


def tiny_rollout(rid=0, n=16):
    return encode_rollout(
        {"rewards": np.arange(n, dtype=np.float32) + rid},
        model_version=0, env_id=0, rollout_id=rid, length=n,
        total_reward=0.0,
    )


class TestRolloutRing:
    def test_fifo_exactly_once(self):
        server, actor = make_lane("fifo")
        try:
            for i in range(7):
                actor.publish_rollout(tiny_rollout(i))
            got = server.consume_rollouts(64, timeout=1.0)
            assert [r.rollout_id for r in got] == list(range(7))
            assert server.consume_rollouts(64, timeout=0.01) == []
        finally:
            actor.close()
            server.close()

    def test_wraparound_many_laps(self):
        """Frames must survive the ring edge: ship several ring-sizes worth
        of data through a small ring, draining between bursts."""
        server, actor = make_lane("wrap", ring_bytes=1 << 14)  # 16 KiB ring
        try:
            sent = 0
            received = []
            for wave in range(40):
                for _ in range(3):
                    msg = tiny_rollout(sent, n=200)   # ~800B+ frames
                    assert actor.publish_rollout_bytes(
                        msg.SerializeToString()
                    )
                    sent += 1
                received.extend(server.consume_rollouts(16, timeout=1.0))
            received.extend(server.consume_rollouts(16, timeout=0.2))
            assert [r.rollout_id for r in received] == list(range(sent))
        finally:
            actor.close()
            server.close()

    def test_drop_newest_when_full_is_counted(self):
        server, actor = make_lane("full", ring_bytes=1 << 12)  # 4 KiB ring
        try:
            wire = tiny_rollout(0, n=200).SerializeToString()   # ~860B
            sent = sum(
                1 for _ in range(20)
                if actor.publish_rollout_bytes(wire)
            )
            assert 0 < sent < 20          # ring filled, surplus dropped
            # producer-side drop counter is in the ring header
            assert server.pending_rollouts == sent
            got = server.consume_rollouts(64, timeout=1.0)
            assert len(got) == sent
            # after draining+release, publishing works again
            server.consume_rollouts(1, timeout=0.01)   # releases prior batch
            assert actor.publish_rollout_bytes(wire)
        finally:
            actor.close()
            server.close()

    def test_deferred_release_protects_inflight_views(self):
        """The zero-copy contract: frames handed out by a drain must stay
        intact while the producer keeps writing — their ring space is only
        released at the NEXT drain."""
        server, actor = make_lane("views", ring_bytes=1 << 14)
        try:
            wire = bytes(tiny_rollout(1, n=500).SerializeToString())
            n_fit = 0
            while actor.publish_rollout_bytes(wire):
                n_fit += 1
            views = server._drain(n_fit, timeout=1.0)
            assert len(views) == n_fit
            # ring is logically empty but unreleased: the producer must
            # still see it as full and drop, not overwrite the views
            # (items are (recv_ts, view) pairs since ISSUE 12)
            assert not actor.publish_rollout_bytes(wire)
            assert all(bytes(v) == wire for _ts, v in views)
        finally:
            actor.close()
            server.close()

    def test_consume_decoded_roundtrip(self):
        """The learner-ingest path: zero-copy drain → native decoder views
        → values bit-identical to what the actor shipped."""
        server, actor = make_lane("dec", ring_bytes=1 << 20)
        try:
            tree = {
                "obs": {"units": np.random.default_rng(0)
                        .normal(size=(9, 8, 4)).astype(np.float32)},
                "rewards": np.arange(8, dtype=np.float32),
            }
            actor.publish_rollout_bytes(
                encode_rollout_bytes(tree, 5, 0, 77, 8, 1.25)
            )
            out = server.consume_decoded(8, timeout=1.0)
            assert len(out) == 1
            meta, arrays = out[0]
            assert meta["model_version"] == 5
            assert meta["rollout_id"] == 77
            np.testing.assert_array_equal(
                arrays["obs"]["units"], tree["obs"]["units"]
            )
            np.testing.assert_array_equal(arrays["rewards"], tree["rewards"])
        finally:
            actor.close()
            server.close()


class TestWeightsSlab:
    def test_latest_wins_and_cache(self):
        server, actor = make_lane("w")
        try:
            assert actor.latest_weights() is None
            for v in (1, 2, 3):
                server.publish_weights(
                    encode_weights({"w": np.full(4, float(v), np.float32)}, v)
                )
            msg = actor.latest_weights()
            assert msg.version == 3
            # unchanged slab: the cached parse is returned, not re-read
            assert actor.latest_weights() is msg
        finally:
            actor.close()
            server.close()

    def test_bf16_wire_through_slab(self):
        server, actor = make_lane("wb")
        try:
            from dotaclient_tpu.transport import decode_weights

            params = {"k": np.linspace(0, 1, 9, dtype=np.float32)}
            server.publish_weights(
                encode_weights(params, 4, wire_dtype="bfloat16")
            )
            version, tree = decode_weights(actor.latest_weights())
            assert version == 4
            assert tree["k"].dtype == np.float32    # upcast on apply
        finally:
            actor.close()
            server.close()

    def test_oversized_weights_rejected(self):
        server, actor = make_lane("wo", weights_bytes=1 << 10)
        try:
            with pytest.raises(ValueError, match="shm_weights_bytes"):
                server.publish_weights(
                    encode_weights(
                        {"w": np.zeros(4096, np.float32)}, 1
                    )
                )
        finally:
            actor.close()
            server.close()


class TestSlotClaim:
    def test_two_actors_distinct_slots_and_release(self):
        server = ShmTransportServer(
            name=lane_name("claim"), slots=2, ring_bytes=1 << 14
        )
        try:
            a1 = ShmTransport(lane_name("claim"), slots=2)
            a2 = ShmTransport(lane_name("claim"), slots=2)
            assert {a1.slot, a2.slot} == {0, 1}
            assert server.n_connected == 2
            with pytest.raises(ConnectionError, match="no free shm"):
                ShmTransport(lane_name("claim"), slots=2)
            a1.close()
            assert server.n_connected == 1
            a3 = ShmTransport(lane_name("claim"), slots=2)  # reuses slot 0
            assert a3.slot == a1.slot
            a2.close()
            a3.close()
        finally:
            server.close()

    def test_actor_detects_dead_learner(self):
        """shm has no connection to break: the actor must notice a dead
        learner via the slab's pid beacon and raise ConnectionError so the
        reconnect/exit-for-supervisor machinery engages (review finding)."""
        import struct

        from dotaclient_tpu.transport import shm_transport as st

        server, actor = make_lane("alive")
        try:
            dead_pid = 2 ** 22 + 54321
            assert not st._pid_alive(dead_pid)
            struct.pack_into(
                "<Q", server._weights.buf, st._OFF_SERVER_PID, dead_pid
            )
            actor._last_liveness = -1e9   # force the time-gated probe
            with pytest.raises(ConnectionError, match="learner process"):
                actor.latest_weights()
            actor._last_liveness = -1e9
            with pytest.raises(ConnectionError, match="learner process"):
                actor.publish_rollout_bytes(b"x" * 64)
        finally:
            actor.close()
            server.close()

    def test_attach_to_dead_lane_raises(self):
        """Attaching to a crashed learner's leftover segments must fail
        like a refused connect — otherwise the reconnect loop 'succeeds'
        against a corpse forever (review finding)."""
        import struct

        from dotaclient_tpu.transport import shm_transport as st

        server = ShmTransportServer(
            name=lane_name("dead"), slots=1, ring_bytes=1 << 14
        )
        try:
            dead_pid = 2 ** 22 + 99991
            assert not st._pid_alive(dead_pid)
            struct.pack_into(
                "<Q", server._weights.buf, st._OFF_SERVER_PID, dead_pid
            )
            with pytest.raises(ConnectionError, match="learner process"):
                ShmTransport(lane_name("dead"), slots=1)
        finally:
            server.close()

    def test_server_restart_reclaims_stale_lane(self):
        """A fixed --shm-name must survive a SIGKILL'd predecessor: the new
        server reclaims segments whose pid beacon is dead instead of
        crash-looping on FileExistsError (review finding)."""
        import struct

        from dotaclient_tpu.transport import shm_transport as st

        name = lane_name("restart")
        old = ShmTransportServer(name=name, slots=1, ring_bytes=1 << 14)
        dead_pid = 2 ** 22 + 77777
        struct.pack_into("<Q", old._weights.buf, st._OFF_SERVER_PID, dead_pid)
        # simulate the crash: the segments persist, close() never runs
        st._OWNED_BY_THIS_PROCESS.discard(f"{name}-w")
        st._OWNED_BY_THIS_PROCESS.discard(f"{name}-r0")
        new = ShmTransportServer(name=name, slots=1, ring_bytes=1 << 14)
        try:
            actor = ShmTransport(name, slots=1)   # fresh lane works
            actor.publish_rollout(tiny_rollout(5))
            got = new.consume_rollouts(4, timeout=1.0)
            assert [r.rollout_id for r in got] == [5]
            actor.close()
        finally:
            new.close()
        # a LIVE owner is never stolen from
        live = ShmTransportServer(name=name, slots=1, ring_bytes=1 << 14)
        try:
            with pytest.raises(FileExistsError, match="live learner"):
                ShmTransportServer(name=name, slots=1, ring_bytes=1 << 14)
        finally:
            live.close()

    def test_crashed_actor_slot_is_reaped(self):
        """A SIGKILL'd actor never runs close(): the server must reap its
        slot (dead-pid claim word + leftover lockfile) so a restarted
        actor can connect instead of exhausting slots."""
        import struct

        from dotaclient_tpu.transport import shm_transport as st

        server = ShmTransportServer(
            name=lane_name("reap"), slots=1, ring_bytes=1 << 14
        )
        try:
            actor = ShmTransport(lane_name("reap"), slots=1)
            # simulate the crash: the claim word + lockfile survive, the
            # process behind the pid does not (use a free pid)
            dead_pid = 2 ** 22 + 12345
            assert not st._pid_alive(dead_pid)
            struct.pack_into(
                "<Q", server._rings[0].buf, st._OFF_CLAIM, dead_pid
            )
            actor._ring = None   # the "crashed" actor must not unlock
            with pytest.raises(ConnectionError):
                ShmTransport(lane_name("reap"), slots=1)   # slot still held
            server._publish_ring_telemetry()               # reap pass
            assert server.n_connected == 0
            revived = ShmTransport(lane_name("reap"), slots=1)
            assert revived.slot == 0
            revived.close()
            actor._weights_shm.close()
        finally:
            server.close()

    def test_both_claimed_rings_are_drained(self):
        server = ShmTransportServer(
            name=lane_name("multi"), slots=2, ring_bytes=1 << 16
        )
        try:
            a1 = ShmTransport(lane_name("multi"), slots=2)
            a2 = ShmTransport(lane_name("multi"), slots=2)
            for i in range(4):
                a1.publish_rollout(tiny_rollout(i))
                a2.publish_rollout(tiny_rollout(100 + i))
            got = server.consume_rollouts(64, timeout=1.0)
            assert sorted(r.rollout_id for r in got) == sorted(
                list(range(4)) + list(range(100, 104))
            )
            a1.close()
            a2.close()
        finally:
            server.close()
