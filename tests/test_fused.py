"""Fused rollout+update program tests (train/fused.py, actor="fused").

The fused program must be the same math as the unfused pair: one
``DeviceActor._rollout_impl`` + one ``_train_step`` on the produced chunk,
from identical initial state. Pinned by running both from copies of the
same params/actor-state and comparing losses and updated parameters.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dotaclient_tpu.config import default_config


def tiny_cfg(n_envs=8, opponent="scripted_easy"):
    cfg = default_config()
    return dataclasses.replace(
        cfg,
        model=dataclasses.replace(cfg.model, dtype="float32"),
        ppo=dataclasses.replace(cfg.ppo, rollout_len=4, batch_rollouts=8),
        env=dataclasses.replace(
            cfg.env, n_envs=n_envs, opponent=opponent, max_dota_time=60.0
        ),
        buffer=dataclasses.replace(cfg.buffer, capacity_rollouts=16, min_fill=8),
        log_every=1,
    )


class TestFusedStep:
    @pytest.mark.slow   # tier-1 duration audit (ISSUE 6): ~49s on the reference container
    def test_fused_equals_collect_then_train(self):
        from dotaclient_tpu.actor.device_rollout import DeviceActor
        from dotaclient_tpu.models import make_policy
        from dotaclient_tpu.parallel import make_mesh
        from dotaclient_tpu.train.fused import make_fused_step
        from dotaclient_tpu.train.ppo import _train_step, init_train_state
        from dotaclient_tpu.models import init_params

        cfg = tiny_cfg()
        mesh = make_mesh(cfg.mesh, devices=jax.devices()[:1])
        policy = make_policy(cfg.model, cfg.obs, cfg.actions)
        params = init_params(policy, jax.random.PRNGKey(0))
        actor = DeviceActor(cfg, policy, seed=3)
        state = init_train_state(params, cfg.ppo)
        actor_state0 = jax.tree.map(jnp.copy, actor.state)

        # unfused reference: collect, then train on the chunk
        a1, chunk, _ = jax.jit(actor._rollout_impl)(
            state.params, actor_state0, state.params
        )
        ref_state, ref_metrics = jax.jit(
            lambda s, b: _train_step(policy, cfg.ppo, s, b)
        )(state, chunk)

        fused = make_fused_step(policy, cfg, mesh, actor)
        new_state, a2, metrics, stats = fused(
            init_train_state(params, cfg.ppo),
            jax.tree.map(jnp.copy, actor_state0),
            params,
        )

        np.testing.assert_allclose(
            float(np.asarray(metrics["loss"])),
            float(np.asarray(ref_metrics["loss"])),
            rtol=1e-5,
        )
        for got, want in zip(
            jax.tree.leaves(new_state.params), jax.tree.leaves(ref_state.params)
        ):
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-6
            )
        # actor state advanced identically (sim arrays, carries, rng)
        for got, want in zip(jax.tree.leaves(a2), jax.tree.leaves(a1)):
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6
            )

    def test_learner_fused_mode_trains(self):
        from dotaclient_tpu.train.learner import Learner

        learner = Learner(tiny_cfg(), actor="fused", seed=1)
        out = learner.train(4)
        assert out["optimizer_steps"] == 4.0
        assert np.isfinite(out["loss"])
        # frames accounting reflects the lane-set batch, not batch_rollouts
        assert out["frames_trained"] == 4 * learner.device_actor.n_lanes * 4

    def test_fused_multi_epoch_scans_updates_in_program(self):
        """epochs_per_batch > 1 in fused mode: the one program applies E
        optimizer steps over its chunk (lax.scan), and the host counters
        stay in lockstep with the device step/version counters."""
        from dotaclient_tpu.train.learner import Learner

        cfg = tiny_cfg()
        cfg = dataclasses.replace(
            cfg, ppo=dataclasses.replace(cfg.ppo, epochs_per_batch=2)
        )
        learner = Learner(cfg, actor="fused", seed=1)
        out = learner.train(4)    # 2 fused calls × 2 epochs
        assert out["optimizer_steps"] == 4.0
        assert np.isfinite(out["loss"])
        assert int(learner.state.step) == 4
        assert int(learner.state.version) == learner._host_version
        # each fused call contributes ONE chunk of unique frames
        assert out["frames_trained"] == 2 * learner.device_actor.n_lanes * 4

    @pytest.mark.slow   # tier-1 duration audit (ISSUE 6): ~40s on the reference container
    def test_fused_minibatches_shuffle_in_program(self):
        """minibatches > 1 in fused mode: each epoch permutes the lanes
        (keyed on seed + step) and scans an optimizer step per group —
        verified against a hand-built reference of the same math."""
        from dotaclient_tpu.actor.device_rollout import DeviceActor
        from dotaclient_tpu.models import init_params, make_policy
        from dotaclient_tpu.parallel import make_mesh
        from dotaclient_tpu.train.fused import make_fused_step
        from dotaclient_tpu.train.ppo import _train_step, init_train_state

        M = 2
        cfg = tiny_cfg()
        cfg = dataclasses.replace(
            cfg, ppo=dataclasses.replace(cfg.ppo, minibatches=M)
        )
        mesh = make_mesh(cfg.mesh, devices=jax.devices()[:1])
        policy = make_policy(cfg.model, cfg.obs, cfg.actions)
        params = init_params(policy, jax.random.PRNGKey(0))
        actor = DeviceActor(cfg, policy, seed=3)
        actor_state0 = jax.tree.map(jnp.copy, actor.state)
        L = actor.n_lanes

        # reference: collect, permute with the same shard-local derivation
        # (one shard on this 1-device mesh), M sequential optimizer steps
        # on the lane groups
        ref_state = init_train_state(params, cfg.ppo)
        _, chunk, _ = jax.jit(actor._rollout_impl)(
            ref_state.params, actor_state0, ref_state.params
        )
        key = jax.random.fold_in(
            jax.random.PRNGKey(cfg.seed), ref_state.step
        )
        (shard_key,) = jax.random.split(key, 1)
        perm = jax.random.permutation(shard_key, L)
        shuf = jax.tree.map(lambda x: jnp.take(x, perm, axis=0), chunk)
        step_jit = jax.jit(
            lambda s, b: _train_step(policy, cfg.ppo, s, b)
        )
        for m in range(M):
            mb = jax.tree.map(
                lambda x: x[m * (L // M):(m + 1) * (L // M)], shuf
            )
            ref_state, _ = step_jit(ref_state, mb)

        fused = make_fused_step(policy, cfg, mesh, actor)
        got_state, _, metrics, _ = fused(
            init_train_state(params, cfg.ppo),
            jax.tree.map(jnp.copy, actor_state0),
            params,
        )
        assert int(got_state.step) == M
        for got, want in zip(
            jax.tree.leaves(got_state.params), jax.tree.leaves(ref_state.params)
        ):
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-6
            )
        assert np.isfinite(float(np.asarray(metrics["loss"])))

    def test_learner_fused_minibatch_accounting(self):
        from dotaclient_tpu.train.learner import Learner

        # 32 lanes: each of the 2 minibatches (16 lanes) must itself split
        # over the forced 8-device data axis
        cfg = tiny_cfg(n_envs=32)
        cfg = dataclasses.replace(
            cfg, ppo=dataclasses.replace(cfg.ppo, minibatches=2)
        )
        learner = Learner(cfg, actor="fused", seed=1)
        out = learner.train(4)    # 2 dispatches × 2 minibatch steps
        assert out["optimizer_steps"] == 4.0
        assert int(learner.state.step) == 4
        assert int(learner.state.version) == learner._host_version
        # each dispatch contributes ONE chunk of unique frames
        assert out["frames_trained"] == 2 * learner.device_actor.n_lanes * 4

    def test_fused_minibatches_must_divide_lanes(self):
        from dotaclient_tpu.train.learner import Learner

        cfg = tiny_cfg(n_envs=8)
        cfg = dataclasses.replace(
            cfg, ppo=dataclasses.replace(cfg.ppo, minibatches=3)
        )
        with pytest.raises(ValueError, match="divisible"):
            Learner(cfg, actor="fused")

    @pytest.mark.slow   # tier-1 duration audit (ISSUE 6): ~36s on the reference container
    def test_steps_per_dispatch_scans_whole_iterations(self):
        """K>1 dispatch batching is the same math as K sequential fused
        calls: identical final params/actor-state, stats summed over the
        scan, host counters advancing in strides of K."""
        from dotaclient_tpu.actor.device_rollout import DeviceActor
        from dotaclient_tpu.models import init_params, make_policy
        from dotaclient_tpu.parallel import make_mesh
        from dotaclient_tpu.train.fused import make_fused_step
        from dotaclient_tpu.train.ppo import init_train_state

        K = 3
        cfg = tiny_cfg()
        mesh = make_mesh(cfg.mesh, devices=jax.devices()[:1])
        policy = make_policy(cfg.model, cfg.obs, cfg.actions)
        params = init_params(policy, jax.random.PRNGKey(0))
        actor = DeviceActor(cfg, policy, seed=3)
        actor_state0 = jax.tree.map(jnp.copy, actor.state)

        # reference: K sequential single-iteration dispatches
        one = make_fused_step(policy, cfg, mesh, actor)
        ref_state = init_train_state(params, cfg.ppo)
        ref_actor = jax.tree.map(jnp.copy, actor_state0)
        ref_stats_sum = None
        for _ in range(K):
            ref_state, ref_actor, _, st = one(
                ref_state, ref_actor, ref_state.params
            )
            st = jax.tree.map(np.asarray, st)
            # tree-map: the stats carry nested leaves now (the outcome
            # plane's reward-term dict + histogram vector, ISSUE 15)
            ref_stats_sum = (
                st if ref_stats_sum is None
                else jax.tree.map(lambda a, b: a + b, ref_stats_sum, st)
            )

        cfg_k = dataclasses.replace(cfg, steps_per_dispatch=K)
        fused_k = make_fused_step(policy, cfg_k, mesh, actor)
        got_state, got_actor, metrics, got_stats = fused_k(
            init_train_state(params, cfg.ppo),
            jax.tree.map(jnp.copy, actor_state0),
            params,
        )
        # NOTE: the reference passes the UPDATED params as opp_params each
        # iteration while the scanned program holds the dispatch-entry
        # params — identical here because opponent lanes don't exist in
        # scripted mode (opp_params is unused by the rollout).
        for got, want in zip(
            jax.tree.leaves(got_state.params), jax.tree.leaves(ref_state.params)
        ):
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-6
            )
        for got, want in zip(
            jax.tree.leaves(got_actor), jax.tree.leaves(ref_actor)
        ):
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6
            )
        for (path_got, got), (_, want) in zip(
            jax.tree_util.tree_flatten_with_path(got_stats)[0],
            jax.tree_util.tree_flatten_with_path(ref_stats_sum)[0],
        ):
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6,
                err_msg=f"stats leaf {jax.tree_util.keystr(path_got)}",
            )
        assert np.isfinite(float(np.asarray(metrics["loss"])))

    def test_learner_steps_per_dispatch_accounting(self):
        from dotaclient_tpu.train.learner import Learner

        cfg = dataclasses.replace(tiny_cfg(), steps_per_dispatch=4)
        learner = Learner(cfg, actor="fused", seed=1)
        out = learner.train(8)    # 2 dispatches × 4 iterations
        assert out["optimizer_steps"] == 8.0
        assert np.isfinite(out["loss"])
        assert int(learner.state.step) == 8
        assert learner._host_step == 8
        assert int(learner.state.version) == learner._host_version
        # each of the 8 in-program iterations produced a fresh chunk
        assert out["frames_trained"] == 8 * learner.device_actor.n_lanes * 4
        assert learner.device_actor.rollouts_shipped == 8 * learner.device_actor.n_lanes

    def test_steps_per_dispatch_rejected_outside_fused(self):
        from dotaclient_tpu.train.learner import Learner

        cfg = dataclasses.replace(tiny_cfg(), steps_per_dispatch=2)
        with pytest.raises(ValueError, match="steps_per_dispatch"):
            Learner(cfg, actor="device")

    @pytest.mark.xfail(
        reason="pre-existing tolerance drift (tracked, ISSUE 6 satellite): "
        "on the forced 8-virtual-device CPU mesh the TP trajectory's loss "
        "drifts past rtol=2e-4 of the single-device run after 2 fused "
        "iterations (measured -0.0326 vs -0.0334 on clean PR 2..5 HEADs — "
        "XLA CPU fuses the sharded reductions differently, and the fused "
        "rollout+update program compounds the rounding across the scan). "
        "The TP equivalence guarantee itself is covered at step scope by "
        "test_parallel; widening the tolerance to the observed ~3e-2 "
        "would make this assertion vacuous, so it stays xfail until the "
        "trajectory-scope comparison is reworked (e.g. per-iteration "
        "re-sync or f64 accumulation).",
        strict=False,
    )
    @pytest.mark.slow   # tier-1 duration audit (ISSUE 6): ~40s on the reference container
    def test_fused_under_tensor_parallelism_matches_single_device(self):
        """The fused program with a (data, model=2) mesh must produce the
        same training trajectory as the single-device fused program —
        the TP equivalence guarantee (test_parallel) extended to the
        rollout+update fusion."""
        from dotaclient_tpu.actor.device_rollout import DeviceActor
        from dotaclient_tpu.models import init_params, make_policy
        from dotaclient_tpu.parallel import make_mesh
        from dotaclient_tpu.train.fused import make_fused_step
        from dotaclient_tpu.train.ppo import init_train_state

        if len(jax.devices()) < 8:
            pytest.skip("needs the 8 forced host devices")
        cfg = tiny_cfg(n_envs=16)   # 16 lanes / 4 data shards under TP
        policy = make_policy(cfg.model, cfg.obs, cfg.actions)
        params = init_params(policy, jax.random.PRNGKey(0))

        def run(cfg_run, devices):
            mesh = make_mesh(cfg_run.mesh, devices=devices)
            actor = DeviceActor(cfg_run, policy, seed=5)
            fused = make_fused_step(policy, cfg_run, mesh, actor)
            state = init_train_state(params, cfg_run.ppo)
            for _ in range(2):
                state, actor_state, metrics, _stats = fused(
                    state, actor.state, state.params
                )
                actor.state = actor_state
            return state, metrics

        s1, m1 = run(cfg, jax.devices()[:1])
        cfg_tp = dataclasses.replace(
            cfg, mesh=dataclasses.replace(cfg.mesh, model_parallel=2)
        )
        s2, m2 = run(cfg_tp, jax.devices())
        # params actually partition over the model axis under TP
        kernel = s2.params["params"]["core"]["hi"]["kernel"]
        assert "model" in str(kernel.sharding.spec)
        np.testing.assert_allclose(
            float(np.asarray(m1["loss"])), float(np.asarray(m2["loss"])),
            rtol=2e-4, atol=2e-5,
        )
        for a, b in zip(
            jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
            )

    def test_fused_league_uses_frozen_opponent(self):
        from dotaclient_tpu.train.learner import Learner

        cfg = tiny_cfg(opponent="league")
        cfg = dataclasses.replace(
            cfg,
            league=dataclasses.replace(
                cfg.league, enabled=True, snapshot_every=2, pool_size=2,
                selfplay_prob=0.0,
            ),
        )
        learner = Learner(cfg, actor="fused", seed=2)
        out = learner.train(3)
        assert np.isfinite(out["loss"])
        assert len(learner.league.snapshots) >= 1
