"""Fault-tolerance layer tests (ISSUE 4) — the tier-1 chaos smoke.

Everything here is fast and in-process: the fault-injection registry, the
CRC32 wire trailer on both lanes, poison-frame quarantine, heartbeat/idle
liveness on the TCP lane, the checkpoint save-failure degrade, the
learner's graceful stop, and the actor's partial-rollout flush. The real
multi-process chaos plan (kill -9, SIGTERM+restore, supervisor restart
policy) runs in tests/test_chaos.py, marked slow.
"""

import dataclasses
import os
import socket as socket_mod
import time

import numpy as np
import pytest

from dotaclient_tpu.transport import (
    ShmTransport,
    ShmTransportServer,
    SocketTransport,
    TransportServer,
    encode_rollout,
    encode_weights,
)
from dotaclient_tpu.transport.serialize import frame_crc32
from dotaclient_tpu.utils import faults, telemetry


@pytest.fixture(autouse=True)
def _clean_faults():
    """Faults must never leak into other tests (components cache the
    registry at construction, so order matters inside each test too)."""
    yield
    faults.configure(None)


def counter_value(name: str) -> float:
    return telemetry.get_registry().counter(name).value


def tiny_rollout(rid=0, n=16):
    return encode_rollout(
        {"rewards": np.arange(n, dtype=np.float32) + rid},
        model_version=0, env_id=0, rollout_id=rid, length=n,
        total_reward=0.0,
    )


def wait_until(pred, timeout=5.0, interval=0.01):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


class TestFaultRegistry:
    def test_disabled_is_none(self):
        faults.configure(None)
        assert faults.get() is None

    def test_one_shot_trigger(self):
        reg = faults.configure("transport.corrupt_frame@3")
        hits = [reg.fire("transport.corrupt_frame") for _ in range(6)]
        assert hits == [False, False, True, False, False, False]
        assert reg.fired("transport.corrupt_frame") == 1

    def test_repeating_trigger(self):
        reg = faults.configure("x@2+3")
        hits = [reg.fire("x") for _ in range(9)]
        #        1      2     3      4      5     6      7      8     9
        assert hits == [
            False, True, False, False, True, False, False, True, False,
        ]

    def test_value_fault_and_unknown_site(self):
        reg = faults.configure("transport.delay_send=0.25,a@1")
        assert reg.value("transport.delay_send") == 0.25
        assert reg.value("absent", default=1.5) == 1.5
        assert not reg.fire("never.configured")

    def test_multiple_entries_and_spaces(self):
        reg = faults.configure(" a@1 , b=2.0 ,c@4+1 ")
        assert reg.fire("a") and reg.value("b") == 2.0
        assert not reg.fire("c")

    def test_bad_specs_raise(self):
        for spec in ("nonsense", "a@zero", "a@0", "a=notafloat", "a@1+-1"):
            with pytest.raises(faults.FaultSpecError):
                faults.configure(spec)
        faults.configure(None)

    def test_firing_is_counted_in_telemetry(self):
        before = counter_value("faults/injected_total")
        reg = faults.configure("y@1")
        reg.fire("y")
        assert counter_value("faults/injected_total") == before + 1


class TestFrameCrc:
    def test_small_frame_is_plain_crc32(self):
        import zlib

        payload = b"hello, wire"
        assert frame_crc32(payload) == zlib.crc32(payload) & 0xFFFFFFFF

    @pytest.mark.parametrize("size", (64, 4096, 4097, 65536, 1 << 20))
    def test_bit_flip_detected_any_position(self, size):
        rng = np.random.default_rng(size)
        payload = bytearray(rng.integers(0, 256, size, dtype=np.uint8))
        base = frame_crc32(bytes(payload))
        # flip one bit at the head, the middle, an odd tail offset, the end
        for pos in (0, size // 2, size - 3, size - 1):
            payload[pos] ^= 0x10
            assert frame_crc32(bytes(payload)) != base, f"missed flip @{pos}"
            payload[pos] ^= 0x10
        assert frame_crc32(bytes(payload)) == base

    def test_truncation_detected(self):
        payload = bytes(np.random.default_rng(0).integers(
            0, 256, 70000, dtype=np.uint8
        ))
        assert frame_crc32(payload[:-8]) != frame_crc32(payload)

    def test_memoryview_and_bytes_agree(self):
        payload = bytes(range(256)) * 300   # > fold threshold
        assert frame_crc32(memoryview(payload)) == frame_crc32(payload)
        # unaligned view (the shm ring hands arbitrary offsets)
        buf = b"\x00" + payload
        assert frame_crc32(memoryview(buf)[1:]) == frame_crc32(payload)


class TestSocketCorruptFrames:
    def test_corrupt_frame_dropped_and_counted(self):
        """A bit-flipped frame increments frames_corrupt_total and is
        dropped; the good frames around it are delivered; nothing
        crashes."""
        server = TransportServer(port=0)
        try:
            before = counter_value("transport/frames_corrupt_total")
            faults.configure("transport.corrupt_frame@2")
            host, port = server.address
            actor = SocketTransport(host, port)
            for i in range(4):
                actor.publish_rollout(tiny_rollout(rid=i))
            got = []
            deadline = time.time() + 5
            while len(got) < 3 and time.time() < deadline:
                got.extend(server.consume_rollouts(16, timeout=0.2))
            assert sorted(r.rollout_id for r in got) == [0, 2, 3]
            assert (
                counter_value("transport/frames_corrupt_total") == before + 1
            )
            # the stream stayed in sync: a later publish still arrives
            actor.publish_rollout(tiny_rollout(rid=9))
            assert wait_until(
                lambda: any(
                    r.rollout_id == 9
                    for r in server.consume_rollouts(16, timeout=0.2)
                )
            )
            actor.close()
        finally:
            faults.configure(None)
            server.close()

    def test_poison_streak_quarantines_peer(self):
        """poison_frame_limit consecutive corrupt frames cut the peer's
        connection (counted) without hurting the server or other actors."""
        server = TransportServer(port=0, poison_frame_limit=2)
        try:
            q0 = counter_value("transport/peers_quarantined")
            host, port = server.address
            faults.configure("transport.corrupt_frame@1+1")  # every frame
            poisoner = SocketTransport(host, port)
            faults.configure(None)
            survivor = SocketTransport(host, port)
            for i in range(3):
                try:
                    poisoner.publish_rollout(tiny_rollout(rid=i))
                except (ConnectionError, OSError):
                    break   # server already cut the quarantined conn
            assert wait_until(
                lambda: counter_value("transport/peers_quarantined") == q0 + 1
            )
            # quarantine means the CONNECTION died, not the server
            survivor.publish_rollout(tiny_rollout(rid=42))
            assert wait_until(
                lambda: any(
                    r.rollout_id == 42
                    for r in server.consume_rollouts(16, timeout=0.2)
                )
            )
            server.publish_weights(
                encode_weights({"w": np.ones(3, np.float32)}, 1)
            )  # fanout also healthy
            survivor.close()
            poisoner.close()
        finally:
            faults.configure(None)
            server.close()

    def test_producer_death_mid_frame(self):
        """kill -9 semantics, distilled: a producer that vanishes after
        shipping HALF a frame (header promised more bytes than sent) must
        not wedge or crash the reader — the partial frame is discarded with
        the connection and later traffic flows."""
        from dotaclient_tpu.transport import socket_transport as st

        server = TransportServer(port=0)
        try:
            host, port = server.address
            raw = socket_mod.create_connection((host, port))
            payload = tiny_rollout(rid=7).SerializeToString()
            header = st._pack_header(st._KIND_ROLLOUT, len(payload))
            raw.sendall(header + payload[: len(payload) // 2])
            raw.close()   # no trailer, no tail: mid-frame death
            survivor = SocketTransport(host, port)
            survivor.publish_rollout(tiny_rollout(rid=8))
            assert wait_until(
                lambda: any(
                    r.rollout_id == 8
                    for r in server.consume_rollouts(16, timeout=0.2)
                )
            )
            survivor.close()
        finally:
            server.close()

    def test_garbage_length_quarantined_immediately(self):
        """A corrupt header (the length word cannot be trusted — here the
        header CRC fails) is unrecoverable on a byte stream: the peer is
        quarantined at once, not after a limit, and crucially BEFORE any
        phantom payload is buffered (a plausible-but-wrong length ≤
        MAX_FRAME would otherwise swallow good frames for minutes)."""
        from dotaclient_tpu.transport import socket_transport as st

        server = TransportServer(port=0, poison_frame_limit=100)
        try:
            q0 = counter_value("transport/peers_quarantined")
            host, port = server.address
            # bit-flipped length word, stale header CRC: plausible length
            # (64 KiB), invalid header — must quarantine without waiting
            # for 64 KiB that will never arrive
            good = st._pack_header(st._KIND_ROLLOUT, 16384)
            bad = bytearray(good)
            bad[3] ^= 0x01   # length 16384 -> 16640; CRC now stale
            raw = socket_mod.create_connection((host, port))
            raw.sendall(bytes(bad))
            assert wait_until(
                lambda: counter_value("transport/peers_quarantined") == q0 + 1
            )
            raw.close()
            # oversized length with a VALID header CRC (hostile sender) is
            # equally fatal via the MAX_FRAME bound
            raw2 = socket_mod.create_connection((host, port))
            raw2.sendall(st._pack_header(st._KIND_ROLLOUT, st.MAX_FRAME + 1))
            assert wait_until(
                lambda: counter_value("transport/peers_quarantined") == q0 + 2
            )
            raw2.close()
        finally:
            server.close()


class TestTcpLiveness:
    def test_heartbeats_flow_and_keep_both_sides_alive(self):
        """With aggressive heartbeat + idle settings, an otherwise silent
        learner/actor pair stays connected: the learner's heartbeats reset
        the actor's idle timer, the actor's echoes reset the learner's."""
        server = TransportServer(
            port=0, heartbeat_interval_s=0.05, idle_timeout_s=0.5
        )
        try:
            hb0 = counter_value("transport/heartbeats_sent")
            host, port = server.address
            actor = SocketTransport(host, port, idle_timeout_s=0.5)
            time.sleep(1.2)   # several idle windows with zero publishes
            assert counter_value("transport/heartbeats_sent") > hb0
            assert actor.latest_weights() is None   # alive: no raise
            assert server.n_connected == 1          # not idle-dropped
            actor.close()
        finally:
            server.close()

    def test_frequent_publishes_keep_quiet_actor_alive(self):
        """A learner that publishes weights faster than its heartbeat
        interval never sends heartbeats — the actor must echo liveness on
        ANY inbound frame, or a healthy-but-rollout-quiet actor would be
        idle-dropped mid-stream."""
        # idle window must exceed the actor's fixed ~1s echo rate limit
        # (production: 30s idle vs 1s echo), hence the 1.5s here
        server = TransportServer(
            port=0, heartbeat_interval_s=0.0, idle_timeout_s=1.5
        )
        try:
            host, port = server.address
            actor = SocketTransport(host, port, idle_timeout_s=8.0)
            assert wait_until(lambda: server.n_connected == 1)
            deadline = time.time() + 3.5   # several idle windows
            v = 0
            while time.time() < deadline:
                v += 1
                server.publish_weights(
                    encode_weights({"w": np.ones(3, np.float32)}, v)
                )
                time.sleep(0.1)
            assert server.n_connected == 1   # never idle-dropped
            assert actor.latest_weights() is not None
            actor.close()
        finally:
            server.close()

    def test_actor_idle_timeout_detects_half_open(self):
        """A learner that stops sending entirely (heartbeats disabled —
        the half-open shape) trips the actor's idle timeout: the transport
        declares itself dead so the reconnect/exit machinery engages."""
        server = TransportServer(
            port=0, heartbeat_interval_s=0.0, idle_timeout_s=0.0
        )
        try:
            host, port = server.address
            actor = SocketTransport(host, port, idle_timeout_s=0.3)
            assert wait_until(lambda: actor._dead is not None, timeout=5.0)
            with pytest.raises(ConnectionError):
                actor.latest_weights()
            actor.close()
        finally:
            server.close()

    def test_learner_drops_idle_connection(self):
        """With learner heartbeats off, a raw connection that never sends
        anything is a half-open suspect: dropped and counted after
        idle_timeout_s."""
        server = TransportServer(
            port=0, heartbeat_interval_s=0.0, idle_timeout_s=0.3
        )
        try:
            d0 = counter_value("transport/conn_idle_drops")
            host, port = server.address
            raw = socket_mod.create_connection((host, port))
            assert wait_until(lambda: server.n_connected == 1)
            assert wait_until(
                lambda: counter_value("transport/conn_idle_drops") == d0 + 1,
                timeout=5.0,
            )
            assert server.n_connected == 0
            raw.close()
        finally:
            server.close()


def shm_lane(tag, **kw):
    name = f"t-faults-{os.getpid()}-{tag}"
    server = ShmTransportServer(name=name, slots=1, ring_bytes=1 << 16,
                                weights_bytes=1 << 20, **kw)
    actor = ShmTransport(name, slots=1)
    return server, actor


class TestShmCorruptFrames:
    def test_corrupt_frame_dropped_and_counted(self):
        before = counter_value("transport/frames_corrupt_total")
        faults.configure("transport.corrupt_frame@2")
        server, actor = shm_lane("corrupt")
        try:
            for i in range(4):
                assert actor.publish_rollout_bytes(
                    tiny_rollout(i).SerializeToString()
                )
            got = server.consume_rollouts(16, timeout=1.0)
            assert [r.rollout_id for r in got] == [0, 2, 3]
            assert (
                counter_value("transport/frames_corrupt_total") == before + 1
            )
        finally:
            actor.close()
            server.close()

    def test_poison_streak_quarantines_slot(self):
        q0 = counter_value("transport/peers_quarantined")
        faults.configure("transport.corrupt_frame@1+1")   # every frame
        server, actor = shm_lane("poison", poison_frame_limit=2)
        try:
            for i in range(4):
                actor.publish_rollout_bytes(
                    tiny_rollout(i).SerializeToString()
                )
            assert server.consume_rollouts(16, timeout=0.5) == []
            assert counter_value("transport/peers_quarantined") == q0 + 1
            # quarantined slot is skipped wholesale from now on
            faults.configure(None)
            assert server.consume_rollouts(16, timeout=0.05) == []
        finally:
            actor.close()
            server.close()

    def test_garbage_length_resyncs_ring(self):
        """A corrupted length word makes every later boundary garbage; the
        drain discards the buffered region (resync to tail) and the NEXT
        intact frame flows again."""
        from dotaclient_tpu.transport import shm_transport as st

        server, actor = shm_lane("resync")
        try:
            before = counter_value("transport/frames_corrupt_total")
            actor.publish_rollout_bytes(tiny_rollout(0).SerializeToString())
            # scribble the first frame's length prefix (frame starts at
            # ring position 0) with an implausible value
            st._U32.pack_into(
                server._rings[0].buf, st._RING_HDR, 0xFFFFFFF0
            )
            assert server.consume_rollouts(16, timeout=0.2) == []
            assert (
                counter_value("transport/frames_corrupt_total") == before + 1
            )
            actor.publish_rollout_bytes(tiny_rollout(5).SerializeToString())
            got = server.consume_rollouts(16, timeout=1.0)
            assert [r.rollout_id for r in got] == [5]
        finally:
            actor.close()
            server.close()

    def test_weights_slab_corruption_serves_last_good(self):
        server, actor = shm_lane("slab")
        try:
            before = counter_value("transport/frames_corrupt_total")
            server.publish_weights(
                encode_weights({"w": np.ones(4, np.float32)}, 1)
            )
            assert actor.latest_weights().version == 1
            server.publish_weights(
                encode_weights({"w": np.full(4, 2.0, np.float32)}, 2)
            )
            # flip a payload byte AFTER the publish completed (stable seq):
            # a real corruption, not a torn read
            from dotaclient_tpu.transport import shm_transport as st

            server._weights.buf[st._SLAB_HDR + 3] ^= 0xFF
            msg = actor.latest_weights()
            assert msg is not None and msg.version == 1   # last good
            assert (
                counter_value("transport/frames_corrupt_total") == before + 1
            )
            # repeated polls of the SAME corrupt slab neither re-count nor
            # re-copy — one corruption event is one count until republish
            for _ in range(5):
                assert actor.latest_weights().version == 1
            assert (
                counter_value("transport/frames_corrupt_total") == before + 1
            )
            server.publish_weights(
                encode_weights({"w": np.full(4, 3.0, np.float32)}, 3)
            )
            assert actor.latest_weights().version == 3    # recovered
        finally:
            actor.close()
            server.close()


class TestCheckpointDegrade:
    def _state(self):
        import jax

        from dotaclient_tpu.config import ModelConfig, RunConfig
        from dotaclient_tpu.models import init_params, make_policy
        from dotaclient_tpu.train.ppo import init_train_state

        cfg = RunConfig()
        # minimal model: these tests exercise the save FAILURE path, not
        # serialization throughput — keep the orbax write small
        cfg = dataclasses.replace(
            cfg, model=ModelConfig(unit_embed_dim=8, hidden_dim=8,
                                   hero_embed_dim=4)
        )
        policy = make_policy(cfg.model, cfg.obs, cfg.actions)
        params = init_params(policy, jax.random.PRNGKey(0))
        return init_train_state(params, cfg.ppo), cfg

    def test_periodic_save_failure_degrades(self, tmp_path):
        from dotaclient_tpu.utils.checkpoint import CheckpointManager

        state, cfg = self._state()
        before = counter_value("checkpoint/save_failures_total")
        faults.configure("checkpoint.fail_write@1")
        mgr = CheckpointManager(str(tmp_path / "ck"))
        try:
            assert mgr.save(state, cfg) is False   # degraded, no raise
            assert (
                counter_value("checkpoint/save_failures_total") == before + 1
            )
            assert mgr.save(state, cfg) is True    # storage "recovered"
            mgr.wait()
            assert mgr.latest_step() == 0
        finally:
            mgr.close()

    def test_forced_save_failure_stays_loud(self, tmp_path):
        from dotaclient_tpu.utils.checkpoint import CheckpointManager

        state, cfg = self._state()
        faults.configure("checkpoint.fail_write@1")
        mgr = CheckpointManager(str(tmp_path / "ck2"))
        try:
            with pytest.raises(OSError):
                mgr.save(state, cfg, force=True)
        finally:
            mgr.close()


class TestGracefulStop:
    def test_request_stop_drains_mid_run(self, tmp_path):
        """request_stop() mid-train: the loop exits at a step boundary and
        the end-of-run tail still checkpoints the FULL pipeline — the
        restore resumes the exact step (the SIGTERM handler is one line on
        top of this; the signal itself is exercised in test_chaos.py)."""
        import threading

        from dotaclient_tpu.config import RunConfig
        from dotaclient_tpu.train.learner import Learner
        from dotaclient_tpu.utils.checkpoint import CheckpointManager

        from dotaclient_tpu.config import ModelConfig

        cfg = RunConfig()
        cfg = dataclasses.replace(
            cfg,
            model=ModelConfig(unit_embed_dim=8, hidden_dim=8,
                              hero_embed_dim=4),
            env=dataclasses.replace(cfg.env, n_envs=2, max_dota_time=30.0),
            ppo=dataclasses.replace(cfg.ppo, rollout_len=8, batch_rollouts=8),
            buffer=dataclasses.replace(
                cfg.buffer, capacity_rollouts=32, min_fill=8
            ),
            log_every=1000,
            checkpoint_every=1000,
        )
        ckdir = str(tmp_path / "ck")
        learner = Learner(cfg, checkpoint_dir=ckdir, actor="vec")
        result = {}

        def run():
            result["stats"] = learner.train(500)

        t = threading.Thread(target=run, daemon=True)
        t.start()
        assert wait_until(lambda: learner._host_step >= 2, timeout=120)
        learner.request_stop()
        t.join(timeout=120)
        assert not t.is_alive(), "graceful stop did not drain"
        stopped_at = result["stats"]["optimizer_steps"]
        assert 0 < stopped_at < 500
        mgr = CheckpointManager(ckdir)
        try:
            # the drain checkpoint landed at the exact stop step
            assert mgr.latest_step() == int(stopped_at)
        finally:
            mgr.close()


class TestFaultSchemaTier:
    def test_require_faults_tier_validates(self):
        """The FAULT_KEYS tier: missing fault counters fail validation,
        present ones (even at 0 — the servers eager-create them) pass."""
        import json as json_mod
        import sys

        scripts_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts",
        )
        sys.path.insert(0, scripts_dir)
        try:
            from check_telemetry_schema import FAULT_KEYS, validate_lines
        finally:
            sys.path.remove(scripts_dir)

        def line(scalars):
            return json_mod.dumps(
                {"ts": 1.0, "step": 0, "scalars": scalars}
            )

        base = {"x": 1.0}
        errors = validate_lines([line(base)], extra_required=FAULT_KEYS)
        missing = [e for e in errors if "never emitted" in e]
        assert missing and all(k in missing[0] for k in FAULT_KEYS)
        full = {**base, **{k: 0.0 for k in FAULT_KEYS}}
        # (REQUIRED_KEYS still missing — only assert the fault tier clears)
        errors = validate_lines([line(full)], extra_required=FAULT_KEYS)
        assert not any(
            k in e for e in errors for k in FAULT_KEYS
        )


class TestActorPartialFlush:
    def test_flush_partial_ships_in_progress_chunks(self):
        from dotaclient_tpu.actor import VecActorPool
        from dotaclient_tpu.config import RunConfig
        from dotaclient_tpu.models import init_params, make_policy

        import jax

        cfg = RunConfig()
        cfg = dataclasses.replace(
            cfg,
            env=dataclasses.replace(cfg.env, n_envs=2, max_dota_time=600.0),
            ppo=dataclasses.replace(cfg.ppo, rollout_len=16),
        )
        policy = make_policy(cfg.model, cfg.obs, cfg.actions)
        params = init_params(policy, jax.random.PRNGKey(0))
        got = []
        pool = VecActorPool(
            cfg, policy, params, seed=0, version=3, rollout_sink=got.extend
        )
        pool.run(3, refresh_every=0)   # 3 < rollout_len: nothing shipped yet
        shipped_before = len(got)
        n = pool.flush_partial()
        assert n > 0 and len(got) == shipped_before + n
        meta, arrays = got[-1]
        assert meta["length"] == 3       # the true partial length
        assert arrays["valid"][:3].sum() == 3 and arrays["valid"][3:].sum() == 0
        # flushing reset the cursors: a second flush ships nothing
        assert pool.flush_partial() == 0
