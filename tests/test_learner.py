"""Learner-loop + checkpoint/resume tests (SURVEY.md §5.4, §7 e2e slice)."""

import dataclasses

import numpy as np
import jax
import pytest

from dotaclient_tpu.config import RunConfig
from dotaclient_tpu.models import init_params, make_policy
from dotaclient_tpu.train.learner import Learner
from dotaclient_tpu.train.ppo import init_train_state
from dotaclient_tpu.utils.checkpoint import CheckpointManager


def tiny_config() -> RunConfig:
    cfg = RunConfig()
    return dataclasses.replace(
        cfg,
        env=dataclasses.replace(cfg.env, n_envs=2, max_dota_time=30.0),
        ppo=dataclasses.replace(cfg.ppo, rollout_len=8, batch_rollouts=8),
        buffer=dataclasses.replace(cfg.buffer, capacity_rollouts=32, min_fill=8),
        log_every=1000,  # silence console in tests
        checkpoint_every=1000,
    )


class TestLearnerLoop:
    def test_trains_and_publishes_weights(self):
        learner = Learner(tiny_config())
        stats = learner.train(3)
        assert stats["optimizer_steps"] == 3
        assert stats["frames_trained"] == 3 * 8 * 8
        assert int(learner.state.step) == 3
        # final weights published for out-of-process actors
        msg = learner.transport.latest_weights()
        assert msg is not None and msg.version == 3
        # in-process pool got refreshed along the way
        assert learner.pool.version >= 2


class TestMinibatchEpochs:
    def test_minibatched_multi_epoch_training(self):
        """epochs_per_batch × minibatches shuffled slices per consumed
        batch — the standard PPO regime; counters advance per optimizer
        step (one per minibatch)."""
        cfg = tiny_config()
        cfg = dataclasses.replace(
            cfg,
            ppo=dataclasses.replace(
                cfg.ppo, epochs_per_batch=2, minibatches=2, batch_rollouts=16
            ),
            log_every=4,   # a boundary fires within the run → loss captured
        )
        learner = Learner(cfg)
        stats = learner.train(4)   # one consumed batch = 4 optimizer steps
        assert stats["optimizer_steps"] == 4
        assert int(learner.state.step) == 4
        assert "loss" in stats and np.isfinite(stats["loss"])
        # frames count unique experience: one batch consumed
        assert stats["frames_trained"] == 16 * 8

    def test_minibatch_resume_reproduces_metrics(self, tmp_path):
        """The shuffle-stream position is checkpointed: a resumed learner
        replays the SAME upcoming permutations as the original's
        continuation (rel-tol: resumed state crosses a save/restore
        round-trip)."""
        from dotaclient_tpu.utils.checkpoint import CheckpointManager

        cfg = tiny_config()
        cfg = dataclasses.replace(
            cfg,
            ppo=dataclasses.replace(
                cfg.ppo, epochs_per_batch=1, minibatches=2, batch_rollouts=16
            ),
            log_every=1,
        )
        ckdir = str(tmp_path / "ck")
        a = Learner(cfg, seed=4, actor="device")
        a.train(2)
        mgr = CheckpointManager(ckdir)
        mgr.save(a.state, cfg, force=True, pipeline=a._pipeline_state())
        mgr.wait()
        a.train(2)
        b = Learner(cfg, checkpoint_dir=ckdir, restore=True, actor="device")
        assert b._mb_draws == a._mb_draws - 1  # one batch consumed post-save
        b.train(2)
        for k in ("loss", "policy_loss", "entropy"):
            assert a._last_metrics[k] == pytest.approx(
                b._last_metrics[k], rel=1e-5
            ), f"{k} diverged after minibatch resume"

    def test_indivisible_minibatches_rejected(self):
        cfg = tiny_config()
        cfg = dataclasses.replace(
            cfg, ppo=dataclasses.replace(cfg.ppo, minibatches=3)
        )
        with pytest.raises(ValueError, match="divisible"):
            Learner(cfg)


class TestCheckpoint:
    def test_save_restore_roundtrip(self, tmp_path):
        cfg = tiny_config()
        policy = make_policy(cfg.model, cfg.obs, cfg.actions)
        params = init_params(policy, jax.random.PRNGKey(0))
        state = init_train_state(params, cfg.ppo)
        state = dataclasses.replace(
            state,
            step=jax.numpy.asarray(7, jax.numpy.int32),
            version=jax.numpy.asarray(7, jax.numpy.int32),
        )
        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        assert mgr.save(state, cfg, force=True)
        mgr.wait()
        assert mgr.latest_step() == 7

        restored, rcfg = mgr.restore(cfg)
        assert int(restored.step) == 7
        assert int(restored.version) == 7
        assert rcfg.ppo.rollout_len == cfg.ppo.rollout_len
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            restored.params,
            state.params,
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            restored.opt_state,
            state.opt_state,
        )
        mgr.close()

    def test_learner_resume_continues_step_count(self, tmp_path):
        ckpt_dir = str(tmp_path / "ckpt")
        cfg = tiny_config()
        learner = Learner(cfg, checkpoint_dir=ckpt_dir)
        learner.train(2)
        learner.ckpt.wait()
        assert learner.ckpt.latest_step() == 2

        resumed = Learner(cfg, checkpoint_dir=ckpt_dir, restore=True)
        assert int(resumed.state.step) == 2
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            resumed.state.params,
            learner.state.params,
        )
        resumed.train(1)
        assert int(resumed.state.step) == 3
