"""Learner-loop + checkpoint/resume tests (SURVEY.md §5.4, §7 e2e slice)."""

import dataclasses

import numpy as np
import jax
import pytest

from dotaclient_tpu.config import RunConfig
from dotaclient_tpu.models import init_params, make_policy
from dotaclient_tpu.train.learner import Learner
from dotaclient_tpu.train.ppo import init_train_state
from dotaclient_tpu.utils.checkpoint import CheckpointManager


def tiny_config() -> RunConfig:
    cfg = RunConfig()
    return dataclasses.replace(
        cfg,
        env=dataclasses.replace(cfg.env, n_envs=2, max_dota_time=30.0),
        ppo=dataclasses.replace(cfg.ppo, rollout_len=8, batch_rollouts=8),
        buffer=dataclasses.replace(cfg.buffer, capacity_rollouts=32, min_fill=8),
        log_every=1000,  # silence console in tests
        checkpoint_every=1000,
    )


class TestLearnerLoop:
    def test_trains_and_publishes_weights(self):
        learner = Learner(tiny_config())
        stats = learner.train(3)
        assert stats["optimizer_steps"] == 3
        assert stats["frames_trained"] == 3 * 8 * 8
        assert int(learner.state.step) == 3
        # final weights published for out-of-process actors
        msg = learner.transport.latest_weights()
        assert msg is not None and msg.version == 3
        # in-process pool got refreshed along the way
        assert learner.pool.version >= 2


class TestMinibatchEpochs:
    def test_minibatched_multi_epoch_training(self):
        """epochs_per_batch × minibatches shuffled slices per consumed
        batch — the standard PPO regime; counters advance per optimizer
        step (one per minibatch)."""
        cfg = tiny_config()
        cfg = dataclasses.replace(
            cfg,
            ppo=dataclasses.replace(
                cfg.ppo, epochs_per_batch=2, minibatches=2, batch_rollouts=16
            ),
            log_every=4,   # a boundary fires within the run → loss captured
        )
        learner = Learner(cfg)
        stats = learner.train(4)   # one consumed batch = 4 optimizer steps
        assert stats["optimizer_steps"] == 4
        assert int(learner.state.step) == 4
        assert "loss" in stats and np.isfinite(stats["loss"])
        # frames count unique experience: one batch consumed
        assert stats["frames_trained"] == 16 * 8

    @pytest.mark.slow   # tier-1 duration audit (ISSUE 6): ~110s on the reference container
    def test_minibatch_resume_reproduces_metrics(self, tmp_path):
        """The shuffle-stream position is checkpointed: a resumed learner
        replays the SAME upcoming permutations as the original's
        continuation (rel-tol: resumed state crosses a save/restore
        round-trip)."""
        from dotaclient_tpu.utils.checkpoint import CheckpointManager

        cfg = tiny_config()
        cfg = dataclasses.replace(
            cfg,
            ppo=dataclasses.replace(
                cfg.ppo, epochs_per_batch=1, minibatches=2, batch_rollouts=16
            ),
            log_every=1,
        )
        ckdir = str(tmp_path / "ck")
        a = Learner(cfg, seed=4, actor="device")
        a.train(2)
        mgr = CheckpointManager(ckdir)
        mgr.save(a.state, cfg, force=True, pipeline=a._pipeline_state())
        mgr.wait()
        a.train(2)
        b = Learner(cfg, checkpoint_dir=ckdir, restore=True, actor="device")
        assert b._mb_draws == a._mb_draws - 1  # one batch consumed post-save
        b.train(2)
        for k in ("loss", "policy_loss", "entropy"):
            assert a._last_metrics[k] == pytest.approx(
                b._last_metrics[k], rel=1e-5
            ), f"{k} diverged after minibatch resume"

    def test_indivisible_minibatches_rejected(self):
        cfg = tiny_config()
        cfg = dataclasses.replace(
            cfg, ppo=dataclasses.replace(cfg.ppo, minibatches=3)
        )
        with pytest.raises(ValueError, match="divisible"):
            Learner(cfg)


class TestFusedEpochStep:
    def multi_cfg(self, fused: bool) -> "RunConfig":
        cfg = tiny_config()
        return dataclasses.replace(
            cfg,
            ppo=dataclasses.replace(
                cfg.ppo, epochs_per_batch=2, minibatches=2,
                batch_rollouts=16, fused_epoch=fused,
            ),
        )

    @pytest.mark.slow   # tier-1 duration audit (ISSUE 6): ~38s on the reference container
    def test_one_dispatch_per_batch(self):
        """The acceptance contract: with minibatches > 1, one consumed
        batch issues exactly ONE donated dispatch (the fused epoch step) —
        not epochs × minibatches gather+step pairs."""
        learner = Learner(self.multi_cfg(fused=True), actor="device")
        assert learner.epoch_step is not None
        calls = {"epoch": 0, "staged": 0, "gather": 0}
        real_epoch = learner.epoch_step
        learner.epoch_step = lambda *a: (calls.__setitem__(
            "epoch", calls["epoch"] + 1) or real_epoch(*a))
        learner.train_step = lambda *a: calls.__setitem__(
            "staged", calls["staged"] + 1)
        learner._minibatch_gather = lambda *a: calls.__setitem__(
            "gather", calls["gather"] + 1)
        learner.train(4)   # one consumed batch = 2 epochs × 2 minibatches
        assert calls == {"epoch": 1, "staged": 0, "gather": 0}

    @pytest.mark.slow   # tier-1 duration audit (ISSUE 6): ~46s on the reference container
    def test_fused_epoch_off_uses_staged_path(self):
        learner = Learner(self.multi_cfg(fused=False), actor="device")
        assert learner.epoch_step is None
        stats = learner.train(4)
        assert stats["optimizer_steps"] == 4
        assert int(learner.state.step) == 4

    @pytest.mark.slow   # tier-1 duration audit (ISSUE 6): ~64s on the reference container
    def test_fused_matches_staged_in_learner(self):
        """End-to-end parity: identical seeds and experience, fused epoch
        vs staged loop — same permutation stream, same final params (to
        the float-ulp XLA-fusion bound of the unit parity test in
        tests/test_train.py)."""
        a = Learner(self.multi_cfg(fused=True), seed=3, actor="device")
        b = Learner(self.multi_cfg(fused=False), seed=3, actor="device")
        a.train(4)
        b.train(4)
        assert a._mb_draws == b._mb_draws == 2
        jax.tree.map(
            lambda x, y: np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=1e-4, atol=1e-7
            ),
            a.state.params,
            b.state.params,
        )


class TestPrefetchLane:
    def surplus_cfg(self) -> "RunConfig":
        # device actor produces 8 rollouts per collect (n_lanes == n_envs
        # vs a scripted bot); batch of 8 with min_fill 16 leaves one whole
        # batch in the ring after the first take — the prefetch lane has
        # something to stage behind the dispatch
        cfg = tiny_config()
        return dataclasses.replace(
            cfg,
            env=dataclasses.replace(cfg.env, n_envs=8),
            ppo=dataclasses.replace(cfg.ppo, batch_rollouts=8),
            buffer=dataclasses.replace(
                cfg.buffer, capacity_rollouts=32, min_fill=16
            ),
        )

    @pytest.mark.slow   # tier-1 duration audit (ISSUE 6): ~66s on the reference container
    def test_prefetch_hits_and_gauges(self):
        learner = Learner(self.surplus_cfg(), actor="device")
        learner.train(6)
        assert learner._prefetch_hits >= 1
        learner._publish_pipeline_gauges()
        snap = learner.telemetry.snapshot()
        assert 0.0 < snap["learner/prefetch_hit_rate"] <= 1.0
        assert 0.0 <= snap["learner/overlap_fraction"] <= 1.0
        assert snap["span/learner/prefetch/count"] >= 1

    def test_end_of_run_leaves_clean_lane_and_flush_restores_ring(self):
        """train() never ends with a held batch (the loop skips staging
        behind the final dispatch), and _flush_prefetch returns a staged
        batch's rows to the FRONT of the ring — prefetching can never turn
        into experience loss."""
        learner = Learner(self.surplus_cfg(), actor="device")
        learner.train(1)
        assert learner._prefetched is None
        assert learner.buffer._held == {}
        size_after = learner.buffer.size
        # stage a batch by hand, then flush: ring restored, and the next
        # take re-serves the SAME rows
        learner._prefetch_next(drain_transport=False)
        if learner._prefetched is None:
            pytest.skip("ring underfilled — nothing prefetched to flush")
        staged = np.asarray(learner._prefetched["rewards"])
        learner._flush_prefetch()
        assert learner._prefetched is None
        assert learner.buffer._held == {}
        assert learner.buffer.size == size_after
        again = learner.buffer.take(current_version=learner._host_version)
        np.testing.assert_array_equal(staged, np.asarray(again["rewards"]))

    @pytest.mark.slow   # tier-1 duration audit (ISSUE 6): ~49s on the reference container
    def test_pipeline_checkpoint_includes_flushed_prefetch(self, tmp_path):
        """_pipeline_state folds an in-flight prefetched batch back into
        the buffer snapshot — a restore sees every unconsumed rollout."""
        learner = Learner(self.surplus_cfg(), actor="device")
        learner.train(2)
        # force a live prefetched batch, then snapshot
        chunk, _ = learner.device_actor.collect(learner.state.params)
        learner.buffer.add_device(chunk, learner._host_version)
        learner._prefetch_next(drain_transport=False)
        if learner._prefetched is None:
            pytest.skip("ring underfilled — nothing prefetched to flush")
        held_before = dict(learner.buffer._held)
        assert held_before
        state = learner._pipeline_state()
        assert learner._prefetched is None
        assert learner.buffer._held == {}
        order = [int(s) for s in state["buffer"]["order"] if s >= 0]
        for slots in held_before.values():
            for s in slots:
                assert s in order


class TestCheckpoint:
    def test_save_restore_roundtrip(self, tmp_path):
        cfg = tiny_config()
        policy = make_policy(cfg.model, cfg.obs, cfg.actions)
        params = init_params(policy, jax.random.PRNGKey(0))
        state = init_train_state(params, cfg.ppo)
        state = dataclasses.replace(
            state,
            step=jax.numpy.asarray(7, jax.numpy.int32),
            version=jax.numpy.asarray(7, jax.numpy.int32),
        )
        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        assert mgr.save(state, cfg, force=True)
        mgr.wait()
        assert mgr.latest_step() == 7

        restored, rcfg = mgr.restore(cfg)
        assert int(restored.step) == 7
        assert int(restored.version) == 7
        assert rcfg.ppo.rollout_len == cfg.ppo.rollout_len
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            restored.params,
            state.params,
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            restored.opt_state,
            state.opt_state,
        )
        mgr.close()

    @pytest.mark.slow   # tier-1 duration audit (ISSUE 6): ~42s on the reference container
    def test_learner_resume_continues_step_count(self, tmp_path):
        ckpt_dir = str(tmp_path / "ckpt")
        cfg = tiny_config()
        learner = Learner(cfg, checkpoint_dir=ckpt_dir)
        learner.train(2)
        learner.ckpt.wait()
        assert learner.ckpt.latest_step() == 2

        resumed = Learner(cfg, checkpoint_dir=ckpt_dir, restore=True)
        assert int(resumed.state.step) == 2
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            resumed.state.params,
            learner.state.params,
        )
        resumed.train(1)
        assert int(resumed.state.step) == 3
