"""Multi-chip learner (ISSUE 10): mesh-sharded train step, device-sharded
trajectory ring, sharded snapshot/checkpoint paths.

tests/conftest.py forces 8 host devices, so every test here runs on a real
8-way mesh; the 1-device comparisons build a second mesh over
``jax.devices()[:1]`` in the same process (make_mesh's explicit-layout
slicing) — exactly how bench.py's multichip parity probe and the
single-chip degenerate case work.
"""

import dataclasses
import json
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dotaclient_tpu.config import MeshConfig, RunConfig
from dotaclient_tpu.parallel import (
    batch_shard_count,
    make_mesh,
)
from dotaclient_tpu.train.ppo import (
    example_batch,
    init_train_state,
    make_epoch_step,
    train_state_sharding,
)
from dotaclient_tpu.utils import telemetry

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def tiny_config(**over) -> RunConfig:
    # batch_rollouts/capacity stay multiples of 8: batches shard over the
    # 8-way data axis (same rule every sharded-path test file follows)
    cfg = RunConfig()
    return dataclasses.replace(
        cfg,
        env=dataclasses.replace(cfg.env, n_envs=2, max_dota_time=30.0),
        ppo=dataclasses.replace(cfg.ppo, rollout_len=4, batch_rollouts=8),
        buffer=dataclasses.replace(
            cfg.buffer, capacity_rollouts=16, min_fill=8
        ),
        log_every=1000,
        checkpoint_every=1000,
        **over,
    )


def seeded_batch(cfg: RunConfig, seed: int = 0):
    rng = np.random.default_rng(seed)
    B, T = cfg.ppo.batch_rollouts, cfg.ppo.rollout_len
    batch = dict(example_batch(cfg, batch=B))
    batch["obs"] = dict(batch["obs"])
    batch["obs"]["units"] = jnp.asarray(
        rng.normal(size=batch["obs"]["units"].shape).astype(np.float32)
    )
    batch["rewards"] = jnp.asarray(
        rng.normal(size=(B, T)).astype(np.float32) * 0.1
    )
    batch["behavior_logp"] = jnp.asarray(
        -np.abs(rng.normal(size=(B, T))).astype(np.float32)
    )
    return batch


class TestMeshConstruction:
    def test_explicit_layout_slices_devices(self):
        """An explicit data_parallel smaller than the visible device set
        takes the first dcn×data×model devices — the 1-device mesh is the
        degenerate case of the one sharded code path, buildable inside an
        8-device process (the parity probes depend on it)."""
        mesh1 = make_mesh(MeshConfig(data_parallel=1))
        assert mesh1.devices.size == 1
        mesh2 = make_mesh(MeshConfig(data_parallel=1, model_parallel=2))
        assert mesh2.devices.size == 2
        # the default -1 still takes everything
        assert make_mesh(MeshConfig()).devices.size == 8

    def test_batch_shard_count_shared_helper(self):
        cfg = MeshConfig()
        assert batch_shard_count(make_mesh(cfg), cfg) == 8
        assert batch_shard_count(
            make_mesh(MeshConfig(data_parallel=1)),
            MeshConfig(data_parallel=1),
        ) == 1

    def test_mesh_override_flag_parses(self):
        from dotaclient_tpu.utils.overrides import parse_dataclass_overrides

        out = parse_dataclass_overrides(
            MeshConfig, "data_parallel=4,model_parallel=2", "--mesh"
        )
        assert out == {"data_parallel": 4, "model_parallel": 2}
        with pytest.raises(ValueError, match="--mesh"):
            parse_dataclass_overrides(MeshConfig, "nope=1", "--mesh")


class TestShardedParity:
    @pytest.mark.slow   # two epoch-step compiles (1-dev + 8-dev mesh)
    def test_sharded_epoch_step_matches_single_device(self):
        """The 8-way data-sharded fused epoch step (grad psum emitted from
        the shardings) must produce the same updates as the 1-device mesh
        on the same data with the same ``_mb_rng`` permutation stream —
        within float-reassociation tolerance (the psum reorders sums)."""
        from dotaclient_tpu.models import init_params, make_policy

        cfg = tiny_config()
        cfg = dataclasses.replace(
            cfg,
            ppo=dataclasses.replace(
                cfg.ppo, epochs_per_batch=2, minibatches=2
            ),
        )
        B, E = cfg.ppo.batch_rollouts, cfg.ppo.epochs_per_batch
        policy = make_policy(cfg.model, cfg.obs, cfg.actions)
        batch = seeded_batch(cfg)
        results = {}
        for label, devices in (
            ("one", jax.devices()[:1]),
            ("mesh", None),
        ):
            mesh = make_mesh(cfg.mesh, devices=devices)
            st_sh = train_state_sharding(policy, cfg, mesh)
            state = jax.device_put(
                init_train_state(
                    init_params(policy, jax.random.PRNGKey(cfg.seed)),
                    cfg.ppo,
                ),
                st_sh,
            )
            step = make_epoch_step(policy, cfg, mesh)
            mb_rng = np.random.default_rng(cfg.seed + 1)   # learner stream
            losses = []
            for _ in range(3):
                perms = np.stack(
                    [mb_rng.permutation(B) for _ in range(E)]
                ).astype(np.int32)
                state, m = step(state, batch, perms)
                losses.append(float(np.asarray(m["loss"])))
            results[label] = (losses, jax.device_get(state.params))
        l_one, p_one = results["one"]
        l_mesh, p_mesh = results["mesh"]
        # Reassociation tolerance, not ulp: the psum reorders reduction
        # sums and the tiny-config training dynamics amplify the per-step
        # float noise across the 3 steps (measured ~7e-4 relative on this
        # shape). A REAL divergence — dropped minibatch slice,
        # sharding-dependent RNG, wrong perm stream — shows up as O(1).
        np.testing.assert_allclose(l_mesh, l_one, rtol=5e-3, atol=1e-5)
        for a, b in zip(jax.tree.leaves(p_one), jax.tree.leaves(p_mesh)):
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), rtol=5e-3, atol=1e-4
            )


class TestDirectToShardIngest:
    def _decoded(self, cfg, n, version=0, seed=0):
        """n decoded-payload-shaped (meta, arrays) rows through the real
        wire codec, honoring the config's rollout_wire_dtype."""
        from dotaclient_tpu.transport import serialize as S

        rng = np.random.default_rng(seed)
        row = jax.tree.map(
            lambda x: np.array(x[0]), example_batch(cfg, batch=1)
        )
        flat = S.flatten_tree(row)
        for name, arr in flat.items():
            if arr.dtype == np.float32:
                flat[name] = rng.normal(size=arr.shape).astype(np.float32)
        row = S.unflatten_tree(flat)
        payload = bytes(
            S.encode_rollout_bytes(
                row, version, 0, 0, cfg.ppo.rollout_len, 0.0,
                wire_dtype=cfg.transport.rollout_wire_dtype,
                int_bounds=S.rollout_int_bounds(cfg),
            )
        )
        out = []
        for i in range(n):
            meta, arrays = S.decode_rollout_bytes(payload)
            meta["rollout_id"] = i
            out.append((meta, arrays))
        return out, row

    def test_host_scatter_pins_data_sharded_rows(self):
        """The host ingest path's compiled scatter must take its rows
        DATA-SHARDED (each device receives 1/n of the group's bytes at
        H2D), not replicated — the single-device-memory/replicated-rows
        scatter is the regression this PR exists to fix."""
        from dotaclient_tpu.buffer import TrajectoryBuffer

        cfg = tiny_config()
        mesh = make_mesh(cfg.mesh)
        buf = TrajectoryBuffer(cfg, mesh)
        in_sh = buf._scatter.lower(
            jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), buf._store
            ),
            jax.tree.map(
                lambda x: jax.ShapeDtypeStruct((8,) + x.shape[1:], x.dtype),
                buf._store,
            ),
            jax.ShapeDtypeStruct((8,), np.int32),
        ).compile().input_shardings[0]
        # arg order: store tree, rows tree, idx — rows must shard over data
        n_leaves = len(jax.tree.leaves(buf._store))
        rows_shardings = jax.tree.leaves(in_sh)[n_leaves:2 * n_leaves]
        for s in rows_shardings:
            assert not s.is_fully_replicated, (
                f"ingest rows compiled replicated ({s}) — every device "
                f"would receive the full group's bytes"
            )

    def test_ingest_roundtrip_narrow_ring_on_mesh(self):
        """Direct-to-shard ingest through the NARROW (bf16-wire) ring:
        decoded rows scatter to an 8-way-sharded store and ``take()``
        hands back the on-device-upcast batch, bit-identical to decoding
        the wire with upcast — the PR 7 contract carried onto the mesh."""
        try:
            import ml_dtypes  # noqa: F401
        except ImportError:
            pytest.skip("ml_dtypes unavailable")
        from dotaclient_tpu.buffer import TrajectoryBuffer
        from dotaclient_tpu.transport import serialize as S

        cfg = tiny_config()
        cfg = dataclasses.replace(
            cfg,
            transport=dataclasses.replace(
                cfg.transport, rollout_wire_dtype="bfloat16"
            ),
        )
        mesh = make_mesh(cfg.mesh)
        buf = TrajectoryBuffer(cfg, mesh)
        decoded, _ = self._decoded(cfg, 8)
        assert buf.add(decoded, current_version=0) == 8
        # ring leaves live sharded across all 8 devices, in the narrow dtype
        store_leaf = jax.tree.leaves(buf._store)[0]
        assert len(store_leaf.sharding.device_set) == 8
        batch = buf.take(batch_size=8, current_version=0)
        assert batch is not None
        # consumed batch is already laid out for the sharded step
        assert len(batch["rewards"].sharding.device_set) == 8
        assert not batch["rewards"].sharding.is_fully_replicated
        assert batch["rewards"].dtype == jnp.float32   # upcast on-device
        # value parity vs decoding the wire with upcast on the host
        payload_meta, arrays = decoded[0]
        host = S.decode_rollout_bytes(
            bytes(
                S.encode_rollout_bytes(
                    jax.tree.map(np.asarray, arrays), 0, 0, 0,
                    cfg.ppo.rollout_len, 0.0,
                )
            ),
            upcast=True,
        )[1]
        got_row = jax.tree.map(lambda x: np.asarray(x[0]), batch)
        for a, b in zip(jax.tree.leaves(got_row), jax.tree.leaves(host)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_pad_rows_shard_divisible_and_trace_bounded(self):
        """Ingest groups pad to shard-divisible pow2 buckets: every padded
        size divides by the 8-way shard count (jax rejects a non-dividing
        NamedSharding) and the retrace bound tightens to
        log2(capacity/n_data)+1 distinct programs."""
        from dotaclient_tpu.buffer import TrajectoryBuffer

        cfg = tiny_config()
        buf = TrajectoryBuffer(cfg, make_mesh(cfg.mesh))
        assert [buf._pad_rows(n) for n in (1, 3, 8, 9, 16)] == [
            8, 8, 8, 16, 16
        ]
        rid = 0
        for n in (1, 3, 5, 8):   # 4 distinct sizes, all → the 8-bucket
            decoded, _ = self._decoded(cfg, n, seed=rid)
            for i, (meta, _a) in enumerate(decoded):
                meta["rollout_id"] = rid + i
            rid += n
            buf.add(decoded, current_version=0)
        assert buf.scatter_traces <= 2   # log2(16/8)+1

    def test_shard_bytes_gauge(self):
        from dotaclient_tpu.buffer import TrajectoryBuffer

        reg = telemetry.Registry()
        cfg = tiny_config()
        buf = TrajectoryBuffer(cfg, make_mesh(cfg.mesh), registry=reg)
        total = sum(
            leaf.size * leaf.dtype.itemsize
            for leaf in jax.tree.leaves(jax.device_get(buf._store))
        )
        assert reg.snapshot()["buffer/shard_bytes"] == float(total // 8)


class TestCrossDeviceCountRestore:
    def _tiny_state(self):
        params = {
            "dense": {"kernel": jnp.arange(32, dtype=jnp.float32).reshape(4, 8)},
            "scale": jnp.asarray(2.5, jnp.float32),
        }
        return init_train_state(params, RunConfig().ppo)

    def test_checkpoint_restores_across_device_counts(self, tmp_path):
        """A checkpoint written by an 8-device-sharded state restores into
        a 1-device mesh (and vice versa): saves are host-layout arrays —
        device-count-free — and the restore side re-commits via the
        target mesh's state_shardings, exactly what the learner's
        --restore/rollback paths do."""
        from dotaclient_tpu.parallel.sharding import state_shardings
        from dotaclient_tpu.utils.checkpoint import CheckpointManager

        cfg = RunConfig()
        mesh8 = make_mesh(cfg.mesh)
        mesh1 = make_mesh(cfg.mesh, devices=jax.devices()[:1])
        for src_mesh, dst_mesh in ((mesh8, mesh1), (mesh1, mesh8)):
            state = self._tiny_state()
            src_sh = state_shardings(state, src_mesh, cfg.mesh)
            state = jax.device_put(state, src_sh)
            d = tmp_path / f"ck_{src_mesh.devices.size}to{dst_mesh.devices.size}"
            mgr = CheckpointManager(str(d))
            try:
                assert mgr.save(state, cfg, force=True)
                mgr.wait()
                restored, _ = mgr.restore(cfg, abstract_state=state)
            finally:
                mgr.close()
            dst_sh = state_shardings(restored, dst_mesh, cfg.mesh)
            resharded = jax.device_put(restored, dst_sh)
            leaf = jax.tree.leaves(resharded.params)[0]
            assert len(leaf.sharding.device_set) == dst_mesh.devices.size
            for a, b in zip(
                jax.tree.leaves(jax.device_get(state)),
                jax.tree.leaves(jax.device_get(resharded)),
            ):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_buffer_state_dict_roundtrips_across_mesh_sizes(self):
        """The ring's state_dict is host arrays; load_state_dict re-commits
        to THIS buffer's sharding — an 8-way ring snapshot restores into a
        1-device ring and back with identical contents."""
        from dotaclient_tpu.buffer import TrajectoryBuffer

        cfg = tiny_config()
        mesh8 = make_mesh(cfg.mesh)
        cfg1 = dataclasses.replace(
            cfg, mesh=dataclasses.replace(cfg.mesh, data_parallel=1)
        )
        mesh1 = make_mesh(cfg1.mesh)
        src = TrajectoryBuffer(cfg, mesh8)
        decoded, _ = TestDirectToShardIngest()._decoded(cfg, 8)
        src.add(decoded, current_version=0)
        snap = src.state_dict()
        dst = TrajectoryBuffer(cfg1, mesh1)
        dst.load_state_dict(snap)
        assert dst.size == src.size
        b1 = dst.take(batch_size=8, current_version=0)
        assert len(jax.tree.leaves(b1)[0].sharding.device_set) == 1
        src.load_state_dict(snap)   # and back onto the mesh
        b8 = src.take(batch_size=8, current_version=0)
        for a, b in zip(jax.tree.leaves(b1), jax.tree.leaves(b8)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestShardedSnapshots:
    @pytest.mark.slow   # learner construction compiles the full pipeline
    def test_zero_train_thread_fetches_for_sharded_snapshots(self):
        """Async publish/checkpoint boundaries on an 8-way-sharded state
        stay DISPATCH-ONLY on the train thread: the on-device copy + the
        engine submit perform zero train-thread device_gets — assembling
        replicated params from shard 0 is the engine thread's job."""
        from dotaclient_tpu.train.learner import Learner

        learner = Learner(tiny_config(), actor="device")
        try:
            learner.train(2)   # compile + warm every boundary program
            train_thread = threading.current_thread()
            calls = {"train": 0}
            real_device_get = jax.device_get

            def counting(x):
                if threading.current_thread() is train_thread:
                    calls["train"] += 1
                return real_device_get(x)

            jax.device_get = counting
            try:
                for _ in range(3):
                    learner._publish_weights()
                learner._snap_engine.submit_checkpoint(
                    learner._snap_copy(learner.state), learner.config
                )
            finally:
                jax.device_get = real_device_get
            assert calls["train"] == 0, (
                f"{calls['train']} device fetch(es) on the train thread "
                f"during sharded snapshot boundaries — the boundary must "
                f"stay dispatch-only"
            )
            assert learner._snap_engine.drain(timeout=30)
        finally:
            if learner._snap_engine is not None:
                learner._snap_engine.stop()

    @pytest.mark.slow   # learner construction compiles the full pipeline
    def test_learner_state_committed_to_mesh_and_telemetry(self):
        """The constructor commits the TrainState to its state_shardings
        (every param leaf lives on all 8 devices before the first
        dispatch) and eager-creates the --require-multichip keys."""
        from dotaclient_tpu.train.learner import Learner

        learner = Learner(tiny_config(), actor="device")
        try:
            leaf = jax.tree.leaves(learner.state.params)[0]
            assert len(leaf.sharding.device_set) == 8
            snap = telemetry.get_registry().snapshot()
            assert snap["mesh/n_devices"] == 8.0
            assert snap["mesh/data_shards"] == 8.0
            assert snap["buffer/shard_bytes"] > 0
            assert snap["learner/psum_ms"] >= 0
        finally:
            if learner._snap_engine is not None:
                learner._snap_engine.stop()


class TestPreflightAndSchema:
    def _load_script(self, name):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            name, os.path.join(ROOT, "scripts", f"{name}.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_preflight_classifies_libtpu_mismatch(self):
        """The exact failure shape that produced MULTICHIP_r01.json's
        40-frame traceback must classify into a one-line reason + a
        remediation line (the actionable-skip contract)."""
        mod = self._load_script("run_multichip")
        tail = (
            'jax.errors.JaxRuntimeError: FAILED_PRECONDITION: libtpu '
            'version mismatch: terminal has "TFRT TPU v5 lite ... '
            'cl/831091709", client AOT libtpu has "... cl/854318611". '
            'Client and terminal must use the same libtpu build'
        )
        got = mod.classify_backend_error(tail)
        assert got is not None
        reason, remediation = got
        assert "libtpu" in reason
        assert "--force-host" in remediation
        # generic FAILED_PRECONDITION still classifies (second signature)
        assert mod.classify_backend_error(
            "FAILED_PRECONDITION: something else"
        ) is not None
        # a hung backend init surfaces as the timeout marker and must
        # classify too (a held chip usually BLOCKS init, not errors)
        timeout_reason, timeout_fix = mod.classify_backend_error(
            "MULTICHIP_PREFLIGHT_TIMEOUT after 300s\n"
        )
        assert "timeout" in timeout_reason
        assert "--force-host" in timeout_fix
        # unknown breakage stays unclassified → caller reports the tail
        assert mod.classify_backend_error("ValueError: nope") is None

    def test_preflight_timeout_becomes_marker_not_traceback(self):
        """A subprocess that outlives its timeout returns the classifiable
        marker (rc -1) instead of raising TimeoutExpired out of the
        preflight — the no-traceback contract covers hangs."""
        mod = self._load_script("run_multichip")
        rc, out = mod._run_subprocess(
            "import time; time.sleep(60)", timeout=1.0
        )
        assert rc == -1
        assert "MULTICHIP_PREFLIGHT_TIMEOUT" in out
        assert mod.classify_backend_error(out) is not None

    def test_require_multichip_tier(self):
        """--require-multichip pins exactly the eager-created mesh keys."""
        mod = self._load_script("check_telemetry_schema")
        base = {k: 1.0 for k in mod.REQUIRED_KEYS}
        for root in {
            k.rsplit("/", 1)[0]
            for k in mod.REQUIRED_KEYS
            if k.startswith("span/")
        }:
            for leaf in mod.TIMER_LEAVES:
                base[f"{root}/{leaf}"] = 1.0
        full = dict(base)
        full.update({k: 8.0 for k in mod.MULTICHIP_KEYS})
        line = json.dumps({"ts": 1.0, "step": 0, "scalars": full})
        assert mod.validate_lines(
            [line], extra_required=mod.MULTICHIP_KEYS
        ) == []
        missing = dict(full)
        del missing["mesh/n_devices"]
        line = json.dumps({"ts": 1.0, "step": 0, "scalars": missing})
        errs = mod.validate_lines([line], extra_required=mod.MULTICHIP_KEYS)
        assert any("mesh/n_devices" in e for e in errs)
