"""Contract tests for ``AmqpTransport`` against an in-memory fake pika.

VERDICT round 1 flagged ``AmqpTransport`` as compiles-only code — the
broker topology (durable experience work-queue + fanout weights exchange,
SURVEY.md §2.4) was never exercised. The sandbox has no broker and no pika,
so these tests install a faithful in-memory fake of the pika surface the
transport uses (BlockingConnection / channel / queue_declare /
exchange_declare / basic_publish / consume / basic_get) and verify the
transport's AMQP semantics:

  * experience is a work queue — each rollout consumed by exactly one
    learner, acked messages never redelivered;
  * weights ride a fanout exchange — every bound consumer queue gets every
    publish, and ``latest_weights`` drains to the newest (latest-wins);
  * consumers that bind after a publish miss it (fanout, not a store);
  * unacked deliveries are requeued when the consumer loop stops.
"""

from __future__ import annotations

import sys
import types
from collections import deque

import pytest

from dotaclient_tpu.protos import dota_pb2 as pb


# ---------------------------------------------------------------------------
# fake pika
# ---------------------------------------------------------------------------


class _FakeBroker:
    """One RabbitMQ: named queues, fanout exchanges, bindings."""

    def __init__(self) -> None:
        self.queues: dict[str, deque] = {}
        self.exchanges: dict[str, list[str]] = {}  # exchange -> bound queues
        self._anon = 0

    def declare_queue(self, name: str) -> str:
        if not name:
            self._anon += 1
            name = f"amq.gen-{self._anon}"
        self.queues.setdefault(name, deque())
        return name

    def declare_exchange(self, name: str) -> None:
        self.exchanges.setdefault(name, [])

    def bind(self, exchange: str, queue: str) -> None:
        self.exchanges.setdefault(exchange, []).append(queue)

    def publish(self, exchange: str, routing_key: str, body: bytes) -> None:
        if exchange == "":  # default exchange: routing key names the queue
            self.queues.setdefault(routing_key, deque()).append(body)
        else:  # fanout: copy to every bound queue
            for q in self.exchanges.get(exchange, []):
                self.queues[q].append(body)


class _Method:
    def __init__(self, queue: str = "", delivery_tag: int = 0) -> None:
        self.queue = queue
        self.delivery_tag = delivery_tag


class _DeclareOk:
    def __init__(self, queue: str) -> None:
        self.method = _Method(queue=queue)


class _FakeChannel:
    def __init__(self, broker: _FakeBroker) -> None:
        self._b = broker
        self._tag = 0
        self._unacked: dict[int, tuple[str, bytes]] = {}

    def queue_declare(self, queue: str = "", durable: bool = False,
                      exclusive: bool = False) -> _DeclareOk:
        return _DeclareOk(self._b.declare_queue(queue))

    def exchange_declare(self, exchange: str, exchange_type: str) -> None:
        assert exchange_type == "fanout"
        self._b.declare_exchange(exchange)

    def queue_bind(self, exchange: str, queue: str) -> None:
        self._b.bind(exchange, queue)

    def basic_publish(self, exchange: str, routing_key: str, body: bytes) -> None:
        self._b.publish(exchange, routing_key, body)

    def consume(self, queue: str, inactivity_timeout=None):
        q = self._b.queues[queue]
        while True:
            if q:
                body = q.popleft()
                self._tag += 1
                self._unacked[self._tag] = (queue, body)
                yield _Method(queue, self._tag), None, body
            else:
                # empty queue == broker inactivity: one (None, None, None)
                # wakeup per pika's inactivity_timeout contract
                yield None, None, None

    def basic_ack(self, delivery_tag: int) -> None:
        self._unacked.pop(delivery_tag, None)

    def cancel(self) -> None:
        # pika: cancelling the consumer requeues unacked deliveries
        for queue, body in reversed(list(self._unacked.values())):
            self._b.queues[queue].appendleft(body)
        self._unacked.clear()

    def basic_get(self, queue: str, auto_ack: bool = False):
        q = self._b.queues[queue]
        if not q:
            return None, None, None
        return _Method(queue), None, q.popleft()


class _FakeConnection:
    def __init__(self, params) -> None:
        self._broker = params._broker

    def channel(self) -> _FakeChannel:
        return _FakeChannel(self._broker)


def _install_fake_pika(monkeypatch) -> _FakeBroker:
    broker = _FakeBroker()
    mod = types.ModuleType("pika")

    class ConnectionParameters:
        def __init__(self, host: str, port: int = 5672) -> None:
            self.host, self.port = host, port
            self._broker = broker

    mod.ConnectionParameters = ConnectionParameters
    mod.BlockingConnection = _FakeConnection
    monkeypatch.setitem(sys.modules, "pika", mod)
    return broker


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _rollout(env_id: int, version: int = 1) -> pb.Rollout:
    r = pb.Rollout()
    r.env_id = env_id
    r.model_version = version
    r.length = 4
    return r


def _weights(version: int) -> pb.ModelWeights:
    w = pb.ModelWeights()
    w.version = version
    return w


# ---------------------------------------------------------------------------
# tests
# ---------------------------------------------------------------------------


class TestAmqpTransport:
    def test_requires_pika(self, monkeypatch):
        monkeypatch.setitem(sys.modules, "pika", None)
        from dotaclient_tpu.transport.queues import AmqpTransport

        with pytest.raises(RuntimeError, match="pika"):
            AmqpTransport("localhost")

    def test_rollout_work_queue_exactly_once(self, monkeypatch):
        _install_fake_pika(monkeypatch)
        from dotaclient_tpu.transport.queues import AmqpTransport

        actor_a = AmqpTransport("broker")
        actor_b = AmqpTransport("broker")
        learner = AmqpTransport("broker")

        for i in range(3):
            actor_a.publish_rollout(_rollout(i))
        for i in range(3, 5):
            actor_b.publish_rollout(_rollout(i))

        got = learner.consume_rollouts(max_count=10, timeout=0.01)
        assert sorted(r.env_id for r in got) == [0, 1, 2, 3, 4]
        # consumed exactly once: a second consume sees nothing
        assert learner.consume_rollouts(max_count=10, timeout=0.01) == []

    def test_consume_respects_max_count_and_requeues_rest(self, monkeypatch):
        _install_fake_pika(monkeypatch)
        from dotaclient_tpu.transport.queues import AmqpTransport

        actor = AmqpTransport("broker")
        learner = AmqpTransport("broker")
        for i in range(6):
            actor.publish_rollout(_rollout(i))

        first = learner.consume_rollouts(max_count=4, timeout=0.01)
        assert [r.env_id for r in first] == [0, 1, 2, 3]
        rest = learner.consume_rollouts(max_count=10, timeout=0.01)
        assert [r.env_id for r in rest] == [4, 5]

    def test_weights_fanout_reaches_every_actor(self, monkeypatch):
        _install_fake_pika(monkeypatch)
        from dotaclient_tpu.transport.queues import AmqpTransport

        actor_a = AmqpTransport("broker")
        actor_b = AmqpTransport("broker")
        learner = AmqpTransport("broker")

        learner.publish_weights(_weights(7))
        got_a = actor_a.latest_weights()
        got_b = actor_b.latest_weights()
        assert got_a is not None and got_a.version == 7
        assert got_b is not None and got_b.version == 7

    def test_latest_weights_drains_to_newest(self, monkeypatch):
        _install_fake_pika(monkeypatch)
        from dotaclient_tpu.transport.queues import AmqpTransport

        actor = AmqpTransport("broker")
        learner = AmqpTransport("broker")
        for v in (1, 2, 3):
            learner.publish_weights(_weights(v))
        got = actor.latest_weights()
        assert got is not None and got.version == 3
        # drained: nothing left until the next publish
        assert actor.latest_weights() is None
        learner.publish_weights(_weights(4))
        got = actor.latest_weights()
        assert got is not None and got.version == 4

    def test_late_binder_misses_prior_weights(self, monkeypatch):
        """Fanout is not a store — matches the reference's RMQ topology,
        where late-joining actors wait for the next weight publish."""
        _install_fake_pika(monkeypatch)
        from dotaclient_tpu.transport.queues import AmqpTransport

        learner = AmqpTransport("broker")
        learner.publish_weights(_weights(1))
        late_actor = AmqpTransport("broker")
        assert late_actor.latest_weights() is None
        learner.publish_weights(_weights(2))
        got = late_actor.latest_weights()
        assert got is not None and got.version == 2

    def test_wire_roundtrip_preserves_tensor_payload(self, monkeypatch):
        """Rollouts cross the fake broker as real serialized protobuf —
        the same bytes the C++ fast-path decoder parses."""
        _install_fake_pika(monkeypatch)
        import numpy as np

        from dotaclient_tpu.transport.queues import AmqpTransport
        from dotaclient_tpu.transport.serialize import (
            decode_rollout,
            encode_rollout,
        )

        arrays = {
            "units": np.arange(24, dtype=np.float32).reshape(2, 3, 4),
            "rewards": np.array([0.5, -1.0], np.float32),
        }
        msg = encode_rollout(
            arrays, model_version=3, env_id=9, rollout_id=1, length=2,
            total_reward=-0.5,
        )

        actor = AmqpTransport("broker")
        learner = AmqpTransport("broker")
        actor.publish_rollout(msg)
        (got,) = learner.consume_rollouts(max_count=1, timeout=0.01)
        meta, decoded = decode_rollout(got)
        assert meta["model_version"] == 3 and meta["env_id"] == 9
        np.testing.assert_array_equal(decoded["units"], arrays["units"])
        np.testing.assert_array_equal(decoded["rewards"], arrays["rewards"])
