"""Quantized experience plane tests (ISSUE 7).

Covers the rollout wire-cast discipline end-to-end: the name/dtype-driven
cast plan and its pinned-f32 allowlist, marker round-trip parity across the
python-proto codec, the native bytes codec, and the shm lane, the
loud _MAX_TENSORS ceiling, the trajectory buffer's narrow store + on-device
consume-time upcast, narrow-native finiteness admission (zero f32 copies),
CRC/quarantine behavior on narrow frames, the wire telemetry tier, and a
short narrow-vs-f32 learner parity run (slow)."""

import dataclasses
import importlib.util
import json
import os
import tracemalloc

import jax
import numpy as np
import pytest

from dotaclient_tpu.config import RunConfig
from dotaclient_tpu.transport import serialize as S
from dotaclient_tpu.utils import telemetry

try:
    import ml_dtypes

    BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    BF16 = None

pytestmark = pytest.mark.skipif(BF16 is None, reason="ml_dtypes unavailable")


def tiny_config(wire: str = "bfloat16") -> RunConfig:
    # batch_rollouts/capacity stay multiples of 8: the test env forces 8
    # host devices and batches shard over the data axis
    cfg = RunConfig()
    return dataclasses.replace(
        cfg,
        env=dataclasses.replace(cfg.env, n_envs=2, max_dota_time=30.0),
        ppo=dataclasses.replace(cfg.ppo, rollout_len=4, batch_rollouts=8),
        buffer=dataclasses.replace(
            cfg.buffer, capacity_rollouts=16, min_fill=8
        ),
        transport=dataclasses.replace(
            cfg.transport, rollout_wire_dtype=wire
        ),
        log_every=1000,
        checkpoint_every=1000,
    )


def decoded_copies(cfg, row, n):
    """n independently-decoded (meta, arrays) pairs of the same row."""
    payload = bytes(
        S.encode_rollout_bytes(row, **META, **wire_kwargs(cfg))
    )
    out = []
    for i in range(n):
        meta, arrays = S.decode_rollout_bytes(payload)
        meta["rollout_id"] = i
        out.append((meta, arrays))
    return out


def real_row(cfg: RunConfig, seed: int = 0, representable: bool = True):
    """One rollout row with non-trivial values; with ``representable`` the
    narrowable f32 leaves are pre-rounded to bf16 so the narrow wire is
    exact and parity assertions can demand bit equality."""
    from dotaclient_tpu.train.ppo import example_batch

    rng = np.random.default_rng(seed)
    row = jax.tree.map(
        lambda x: np.array(x[0]), example_batch(cfg, batch=1)
    )
    flat = S.flatten_tree(row)
    for name, arr in flat.items():
        if arr.dtype == np.float32:
            vals = rng.normal(size=arr.shape).astype(np.float32)
            if representable and not S.rollout_leaf_pinned(name):
                vals = vals.astype(BF16).astype(np.float32)
            flat[name] = vals
        elif arr.dtype == np.int32:
            flat[name] = rng.integers(0, 3, size=arr.shape).astype(np.int32)
    return S.unflatten_tree(flat)


def wire_kwargs(cfg: RunConfig):
    return dict(
        wire_dtype=cfg.transport.rollout_wire_dtype,
        int_bounds=S.rollout_int_bounds(cfg),
    )


META = dict(model_version=0, env_id=0, rollout_id=0, length=4,
            total_reward=1.0)


def assert_trees_equal(a, b, exact_dtypes=True):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        x, y = np.asarray(x), np.asarray(y)
        if exact_dtypes:
            assert x.dtype == y.dtype
        np.testing.assert_array_equal(x, y)


class TestCastPlan:
    def test_pinned_leaves_never_narrow(self):
        cfg = tiny_config()
        specs = {
            "behavior_logp": np.float32, "rewards": np.float32,
            "dones": np.float32, "values": np.float32,
            "carry0/0": np.float32, "carry0/1": np.float32,
            "obs/units": np.float32,
        }
        plan = S.rollout_cast_plan(
            specs, "bfloat16", S.rollout_int_bounds(cfg)
        )
        assert set(plan) == {"obs/units"}
        assert plan["obs/units"] == BF16

    def test_int_bounds_drive_exact_narrowing(self):
        cfg = tiny_config()
        bounds = S.rollout_int_bounds(cfg)
        specs = {
            "actions/move_x": np.int32,       # bound 8 → int8
            "obs/hero_id": np.int32,          # bound 31 → int8
            "obs/unit_handles": np.int32,     # bound 32767 → int16
            "obs/unbounded": np.int32,        # no bound → untouched
        }
        plan = S.rollout_cast_plan(specs, "bfloat16", bounds)
        assert plan["actions/move_x"] == np.int8
        assert plan["obs/hero_id"] == np.int8
        assert plan["obs/unit_handles"] == np.int16
        assert "obs/unbounded" not in plan

    def test_f32_wire_is_empty_plan(self):
        assert S.rollout_cast_plan({"obs/units": np.float32}, "float32") == {}

    def test_unknown_wire_dtype_raises(self):
        with pytest.raises(ValueError, match="rollout_wire_dtype"):
            S.rollout_cast_plan({}, "float16")

    def test_out_of_bound_int_fails_loudly(self):
        """The int bound is a config promise — a value that breaks it must
        raise at encode, never wrap into a corrupt stream."""
        arrays = {"actions": {"move_x": np.array([300], np.int32)},
                  "rewards": np.zeros((1,), np.float32)}
        with pytest.raises(ValueError, match="move_x"):
            S.encode_rollout_bytes(
                arrays, 0, 0, 0, 1, 0.0, wire_dtype="bfloat16",
                int_bounds={"actions/move_x": 8},
            )


class TestMarkerRoundTrip:
    def test_native_bytes_parity_with_f32_path(self):
        """encode→wire→decode→upcast over the native codec exactly equals
        the f32 path for bf16-representable inputs."""
        cfg = tiny_config()
        row = real_row(cfg)
        b32 = bytes(S.encode_rollout_bytes(row, **META))
        bnar = bytes(S.encode_rollout_bytes(row, **META, **wire_kwargs(cfg)))
        assert len(bnar) < len(b32)
        m32, a32 = S.decode_rollout_bytes(b32)
        mn, an = S.decode_rollout_bytes(bnar, upcast=True)
        assert "wire_cast" not in m32
        assert mn["wire_cast"]
        assert_trees_equal(an, a32)

    def test_proto_codec_parity(self):
        cfg = tiny_config()
        row = real_row(cfg)
        r = S.encode_rollout(row, **META, **wire_kwargs(cfg))
        mn, an = S.decode_rollout(r, upcast=True)
        _, a32 = S.decode_rollout(S.encode_rollout(row, **META))
        assert mn["wire_cast"]
        assert_trees_equal(an, a32)

    def test_cross_codec_parity(self):
        """A proto-encoded narrow payload decodes identically through the
        native parser (marker intercepted by name on both)."""
        cfg = tiny_config()
        row = real_row(cfg)
        payload = S.encode_rollout(
            row, **META, **wire_kwargs(cfg)
        ).SerializeToString()
        m_native, a_native = S.decode_rollout_bytes(payload, upcast=True)
        m_proto, a_proto = S.decode_rollout_bytes(
            payload, native=False, upcast=True
        )
        assert m_native["wire_cast"] == m_proto["wire_cast"]
        assert_trees_equal(a_native, a_proto)

    def test_pinned_leaves_byte_identical(self):
        """Pinned f32 leaves cross a narrow wire byte-for-byte — even for
        values a bf16 cast would round."""
        cfg = tiny_config()
        row = real_row(cfg, representable=False)
        payload = bytes(
            S.encode_rollout_bytes(row, **META, **wire_kwargs(cfg))
        )
        _, decoded = S.decode_rollout_bytes(payload)
        for name in ("behavior_logp", "rewards", "dones"):
            got, want = decoded[name], row[name]
            assert got.dtype == np.float32
            assert got.tobytes() == want.tobytes()
        for got, want in zip(
            jax.tree.leaves(decoded["carry0"]), jax.tree.leaves(row["carry0"])
        ):
            assert np.asarray(got).dtype == np.float32
            assert np.asarray(got).tobytes() == np.asarray(want).tobytes()

    def test_meta_accounting(self):
        cfg = tiny_config()
        row = real_row(cfg)
        payload = bytes(
            S.encode_rollout_bytes(row, **META, **wire_kwargs(cfg))
        )
        meta, _ = S.decode_rollout_bytes(payload)
        assert meta["wire_bytes"] == len(payload)
        assert meta["raw_bytes"] > meta["wire_bytes"]
        # raw_bytes is EXACT: what this frame actually costs full-width
        # (no marker entry; per-leaf framing re-costed at the original
        # dtype) — an f32 encode of the same row, byte for byte, from
        # both codec paths
        f32_payload = bytes(S.encode_rollout_bytes(row, **META))
        assert meta["raw_bytes"] == len(f32_payload)
        meta_pb, _ = S.decode_rollout_bytes(payload, native=False)
        assert meta_pb["raw_bytes"] == len(f32_payload)
        # every narrowed leaf names its true original dtype
        assert meta["wire_cast"]["obs/units"] == "float32"
        assert meta["wire_cast"]["obs/unit_handles"] == "int32"
        assert all(
            not S.rollout_leaf_pinned(n) for n in meta["wire_cast"]
        )

    def test_f32_wire_unchanged(self):
        """Knob off: no marker, identical bytes to the pre-ISSUE-7 codec."""
        cfg = tiny_config("float32")
        row = real_row(cfg)
        assert wire_kwargs(cfg)["wire_dtype"] == "float32"
        b_plain = bytes(S.encode_rollout_bytes(row, **META))
        b_kw = bytes(S.encode_rollout_bytes(row, **META, **wire_kwargs(cfg)))
        assert b_plain == b_kw


class TestDrainedPayloadAccounting:
    def test_zero_length_payload_cannot_zero_divide_the_gauge(self):
        """A zero-byte payload parses as an empty proto (wire_bytes =
        raw_bytes = 0); on a server whose totals are still zero the
        compression gauge must stay at its floor, not ZeroDivisionError
        out of the learner's ingest drain."""
        reg = telemetry.get_registry()
        totals = [0, 0]
        out, bad = S.decode_drained_payloads([b""], reg, totals)
        assert bad == 0 and len(out) == 1
        assert totals == [0, 0]
        # and real payloads afterwards resume normal accounting
        cfg = tiny_config()
        payload = bytes(
            S.encode_rollout_bytes(
                real_row(cfg), **META, **wire_kwargs(cfg)
            )
        )
        out, bad = S.decode_drained_payloads([payload], reg, totals)
        assert bad == 0 and totals[0] > 0 and totals[1] > totals[0]


class TestTooManyTensors:
    def _big_tree(self, n):
        return {"obs": {f"x{i}": np.zeros((2,), np.float32)
                        for i in range(n)}}

    def test_encode_raises_with_count(self):
        with pytest.raises(ValueError, match="70"):
            S.encode_rollout_bytes(self._big_tree(70), 0, 0, 0, 1, 0.0)
        with pytest.raises(ValueError, match="70"):
            S.encode_rollout(self._big_tree(70), 0, 0, 0, 1, 0.0)

    def test_decode_raises_with_count(self):
        from dotaclient_tpu.protos import dota_pb2 as pb

        r = pb.Rollout(model_version=0)
        for i in range(70):
            r.arrays[f"x{i}"].CopyFrom(
                S.tensor_to_proto(np.zeros((2,), np.float32))
            )
        payload = r.SerializeToString()
        with pytest.raises(ValueError, match="70"):
            S.decode_rollout_bytes(payload)
        with pytest.raises(ValueError, match="70"):
            S.decode_rollout_bytes(payload, native=False)

    def test_marker_counts_toward_ceiling(self):
        tree = self._big_tree(S._MAX_TENSORS)
        # f32: exactly at the ceiling — fine
        S.encode_rollout_bytes(tree, 0, 0, 0, 1, 0.0)
        # narrow: the marker entry tips it over — loud
        with pytest.raises(ValueError, match=str(S._MAX_TENSORS + 1)):
            S.encode_rollout_bytes(
                tree, 0, 0, 0, 1, 0.0, wire_dtype="bfloat16"
            )


def make_buffer(cfg):
    from dotaclient_tpu.buffer.trajectory_buffer import TrajectoryBuffer
    from dotaclient_tpu.parallel import make_mesh

    return TrajectoryBuffer(cfg, make_mesh(cfg.mesh))


class TestNarrowBuffer:
    def test_store_is_narrow_and_take_is_f32(self):
        cfg = tiny_config()
        buf = make_buffer(cfg)
        stored = S.flatten_tree(jax.tree.map(np.asarray, buf._store))
        assert stored["obs/units"].dtype == BF16
        assert stored["actions/move_x"].dtype == np.int8
        assert stored["obs/unit_handles"].dtype == np.int16
        assert stored["behavior_logp"].dtype == np.float32   # pinned
        row = real_row(cfg)
        assert buf.add(decoded_copies(cfg, row, 8), 0) == 8
        batch = buf.take(batch_size=8, current_version=0)
        flat = S.flatten_tree(jax.tree.map(np.asarray, batch))
        assert flat["obs/units"].dtype == np.float32
        assert flat["actions/move_x"].dtype == np.int32
        assert flat["obs/unit_handles"].dtype == np.int32

    def test_upcast_bit_identical_to_f32_path(self):
        """The consume-time upcast makes the narrow ring's batch EQUAL the
        f32 ring's batch for bf16-representable experience — the train
        step cannot tell the wire dtype was ever narrow."""
        row = real_row(tiny_config())
        batches = {}
        for wire in ("float32", "bfloat16"):
            cfg = tiny_config(wire)
            cfg = dataclasses.replace(
                cfg,
                buffer=dataclasses.replace(
                    cfg.buffer, capacity_rollouts=8, min_fill=8
                ),
            )
            buf = make_buffer(cfg)
            assert buf.add(decoded_copies(cfg, row, 8), 0) == 8
            batches[wire] = jax.tree.map(
                np.asarray, buf.take(batch_size=8, current_version=0)
            )
        assert_trees_equal(batches["bfloat16"], batches["float32"])

    def test_full_width_payload_admitted_to_narrow_ring(self):
        """An in-proc actor (or an f32-knob fleet member) ships full-width
        rows; the narrow ring quantizes at the staging copy instead of
        skew-dropping them."""
        cfg = tiny_config()
        buf = make_buffer(cfg)
        assert buf.add([(dict(META), real_row(cfg))], 0) == 1
        assert buf.dropped_skew == 0

    def test_full_width_out_of_bounds_rejected_at_narrow_ring(self):
        """A full-width int row whose values exceed the narrow ring's
        declared bounds must be REJECTED at the door, not silently
        wrapped by the staging/scatter cast (the mirror of the encode
        path's exactness guard — mixed fleets fail loudly too)."""
        cfg = tiny_config()
        buf = make_buffer(cfg)
        row = real_row(cfg)
        flat = S.flatten_tree(row)
        # int8-narrowed action leaf: 300 wraps to 44 under a silent cast
        bad = dict(flat)
        name = next(
            n for n, d in buf._wire_plan.items() if np.dtype(d) == np.int8
        )
        arr = np.array(bad[name])
        arr.flat[0] = 300
        bad[name] = arr
        assert buf.add([(dict(META), S.unflatten_tree(bad))], 0) == 0
        assert buf.dropped_bounds == 1
        assert buf.dropped_skew == 0
        # the same row at legal values is admitted
        assert buf.add([(dict(META), row)], 0) == 1

    def test_narrow_payload_admitted_to_f32_ring(self):
        cfg_f32 = tiny_config("float32")
        cfg_n = tiny_config()
        buf = make_buffer(cfg_f32)
        payload = bytes(
            S.encode_rollout_bytes(
                real_row(cfg_n), **META, **wire_kwargs(cfg_n)
            )
        )
        meta, arrays = S.decode_rollout_bytes(payload)
        assert buf.add([(meta, arrays)], 0) == 1
        assert buf.dropped_skew == 0

    def test_genuine_skew_still_drops(self):
        cfg = tiny_config()
        buf = make_buffer(cfg)
        row = real_row(cfg)
        bad = dict(row)
        bad["rewards"] = row["rewards"].astype(np.float64)   # wrong width
        assert buf.add([(dict(META), bad)], 0) == 0
        assert buf.dropped_skew == 1
        short = dict(row)
        short["rewards"] = row["rewards"][:-1]               # wrong shape
        assert buf.add([(dict(META), short)], 0) == 0
        assert buf.dropped_skew == 2

    def test_snapshot_restores_across_wire_dtypes(self):
        cfg = tiny_config()
        buf = make_buffer(cfg)
        assert buf.add([(dict(META), real_row(cfg))], 0) == 1
        state = buf.state_dict()
        buf_f32 = make_buffer(tiny_config("float32"))
        buf_f32.load_state_dict(state)
        stored = S.flatten_tree(jax.tree.map(np.asarray, buf_f32._store))
        assert stored["obs/units"].dtype == np.float32
        assert buf_f32.size == 1

    def test_restore_frees_out_of_range_slots_instead_of_wrapping(self):
        """The reverse restore (f32 snapshot → narrow ring) runs the same
        bound guard as the ingest door: an int slot whose values exceed
        the narrow bounds is freed and counted, never wrapped by the
        storage-width cast."""
        narrow = make_buffer(tiny_config())
        buf = make_buffer(cfg_f32 := tiny_config("float32"))
        assert buf.add([(dict(META), real_row(cfg_f32))], 0) == 1
        bad = S.flatten_tree(real_row(cfg_f32, seed=1))
        name = next(
            n for n, d in narrow._wire_plan.items()
            if np.dtype(d) == np.int8
        )
        arr = np.array(bad[name])
        arr.flat[0] = 300   # wraps to 44 under a silent int8 cast
        bad[name] = arr
        # the f32 ring has no guards: the oversized row is admitted there
        assert buf.add(
            [(dict(dict(META), rollout_id=1), S.unflatten_tree(bad))], 0
        ) == 1
        narrow.load_state_dict(buf.state_dict())
        assert narrow.size == 1           # only the in-bounds slot survives
        assert narrow.dropped_bounds == 1
        # the surviving slot's int leaf round-trips exactly
        stored = S.flatten_tree(jax.tree.map(np.asarray, narrow._store))
        good_flat = S.flatten_tree(real_row(cfg_f32))
        slot = list(narrow._order)[0]
        np.testing.assert_array_equal(
            stored[name][slot], good_flat[name].astype(np.int8)
        )


@pytest.mark.slow    # DeviceActor's scan compile alone is ~30s on this host
class TestDeviceActorNarrowChunks:
    def test_collect_emits_narrow_chunks_fused_path_untouched(self):
        from dotaclient_tpu.actor.device_rollout import DeviceActor
        from dotaclient_tpu.models import init_params, make_policy

        cfg = tiny_config()
        policy = make_policy(cfg.model, cfg.obs, cfg.actions)
        params = init_params(policy, jax.random.PRNGKey(0))
        actor = DeviceActor(cfg, policy, seed=0)
        chunk, _ = actor.collect(params)
        flat = S.flatten_tree(jax.tree.map(np.asarray, chunk))
        assert flat["obs/units"].dtype == BF16
        assert flat["actions/move_x"].dtype == np.int8
        assert flat["behavior_logp"].dtype == np.float32   # pinned
        # fused mode consumes _rollout_impl directly: full width there
        _, raw_chunk, _ = actor._rollout_impl(
            params, actor.state, params
        )
        raw = S.flatten_tree(raw_chunk)
        assert raw["obs/units"].dtype == np.dtype("float32")


class TestNarrowFiniteness:
    def test_bf16_nan_rejected_at_the_door(self):
        cfg = tiny_config()
        buf = make_buffer(cfg)
        payload = bytes(
            S.encode_rollout_bytes(
                real_row(cfg), **META, **wire_kwargs(cfg)
            )
        )
        meta, arrays = S.decode_rollout_bytes(payload)
        units = np.array(arrays["obs"]["units"])   # views are read-only
        units[0, 0, 0] = np.nan                    # a bf16 NaN is still NaN
        arrays = dict(arrays)
        arrays["obs"] = dict(arrays["obs"])
        arrays["obs"]["units"] = units
        assert buf.add([(meta, arrays)], 0) == 0
        assert buf.dropped_nonfinite == 1

    def test_finiteness_scan_never_upcasts(self):
        """The admission scan runs natively on bf16 rows: peak transient
        allocation stays at the bool-result scale (~0.5× the leaf bytes) —
        an f32 upcast copy would cost 2× the leaf bytes and fail this."""
        cfg = tiny_config()
        buf = make_buffer(cfg)
        n = 1 << 20
        leaf = np.zeros((n,), BF16)
        arrays = {"obs": {"units": leaf}}
        tracemalloc.start()
        try:
            base = tracemalloc.get_traced_memory()[0]
            assert buf._payload_finite(arrays)
            peak = tracemalloc.get_traced_memory()[1]
        finally:
            tracemalloc.stop()
        assert peak - base < leaf.nbytes   # bool result ≈ 0.5×, f32 copy = 2×


class TestShmLaneNarrow:
    def _lane(self, tag, **kw):
        from dotaclient_tpu.transport import ShmTransport, ShmTransportServer

        name = f"t-quant-{os.getpid()}-{tag}"
        server = ShmTransportServer(
            name=name, slots=1, ring_bytes=1 << 20, weights_bytes=1 << 20,
            **kw,
        )
        return server, ShmTransport(name, slots=1)

    def test_narrow_frames_roundtrip_and_count(self):
        cfg = tiny_config()
        row = real_row(cfg)
        reg = telemetry.get_registry()
        server, actor = self._lane("narrow")
        try:
            wire0 = reg.counter("transport/rollout_bytes_total").value
            raw0 = reg.counter("transport/rollout_raw_bytes_total").value
            for i in range(3):
                meta = dict(META, rollout_id=i)
                assert actor.publish_rollout_bytes(
                    S.encode_rollout_bytes(row, **meta, **wire_kwargs(cfg))
                )
            got = server.consume_decoded(16, timeout=1.0)
            assert [m["rollout_id"] for m, _ in got] == [0, 1, 2]
            flat = S.flatten_tree(got[0][1])
            assert flat["obs/units"].dtype == BF16
            wire = reg.counter("transport/rollout_bytes_total").value - wire0
            raw = reg.counter("transport/rollout_raw_bytes_total").value - raw0
            assert raw > wire > 0
            assert (
                reg.gauge("transport/rollout_compression_ratio").value > 1.3
            )
        finally:
            actor.close()
            server.close()

    def test_crc_quarantine_unchanged_on_narrow_frames(self):
        """The integrity layer is payload-agnostic: a bit-flipped narrow
        frame drops + counts exactly like an f32 one, and a poison streak
        still quarantines the slot."""
        from dotaclient_tpu.utils import faults

        cfg = tiny_config()
        row = real_row(cfg)
        reg = telemetry.get_registry()
        before = reg.counter("transport/frames_corrupt_total").value
        faults.configure("transport.corrupt_frame@2")
        server, actor = self._lane("crc")
        try:
            for i in range(4):
                assert actor.publish_rollout_bytes(
                    S.encode_rollout_bytes(
                        row, **dict(META, rollout_id=i), **wire_kwargs(cfg)
                    )
                )
            got = server.consume_decoded(16, timeout=1.0)
            assert [m["rollout_id"] for m, _ in got] == [0, 2, 3]
            assert (
                reg.counter("transport/frames_corrupt_total").value
                == before + 1
            )
        finally:
            faults.configure(None)
            actor.close()
            server.close()


class TestWireTelemetryTier:
    @pytest.fixture()
    def checker(self):
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "check_telemetry_schema_q",
            os.path.join(root, "scripts", "check_telemetry_schema.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_wire_keys_required_only_on_request(self, checker):
        base = {k: 1.0 for k in checker.REQUIRED_KEYS}
        for k in list(base):
            if k.startswith("span/"):
                root = k.rsplit("/", 1)[0]
                for leaf in checker.TIMER_LEAVES:
                    base[f"{root}/{leaf}"] = 1.0
        line = json.dumps({"ts": 1.0, "step": 0, "scalars": base})
        assert checker.validate_lines([line]) == []
        errors = checker.validate_lines(
            [line], extra_required=checker.WIRE_KEYS
        )
        assert any("rollout_compression_ratio" in e for e in errors)
        full = dict(base, **{k: 0.0 for k in checker.WIRE_KEYS})
        line2 = json.dumps({"ts": 1.0, "step": 0, "scalars": full})
        assert checker.validate_lines(
            [line2], extra_required=checker.WIRE_KEYS
        ) == []

    def test_external_transport_run_waives_in_proc_actor_keys(self, checker):
        # a socket/shm learner's JSONL has no in-proc actor spans — the
        # server marker key waives exactly those, nothing else
        scalars = {
            k: 1.0
            for k in checker.REQUIRED_KEYS
            if k not in checker.IN_PROC_ACTOR_KEYS
        }
        for k in list(scalars):
            if k.startswith("span/"):
                root = k.rsplit("/", 1)[0]
                for leaf in checker.TIMER_LEAVES:
                    scalars[f"{root}/{leaf}"] = 1.0
        scalars.update({k: 0.0 for k in checker.WIRE_KEYS})
        no_marker = json.dumps({"ts": 1.0, "step": 0, "scalars": scalars})
        errors = checker.validate_lines(
            [no_marker], extra_required=checker.WIRE_KEYS
        )
        assert any("frames_shipped" in e for e in errors)
        scalars["transport/actors_connected"] = 1.0
        with_marker = json.dumps({"ts": 1.0, "step": 0, "scalars": scalars})
        assert checker.validate_lines(
            [with_marker], extra_required=checker.WIRE_KEYS
        ) == []

    def test_both_servers_eager_create_wire_keys(self, checker):
        from dotaclient_tpu.transport import ShmTransportServer, TransportServer

        reg = telemetry.get_registry()
        srv = TransportServer(port=0)
        try:
            snap = reg.snapshot()
            for k in checker.WIRE_KEYS:
                assert k in snap, k
            assert snap["transport/rollout_compression_ratio"] >= 1.0
        finally:
            srv.close()
        shm = ShmTransportServer(
            name=f"t-quant-{os.getpid()}-tier", slots=1,
            ring_bytes=1 << 16, weights_bytes=1 << 20,
        )
        try:
            snap = reg.snapshot()
            for k in checker.WIRE_KEYS:
                assert k in snap, k
        finally:
            shm.close()


@pytest.mark.slow
class TestLearnerParity:
    def test_short_run_losses_agree_within_bf16_tolerance(self):
        """End-to-end: two vec-actor learners, identical seeds, narrow vs
        f32 experience plane. The first consumed batches differ only by
        the ring's bf16 quantization of observations, so losses must agree
        to bf16 tolerance (the trajectories decouple slowly as the
        quantized obs feed back through updates — keep the run short)."""
        from dotaclient_tpu.config import LearnerConfig
        from dotaclient_tpu.train.learner import Learner

        losses = {}
        for wire in ("float32", "bfloat16"):
            cfg = dataclasses.replace(
                tiny_config(wire),
                ppo=dataclasses.replace(
                    tiny_config().ppo, rollout_len=8, batch_rollouts=8
                ),
                buffer=dataclasses.replace(
                    tiny_config().buffer, capacity_rollouts=32, min_fill=8
                ),
                # sync snapshots + per-step logging: the returned metrics
                # deterministically carry the LAST step's loss
                learner=LearnerConfig(async_snapshots=False),
                log_every=1,
            )
            learner = Learner(cfg, actor="vec", seed=3)
            stats = learner.train(2)
            losses[wire] = stats["loss"]
        assert np.isfinite(losses["float32"])
        assert np.isfinite(losses["bfloat16"])
        assert losses["bfloat16"] == pytest.approx(
            losses["float32"], rel=0.05, abs=5e-3
        )
