"""Policy model tests (SURVEY.md §4: mask correctness, LSTM state-carry
equivalence scan-vs-steps, distribution consistency)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dotaclient_tpu.config import RunConfig
from dotaclient_tpu.envs.lane_sim import LaneSim, TEAM_DIRE, TEAM_RADIANT
from dotaclient_tpu.features import featurize, stack_observations
from dotaclient_tpu.models import (
    distributions as D,
    dummy_obs_batch,
    init_params,
    make_policy,
)
from dotaclient_tpu.protos import dota_pb2 as pb

CFG = RunConfig()
# float32 end-to-end in tests so scan-vs-step comparisons are tight.
MODEL = CFG.model.__class__(dtype="float32")


@pytest.fixture(scope="module")
def policy_and_params():
    policy = make_policy(MODEL, CFG.obs, CFG.actions)
    params = init_params(policy, jax.random.PRNGKey(0))
    # jit once per shape signature; shared across tests (module scope).
    policy.jstep = jax.jit(lambda p, o, c: policy.apply(p, o, c, method="step"))
    policy.jseq = jax.jit(lambda p, o, c: policy.apply(p, o, c, method="sequence"))
    return policy, params


def sim_obs_batch(batch: int, steps: int = 0):
    """Batch of real (featurized) observations from perturbed sims."""
    obs = []
    for i in range(batch):
        cfg = pb.GameConfig(
            seed=i,
            hero_picks=[
                pb.HeroPick(team_id=TEAM_RADIANT, hero_id=1 + i % 3,
                            control_mode=pb.CONTROL_AGENT),
                pb.HeroPick(team_id=TEAM_DIRE, hero_id=1,
                            control_mode=pb.CONTROL_SCRIPTED_EASY),
            ],
        )
        sim = LaneSim(cfg)
        for _ in range(steps + i):
            sim.step({})
        obs.append(featurize(sim.world_state(TEAM_RADIANT), 0, CFG.obs, CFG.actions))
    return {k: jnp.asarray(v) for k, v in stack_observations(obs).items()}


class TestForward:
    def test_step_shapes_and_finiteness(self, policy_and_params):
        policy, params = policy_and_params
        obs = sim_obs_batch(4)
        logits, value, carry = policy.jstep(params, obs, policy.initial_state(4))
        for head, size in CFG.actions.head_sizes.items():
            assert logits[head].shape == (4, size)
            assert np.isfinite(np.asarray(logits[head])).all()
        assert value.shape == (4,)
        assert np.isfinite(np.asarray(value)).all()

    def test_scan_equals_repeated_steps(self, policy_and_params):
        """Sequence mode must reproduce T single steps exactly (the
        truncated-BPTT contract the learner relies on, SURVEY.md §5.7)."""
        policy, params = policy_and_params
        B, T = 4, 5
        rng = np.random.default_rng(0)
        seq = dummy_obs_batch(B, CFG.obs, CFG.actions, time=T)
        seq = dict(seq)
        seq["units"] = jnp.asarray(
            rng.normal(size=seq["units"].shape).astype(np.float32)
        )
        seq["unit_mask"] = jnp.asarray(np.ones(seq["unit_mask"].shape, bool))

        carry = policy.initial_state(B)
        logits_seq, value_seq, final_seq = policy.jseq(params, seq, carry)

        carry_s = policy.initial_state(B)
        step_values = []
        step_type_logits = []
        for t in range(T):
            obs_t = {k: v[:, t] for k, v in seq.items()}
            logits_t, value_t, carry_s = policy.jstep(params, obs_t, carry_s)
            step_values.append(value_t)
            step_type_logits.append(logits_t["action_type"])

        np.testing.assert_allclose(
            np.asarray(value_seq), np.stack([np.asarray(v) for v in step_values], 1),
            rtol=2e-5, atol=2e-5,
        )
        np.testing.assert_allclose(
            np.asarray(logits_seq["action_type"]),
            np.stack([np.asarray(l) for l in step_type_logits], 1),
            rtol=2e-5, atol=2e-5,
        )
        np.testing.assert_allclose(
            np.asarray(final_seq[0]), np.asarray(carry_s[0]), rtol=2e-5, atol=2e-5
        )

    def test_padding_slots_do_not_affect_output(self, policy_and_params):
        """Garbage in masked-out unit slots must be invisible to the model."""
        policy, params = policy_and_params
        obs = sim_obs_batch(4)
        logits_a, value_a, _ = policy.jstep(params, obs, policy.initial_state(4))
        units = np.asarray(obs["units"]).copy()
        mask = np.asarray(obs["unit_mask"])
        units[~mask] = 1e6  # poison the padding
        obs_b = dict(obs)
        obs_b["units"] = jnp.asarray(units)
        logits_b, value_b, _ = policy.jstep(params, obs_b, policy.initial_state(4))
        np.testing.assert_allclose(np.asarray(value_a), np.asarray(value_b), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(logits_a["action_type"]), np.asarray(logits_b["action_type"]), rtol=1e-5
        )


class TestDistributions:
    def test_illegal_actions_never_sampled(self, policy_and_params):
        policy, params = policy_and_params
        obs = sim_obs_batch(4)
        logits, _, _ = policy.jstep(params, obs, policy.initial_state(4))
        mask_type = np.asarray(obs["mask_action_type"])
        mask_target = np.asarray(obs["mask_target_unit"])
        mask_cast = np.asarray(obs["mask_cast_target"])
        sample_jit = jax.jit(lambda rng: D.sample(rng, logits, obs)[0])
        for i in range(200):
            actions = sample_jit(jax.random.PRNGKey(i))
            a_type = np.asarray(actions["action_type"])
            target = np.asarray(actions["target_unit"])
            for b in range(4):
                assert mask_type[b, a_type[b]], "illegal action type sampled"
                if a_type[b] == D.A_ATTACK:
                    assert mask_target[b, target[b]]
                elif a_type[b] == D.A_CAST:
                    assert mask_cast[b, target[b]]

    def test_logprob_matches_sample(self, policy_and_params):
        policy, params = policy_and_params
        obs = sim_obs_batch(4)
        logits, _, _ = policy.jstep(params, obs, policy.initial_state(4))
        actions, logp = D.sample(jax.random.PRNGKey(7), logits, obs)
        lp = D.log_prob(logits, obs, actions)
        np.testing.assert_allclose(np.asarray(logp), np.asarray(lp), rtol=1e-5)
        assert (np.asarray(logp) <= 0).all()

    def test_irrelevant_heads_do_not_change_logprob(self, policy_and_params):
        """NOOP's joint log-prob must ignore move/target/ability heads."""
        policy, params = policy_and_params
        obs = sim_obs_batch(4)
        logits, _, _ = policy.jstep(params, obs, policy.initial_state(4))
        actions = {
            "action_type": jnp.zeros((4,), jnp.int32),  # NOOP
            "move_x": jnp.zeros((4,), jnp.int32),
            "move_y": jnp.zeros((4,), jnp.int32),
            "target_unit": jnp.zeros((4,), jnp.int32),
            "ability": jnp.zeros((4,), jnp.int32),
        }
        lp_a = D.log_prob(logits, obs, actions)
        actions2 = dict(actions)
        actions2["move_x"] = jnp.full((4,), 5, jnp.int32)
        actions2["target_unit"] = jnp.full((4,), 3, jnp.int32)
        lp_b = D.log_prob(logits, obs, actions2)
        np.testing.assert_allclose(np.asarray(lp_a), np.asarray(lp_b), rtol=1e-6)

    def test_entropy_nonnegative_and_finite(self, policy_and_params):
        policy, params = policy_and_params
        obs = sim_obs_batch(4)
        logits, _, _ = policy.jstep(params, obs, policy.initial_state(4))
        ent = np.asarray(D.entropy(logits, obs))
        assert np.isfinite(ent).all()
        assert (ent >= 0).all()

    def test_fully_masked_head_stays_finite(self):
        """A head with zero legal entries must not poison logp/entropy."""
        logits = {h: jnp.zeros((2, n)) for h, n in CFG.actions.head_sizes.items()}
        obs = dummy_obs_batch(2, CFG.obs, CFG.actions)
        obs = dict(obs)
        obs["mask_target_unit"] = jnp.zeros_like(obs["mask_target_unit"])  # none legal
        obs["mask_cast_target"] = jnp.zeros_like(obs["mask_cast_target"])
        actions, logp = D.sample(jax.random.PRNGKey(0), logits, obs)
        assert np.isfinite(np.asarray(logp)).all()
        assert np.isfinite(np.asarray(D.entropy(logits, obs))).all()
