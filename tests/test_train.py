"""PPO train-step tests (SURVEY.md §4: GAE vs NumPy oracle, sharded train
step on 8 forced host devices, 1-device vs 8-device golden comparison)."""

import dataclasses

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dotaclient_tpu.config import MeshConfig, RunConfig
from dotaclient_tpu.models import distributions as D, init_params, make_policy
from dotaclient_tpu.parallel import make_mesh
from dotaclient_tpu.train import (
    example_batch,
    gae,
    gae_reference,
    init_train_state,
    make_epoch_step,
    make_train_step,
    ppo_loss,
)

CFG = RunConfig(model=RunConfig().model.__class__(dtype="float32"))


@pytest.fixture(scope="module")
def setup():
    policy = make_policy(CFG.model, CFG.obs, CFG.actions)
    params = init_params(policy, jax.random.PRNGKey(0))
    return policy, params


def random_batch(policy, params, batch=8, seed=0):
    """A batch whose behavior log-probs are self-consistent with the policy
    (sampled from it), over randomized observations."""
    rng = np.random.default_rng(seed)
    T = CFG.ppo.rollout_len
    b = example_batch(CFG, batch=batch)
    obs = dict(b["obs"])
    obs["units"] = jnp.asarray(rng.normal(size=obs["units"].shape).astype(np.float32))
    obs["globals"] = jnp.asarray(rng.normal(size=obs["globals"].shape).astype(np.float32))
    b["obs"] = obs
    # dones drawn BEFORE the behavior forward: ppo_loss re-runs the sequence
    # with the batch's dones (mid-chunk carry resets), so the behavior
    # log-probs must come from the same done-conditioned forward
    b["dones"] = jnp.asarray((rng.random((batch, T)) < 0.05).astype(np.float32))
    logits, values, _ = policy.apply(
        params, obs, b["carry0"], b["dones"], method="sequence"
    )
    logits_t = {k: v[:, :T] for k, v in logits.items()}
    obs_t = {k: v[:, :T] for k, v in obs.items()}
    actions, logp = D.sample(jax.random.PRNGKey(seed), logits_t, obs_t)
    b["actions"] = actions
    b["behavior_logp"] = logp
    b["rewards"] = jnp.asarray(rng.normal(size=(batch, T)).astype(np.float32))
    return b


class TestGAE:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_matches_numpy_reference(self, seed):
        rng = np.random.default_rng(seed)
        B, T = 5, 20
        r = rng.normal(size=(B, T)).astype(np.float32)
        v = rng.normal(size=(B, T + 1)).astype(np.float32)
        d = (rng.random((B, T)) < 0.15).astype(np.float32)
        a_jax, ret_jax = gae(jnp.asarray(r), jnp.asarray(v), jnp.asarray(d), 0.99, 0.95)
        a_np, ret_np = gae_reference(r, v, d, 0.99, 0.95)
        np.testing.assert_allclose(np.asarray(a_jax), a_np, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(ret_jax), ret_np, rtol=1e-4, atol=1e-5)

    def test_done_cuts_bootstrap(self):
        """After a done, later values must not leak into earlier advantages."""
        B, T = 1, 4
        r = np.zeros((B, T), np.float32)
        v = np.zeros((B, T + 1), np.float32)
        v[0, -1] = 100.0  # huge bootstrap value
        d = np.zeros((B, T), np.float32)
        d[0, T - 1] = 1.0  # ...but episode ends at the last step
        adv, _ = gae(jnp.asarray(r), jnp.asarray(v), jnp.asarray(d), 0.99, 0.95)
        np.testing.assert_allclose(np.asarray(adv), np.zeros((B, T)), atol=1e-6)


class TestVTrace:
    """V-trace off-policy correction (train/gae.py vtrace) — the IMPALA
    estimator behind PPOConfig.advantage='vtrace'."""

    @pytest.mark.parametrize("seed", [0, 1])
    @pytest.mark.parametrize("rho_clip,c_clip", [(1.0, 1.0), (2.0, 1.5)])
    def test_matches_numpy_reference(self, seed, rho_clip, c_clip):
        from dotaclient_tpu.train.gae import vtrace, vtrace_reference

        rng = np.random.default_rng(seed)
        B, T = 4, 16
        r = rng.normal(size=(B, T)).astype(np.float32)
        v = rng.normal(size=(B, T + 1)).astype(np.float32)
        d = (rng.random((B, T)) < 0.15).astype(np.float32)
        blp = -np.abs(rng.normal(size=(B, T))).astype(np.float32)
        tlp = blp + rng.normal(size=(B, T)).astype(np.float32) * 0.3
        a_jax, vs_jax = vtrace(
            *map(jnp.asarray, (r, v, d, blp, tlp)), 0.99, rho_clip, c_clip
        )
        a_np, vs_np = vtrace_reference(
            r, v, d, blp, tlp, 0.99, rho_clip, c_clip
        )
        np.testing.assert_allclose(np.asarray(a_jax), a_np, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(vs_jax), vs_np, rtol=1e-4, atol=1e-5)

    def test_on_policy_reduces_to_gae_lambda_one(self):
        from dotaclient_tpu.train.gae import vtrace

        rng = np.random.default_rng(3)
        B, T = 4, 12
        r = rng.normal(size=(B, T)).astype(np.float32)
        v = rng.normal(size=(B, T + 1)).astype(np.float32)
        d = (rng.random((B, T)) < 0.2).astype(np.float32)
        lp = -np.abs(rng.normal(size=(B, T))).astype(np.float32)
        pg, vs = vtrace(
            *map(jnp.asarray, (r, v, d, lp, lp)), 0.99, 1.0, 1.0
        )
        adv, ret = gae(
            jnp.asarray(r), jnp.asarray(v), jnp.asarray(d), 0.99, 1.0
        )
        np.testing.assert_allclose(np.asarray(pg), np.asarray(adv), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(vs), np.asarray(ret), rtol=1e-4, atol=1e-5)

    def test_loss_and_train_step_with_vtrace(self, setup):
        policy, params = setup
        cfg = dataclasses.replace(CFG.ppo, advantage="vtrace")
        batch = random_batch(policy, params, seed=9)
        loss, metrics = ppo_loss(policy, params, batch, cfg)
        assert np.isfinite(float(loss))
        run_cfg = dataclasses.replace(CFG, ppo=cfg)
        mesh = make_mesh(run_cfg.mesh)
        step = make_train_step(policy, run_cfg, mesh)
        state = init_train_state(params, cfg)
        state, m = step(state, batch)
        assert np.isfinite(float(np.asarray(m["loss"])))
        assert int(state.step) == 1

    def test_unknown_advantage_mode_raises(self, setup):
        policy, params = setup
        batch = random_batch(policy, params)
        with pytest.raises(ValueError, match="advantage"):
            ppo_loss(
                policy, params, batch,
                dataclasses.replace(CFG.ppo, advantage="bogus"),
            )


class TestLoss:
    def test_finite_and_components(self, setup):
        policy, params = setup
        batch = random_batch(policy, params)
        loss, metrics = ppo_loss(policy, params, batch, CFG.ppo)
        assert np.isfinite(float(loss))
        for k in ("policy_loss", "value_loss", "entropy", "approx_kl", "clip_frac"):
            assert np.isfinite(float(metrics[k])), k
        # behavior logp was sampled from these very params: ratio == 1.
        assert float(metrics["approx_kl"]) == pytest.approx(0.0, abs=1e-4)
        assert float(metrics["clip_frac"]) == pytest.approx(0.0, abs=1e-6)

    def test_invalid_steps_do_not_contribute(self, setup):
        """Poisoning rewards on valid==0 steps must not change the loss."""
        policy, params = setup
        batch = random_batch(policy, params)
        valid = np.ones_like(np.asarray(batch["valid"]))
        valid[:, -4:] = 0.0
        batch["valid"] = jnp.asarray(valid)
        loss_a, _ = ppo_loss(policy, params, batch, CFG.ppo)
        rewards = np.asarray(batch["rewards"]).copy()
        rewards[:, -4:] = 1e3
        batch2 = dict(batch)
        batch2["rewards"] = jnp.asarray(rewards)
        loss_b, _ = ppo_loss(policy, params, batch2, CFG.ppo)
        # GAE flows backwards: poisoned *invalid-step* rewards still enter
        # advantages of earlier valid steps unless dones cut them; loss terms
        # themselves only count valid steps. Use dones to isolate.
        dones = np.asarray(batch["dones"]).copy()
        dones[:, -5] = 1.0
        batch["dones"] = jnp.asarray(dones)
        batch2["dones"] = jnp.asarray(dones)
        loss_a, _ = ppo_loss(policy, params, batch, CFG.ppo)
        loss_b, _ = ppo_loss(policy, params, batch2, CFG.ppo)
        np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-5)

    def test_adv_norm_modes(self, setup):
        """Floored/disabled advantage normalization (the 5v5 fine-tune fix,
        BASELINE.md): floor=0 reproduces the standard whitening; a floor
        larger than the batch std leaves centered advantages unscaled, which
        must match adv_norm="none" exactly; tiny-advantage batches shrink
        the policy-loss magnitude instead of being blown up to unit scale."""
        policy, params = setup
        batch = random_batch(policy, params)
        # Tiny rewards => tiny advantages (values at init are near zero too).
        batch["rewards"] = batch["rewards"] * 1e-4
        # Perturb behavior_logp so ratio != 1 and the surrogate is nonzero
        # (centered advantages at ratio 1 sum to exactly zero). The clipped
        # min() is still positively homogeneous in the advantages, so the
        # floor-vs-none scaling relation below is exact.
        rng = np.random.default_rng(7)
        batch["behavior_logp"] = batch["behavior_logp"] + jnp.asarray(
            rng.normal(size=batch["behavior_logp"].shape).astype(np.float32)
            * 0.1
        )
        cfg0 = CFG.ppo
        cfg_floor0 = dataclasses.replace(cfg0, adv_norm_floor=0.0)
        cfg_floor_big = dataclasses.replace(cfg0, adv_norm_floor=10.0)
        cfg_none = dataclasses.replace(cfg0, adv_norm="none")
        l_std, m_std = ppo_loss(policy, params, batch, cfg_floor0)
        l_base, _ = ppo_loss(policy, params, batch, cfg0)
        np.testing.assert_allclose(float(l_std), float(l_base), rtol=1e-6)
        l_floor, m_floor = ppo_loss(policy, params, batch, cfg_floor_big)
        l_none, m_none = ppo_loss(policy, params, batch, cfg_none)
        np.testing.assert_allclose(
            float(m_floor["policy_loss"]), float(m_none["policy_loss"]) / 10.0,
            rtol=1e-4, atol=1e-12,
        )
        # The floored mode keeps the tiny-signal policy loss tiny; the
        # standard whitening inflates it by orders of magnitude.
        assert abs(float(m_none["policy_loss"])) < 1e-2
        assert abs(float(m_none["policy_loss"])) < abs(
            float(m_std["policy_loss"])
        )
        with pytest.raises(ValueError):
            ppo_loss(
                policy, params, batch,
                dataclasses.replace(cfg0, adv_norm="bogus"),
            )

    def test_value_warmup_freezes_policy(self, setup):
        """During value_warmup_steps only the value head moves — every other
        param is bitwise frozen; after the window the full update resumes
        (the --init-from critic-recalibration lever, BASELINE.md)."""
        policy, params = setup
        cfg = dataclasses.replace(CFG, ppo=dataclasses.replace(
            CFG.ppo, value_warmup_steps=2,
        ))
        mesh = make_mesh(cfg.mesh)
        step = make_train_step(policy, cfg, mesh)
        state = init_train_state(params, cfg.ppo)
        p0 = jax.tree.map(np.asarray, state.params)
        for _ in range(2):
            batch = random_batch(policy, params)
            state, _ = step(state, batch)
        p_warm = jax.tree.map(np.asarray, state.params)
        flat0 = dict(jax.tree_util.tree_flatten_with_path(p0)[0])
        flatw = dict(jax.tree_util.tree_flatten_with_path(p_warm)[0])
        moved_head = frozen_rest = 0
        for path, v0 in flat0.items():
            in_head = any(
                getattr(k, "key", None) == "head_value" for k in path
            )
            if in_head:
                assert not np.array_equal(v0, flatw[path]), path
                moved_head += 1
            else:
                np.testing.assert_array_equal(v0, flatw[path], err_msg=str(path))
                frozen_rest += 1
        assert moved_head >= 2 and frozen_rest > 2
        # Step 2 (>= warmup): the policy resumes moving, and the optimizer
        # state is re-initialized at the boundary (frozen params' Adam
        # moments are zero while the shared count advanced during warmup —
        # without the reset the first live update is ~3x oversized). The
        # first live step therefore leaves Adam's count at 1, not 3.
        state, _ = step(state, random_batch(policy, params))
        p_after = jax.tree.map(np.asarray, state.params)
        flata = dict(jax.tree_util.tree_flatten_with_path(p_after)[0])
        assert any(
            not np.array_equal(flatw[path], flata[path])
            for path in flat0
            if not any(getattr(k, "key", None) == "head_value" for k in path)
        )
        counts = [
            int(leaf)
            for path, leaf in jax.tree_util.tree_flatten_with_path(
                state.opt_state
            )[0]
            if any(getattr(k, "name", None) == "count" for k in path)
        ]
        assert counts and all(c == 1 for c in counts), counts


class TestAnchorKL:
    """PPOConfig.anchor_kl_coef — the AlphaStar-style pull toward a frozen
    anchor policy (the anti-drift lever for curriculum fine-tunes)."""

    def test_kl_is_exact_and_nonnegative(self, setup):
        policy, params = setup
        params2 = init_params(policy, jax.random.PRNGKey(7))
        batch = random_batch(policy, params, seed=3)
        T = CFG.ppo.rollout_len
        obs_t = {k: v[:, :T] for k, v in batch["obs"].items()}

        def logits_of(p):
            logits, _, _ = policy.apply(
                p, batch["obs"], batch["carry0"], batch["dones"],
                method="sequence",
            )
            return {k: v[:, :T] for k, v in logits.items()}

        la, lb = logits_of(params), logits_of(params2)
        self_kl = np.asarray(D.kl(la, la, obs_t))
        np.testing.assert_allclose(self_kl, 0.0, atol=1e-5)
        cross = np.asarray(D.kl(la, lb, obs_t))
        assert (cross > -1e-5).all()
        assert cross.max() > 1e-4   # distinct params actually differ

    def test_anchor_term_zero_at_anchor_and_positive_away(self, setup):
        policy, params = setup
        batch = random_batch(policy, params, seed=4)
        cfg = dataclasses.replace(CFG.ppo, anchor_kl_coef=0.5)
        base_loss, base_m = ppo_loss(policy, params, batch, CFG.ppo)
        loss_at, m_at = ppo_loss(
            policy, params, batch, cfg, anchor_params=params
        )
        np.testing.assert_allclose(
            float(m_at["anchor_kl"]), 0.0, atol=1e-5
        )
        np.testing.assert_allclose(
            float(loss_at), float(base_loss), rtol=1e-5, atol=1e-6
        )
        far = init_params(policy, jax.random.PRNGKey(8))
        loss_far, m_far = ppo_loss(
            policy, params, batch, cfg, anchor_params=far
        )
        assert float(m_far["anchor_kl"]) > 1e-4
        np.testing.assert_allclose(
            float(loss_far),
            float(base_loss) + 0.5 * float(m_far["anchor_kl"]),
            rtol=1e-4,
        )

    def test_train_step_with_anchor_stays_closer(self, setup):
        """A few steps on the same batches: the anchored run ends closer
        (in param space) to the anchor than the unanchored run."""
        policy, params = setup
        batches = [random_batch(policy, params, seed=s) for s in (5, 6, 7)]

        def run(coef):
            cfg = dataclasses.replace(
                CFG,
                ppo=dataclasses.replace(CFG.ppo, anchor_kl_coef=coef),
            )
            mesh = make_mesh(cfg.mesh)
            step = make_train_step(
                policy, cfg, mesh,
                anchor_params=params if coef > 0 else None,
            )
            state = init_train_state(params, cfg.ppo)
            for b in batches:
                state, m = step(state, b)
            dist = sum(
                float(jnp.sum(jnp.square(a - b)))
                for a, b in zip(
                    jax.tree.leaves(state.params), jax.tree.leaves(params)
                )
            )
            return dist, m

        d_free, _ = run(0.0)
        d_anchored, m = run(10.0)
        assert "anchor_kl" in m
        assert d_anchored < d_free

    def test_make_train_step_coef_anchor_mismatch_raises(self, setup):
        policy, params = setup
        cfg = dataclasses.replace(
            CFG, ppo=dataclasses.replace(CFG.ppo, anchor_kl_coef=0.1)
        )
        mesh = make_mesh(cfg.mesh)
        with pytest.raises(ValueError, match="anchor_params"):
            make_train_step(policy, cfg, mesh)
        with pytest.raises(ValueError, match="anchor_params"):
            make_train_step(policy, CFG, mesh, anchor_params=params)


class TestKLAdaptiveLR:
    def _step_fn(self, policy, params, kl_cfg):
        cfg = dataclasses.replace(CFG, ppo=kl_cfg)
        mesh = make_mesh(cfg.mesh)
        return make_train_step(policy, cfg, mesh), init_train_state(
            params, kl_cfg
        )

    def test_default_layout_unchanged(self, setup):
        """kl_target=0 keeps the plain-Adam optimizer state: no injected
        hyperparams leaf, so existing checkpoints restore unchanged."""
        policy, params = setup
        state = init_train_state(params, CFG.ppo)
        paths = [
            jax.tree_util.keystr(p)
            for p, _ in jax.tree_util.tree_flatten_with_path(state.opt_state)[0]
        ]
        assert not any("hyperparams" in p for p in paths)

    def test_lr_shrinks_on_kl_overshoot_and_grows_when_under(self, setup):
        policy, params = setup
        # Microscopic target: every real update overshoots 2*target.
        tiny = dataclasses.replace(CFG.ppo, kl_target=1e-9, kl_lr_down=0.5)
        step, state = self._step_fn(policy, params, tiny)
        lrs = []
        for i in range(3):
            state, m = step(state, random_batch(policy, params, seed=i))
            assert float(m["post_kl"]) >= 0.0
            lrs.append(float(m["lr"]))
        lr0 = CFG.ppo.learning_rate
        np.testing.assert_allclose(
            lrs, [lr0, lr0 * 0.5, lr0 * 0.25], rtol=1e-5
        )
        # Huge target: always under target/2 -> lr ratchets up by kl_lr_up.
        huge = dataclasses.replace(CFG.ppo, kl_target=1e3, kl_lr_up=1.5)
        step, state = self._step_fn(policy, params, huge)
        lrs = []
        for i in range(3):
            state, m = step(state, random_batch(policy, params, seed=i))
            lrs.append(float(m["lr"]))
        np.testing.assert_allclose(
            lrs, [lr0, lr0 * 1.5, lr0 * 2.25], rtol=1e-5
        )

    def test_lr_clipped_at_min_scale(self, setup):
        policy, params = setup
        cfg = dataclasses.replace(
            CFG.ppo, kl_target=1e-9, kl_lr_down=0.01, kl_lr_min_scale=0.1
        )
        step, state = self._step_fn(policy, params, cfg)
        for i in range(3):
            state, m = step(state, random_batch(policy, params, seed=i))
        # After two shrinks the clip floor (0.1 * lr0) is binding.
        assert float(m["lr"]) == pytest.approx(
            CFG.ppo.learning_rate * 0.1, rel=1e-5
        )


class TestTrainStep:
    def test_step_runs_and_updates(self, setup):
        policy, params = setup
        mesh = make_mesh(CFG.mesh)  # 8x1 on forced host devices
        assert mesh.devices.size == 8
        state = init_train_state(params, CFG.ppo)
        step = make_train_step(policy, CFG, mesh)
        batch = random_batch(policy, params, batch=16)
        state2, metrics = step(state, batch)
        assert int(state2.step) == 1
        assert int(state2.version) == 1
        assert np.isfinite(float(metrics["loss"]))
        assert float(metrics["grad_norm"]) > 0
        # params actually moved
        delta = jax.tree.reduce(
            lambda acc, x: acc + float(jnp.abs(x).sum()),
            jax.tree.map(lambda a, b: a - b, state2.params, params),
            0.0,
        )
        assert delta > 0

    def test_1dev_vs_8dev_equivalence(self, setup):
        """Golden-shard test (SURVEY.md §4): the sharded train step must
        reproduce the single-device result."""
        policy, params = setup
        batch = random_batch(policy, params, batch=16, seed=3)

        mesh8 = make_mesh(CFG.mesh)
        state8 = init_train_state(params, CFG.ppo)
        step8 = make_train_step(policy, CFG, mesh8)
        new8, m8 = step8(state8, batch)

        mesh1 = make_mesh(
            dataclasses.replace(CFG.mesh, data_parallel=1),
            devices=jax.devices()[:1],
        )
        state1 = init_train_state(params, CFG.ppo)
        step1 = make_train_step(policy, CFG, mesh1)
        new1, m1 = step1(state1, batch)

        np.testing.assert_allclose(
            float(m8["loss"]), float(m1["loss"]), rtol=1e-5
        )
        leaves8 = jax.tree.leaves(new8.params)
        leaves1 = jax.tree.leaves(new1.params)
        for a, b in zip(leaves8, leaves1):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
            )

    def test_epoch_step_matches_staged_minibatch_path(self, setup):
        """The fused epoch step (ONE donated dispatch for all E×M updates)
        must reproduce the staged loop's result when fed the same
        permutations — an execution-plan change, not a training change
        (ISSUE 2 acceptance). Exactness bound: the scanned program fuses
        differently from separate dispatches, so agreement is to float-ulp
        rounding (measured ~1e-10 absolute after 4 updates on CPU), not
        bitwise."""
        policy, params = setup
        # minibatch size (B/M) must stay divisible by the 8 forced host
        # devices — the same constraint the Learner validates at init
        E, M = 2, 2
        cfg = dataclasses.replace(
            CFG,
            ppo=dataclasses.replace(
                CFG.ppo, epochs_per_batch=E, minibatches=M, batch_rollouts=16
            ),
        )
        mesh = make_mesh(cfg.mesh)
        batch = random_batch(policy, params, batch=16, seed=7)
        B, mb = 16, 16 // M
        rng = np.random.default_rng(41)
        perms = np.stack([rng.permutation(B) for _ in range(E)])

        # staged path: a jitted gather + a train-step dispatch per minibatch
        from dotaclient_tpu.parallel import data_sharding

        gather = jax.jit(
            lambda b, idx: jax.tree.map(lambda x: x[idx], b),
            out_shardings=data_sharding(mesh, cfg.mesh),
        )
        staged = init_train_state(params, cfg.ppo)
        step = make_train_step(policy, cfg, mesh)
        staged_metrics = None
        for e in range(E):
            for i in range(M):
                idx = jnp.asarray(perms[e, i * mb:(i + 1) * mb], jnp.int32)
                staged, staged_metrics = step(staged, gather(batch, idx))

        # fused path: everything in one program
        fused = init_train_state(params, cfg.ppo)
        epoch_step = make_epoch_step(policy, cfg, mesh)
        fused, fused_metrics = epoch_step(
            fused, batch, jnp.asarray(perms, jnp.int32)
        )

        assert int(fused.step) == int(staged.step) == E * M
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-7
            ),
            fused.params,
            staged.params,
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                rtol=1e-4, atol=1e-7,
            ),
            fused.opt_state,
            staged.opt_state,
        )
        for k in ("loss", "policy_loss", "value_loss", "entropy"):
            np.testing.assert_allclose(
                np.asarray(fused_metrics[k]), np.asarray(staged_metrics[k]),
                rtol=1e-4, atol=1e-7,
            )

    def test_epoch_step_single_minibatch_matches_plain_steps(self, setup):
        """M == 1: the epoch step scans E whole-batch updates and ignores
        the permutation placeholder — matching E plain train steps (same
        float-ulp fusion bound as the minibatched parity test)."""
        policy, params = setup
        E = 3
        cfg = dataclasses.replace(
            CFG,
            ppo=dataclasses.replace(
                CFG.ppo, epochs_per_batch=E, minibatches=1, batch_rollouts=8
            ),
        )
        mesh = make_mesh(cfg.mesh)
        batch = random_batch(policy, params, batch=8, seed=11)
        plain = init_train_state(params, cfg.ppo)
        step = make_train_step(policy, cfg, mesh)
        for _ in range(E):
            plain, _ = step(plain, batch)
        fused = init_train_state(params, cfg.ppo)
        epoch_step = make_epoch_step(policy, cfg, mesh)
        perms = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (E, 8))
        fused, _ = epoch_step(fused, batch, perms)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-7
            ),
            fused.params,
            plain.params,
        )

    def test_learning_reduces_loss_on_fixed_batch(self, setup):
        """A few steps on one batch must reduce the PPO objective (sanity
        that gradients point the right way end-to-end)."""
        policy, params = setup
        mesh = make_mesh(CFG.mesh)
        state = init_train_state(params, CFG.ppo)
        step = make_train_step(policy, CFG, mesh)
        batch = random_batch(policy, params, batch=8, seed=5)
        losses = []
        for _ in range(5):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0]
