"""Pipeline utilization plane tests (ISSUE 16): phase fractions sum to
~1.0 by construction, the duty cycle pinned against a synthetic dispatch
timeline, the throughput-regression sentinel's warmup arming, snapshot-
frame round trip + fleet ship_wait rollup, the two alert rules' arming
and debounce through the engine, the --require-utilization schema tier,
the off-path cost discipline (factories return None — one pointer test
per call site, the faults.get() pattern), and the report-console bugfix
sweep (trace_report / outcome_report degrade cleanly on fuzzed logs)."""

import json
import os
import time

import pytest

from dotaclient_tpu.utils import alerts, fleet, telemetry, utilization

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _script_module(name):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _accountant_enabled():
    """Every test starts and ends with the plane enabled (the always-on
    default); a leaked False would silently disable other tests' pools."""
    utilization.enabled = True
    yield
    utilization.enabled = True


# ---------------------------------------------------------------------------
# phase accounting arithmetic


class TestPhaseAccountant:
    def _acct(self, reg=None):
        reg = reg or telemetry.Registry()
        handles = utilization.ensure_learner_keys(reg)
        gauges = {
            p: handles[f"util/phase/{p}"]
            for p in utilization.LEARNER_PHASES
        }
        return utilization.PhaseAccountant(
            gauges, utilization.LEARNER_PHASES, residual="host_other",
            now=0.0,
        )

    def test_fractions_sum_to_one(self):
        acct = self._acct()
        acct.phase("dispatch_inflight", 6.0)
        acct.phase("ingest_wait", 2.0)
        acct.phase("gather", 1.0)
        fractions, window = acct.fold(now=10.0)
        assert window == 10.0
        assert abs(sum(fractions.values()) - 1.0) < 1e-9
        assert fractions["dispatch_inflight"] == pytest.approx(0.6)
        assert fractions["host_other"] == pytest.approx(0.1)

    def test_overaccounted_clamps_not_overflows(self):
        """Clock noise pushing accounted past the window must shrink the
        residual to 0, never the sum past 1 (the denominator contract)."""
        acct = self._acct()
        acct.phase("dispatch_inflight", 11.0)
        fractions, _ = acct.fold(now=10.0)
        assert fractions["host_other"] == 0.0
        assert abs(sum(fractions.values()) - 1.0) < 1e-9

    def test_zero_window_is_a_noop(self):
        acct = self._acct()
        assert acct.fold(now=0.0) == ({}, 0.0)

    def test_negative_and_zero_intervals_ignored(self):
        acct = self._acct()
        acct.phase("gather", -1.0)
        acct.phase("gather", 0.0)
        fractions, _ = acct.fold(now=4.0)
        assert fractions["gather"] == 0.0
        assert fractions["host_other"] == pytest.approx(1.0)

    def test_fold_resets_the_window(self):
        acct = self._acct()
        acct.phase("gather", 5.0)
        acct.fold(now=10.0)
        fractions, window = acct.fold(now=14.0)
        assert window == pytest.approx(4.0)
        assert fractions["gather"] == 0.0


class TestLearnerUtilization:
    def _lu(self):
        reg = telemetry.Registry()
        handles = utilization.ensure_learner_keys(reg)
        lu = utilization.LearnerUtilization(handles)
        lu._acct._window_start = 0.0   # pin the synthetic timeline origin
        return reg, lu

    def test_duty_cycle_pinned_against_synthetic_timeline(self):
        """10 s window in which the donated dispatch was in flight 7 s:
        duty cycle 0.7, armed flips, gauges carry the fractions."""
        reg, lu = self._lu()
        # pre-arm: neutral duty cycle, unarmed
        snap = reg.snapshot()
        assert snap["util/armed"] == 0.0
        assert snap["util/duty_cycle"] == 1.0
        lu.phase("dispatch_inflight", 7.0)
        lu.phase("ingest_wait", 1.5)
        lu.phase("publish_stall", 0.5)
        fractions = lu.fold(step=100, now=10.0)
        snap = reg.snapshot()
        assert snap["util/armed"] == 1.0
        assert snap["util/duty_cycle"] == pytest.approx(0.7)
        assert snap["util/phase/ingest_wait"] == pytest.approx(0.15)
        assert snap["util/phase/host_other"] == pytest.approx(0.1)
        assert abs(sum(fractions.values()) - 1.0) < 1e-9

    def test_sentinel_arms_after_warmup_then_latches_on_regression(self):
        reg, lu = self._lu()
        now, step = 0.0, 0
        # warmup + settle at 10 steps/s: first fold has no prior step
        for _ in range(5):
            now += 10.0
            step += 100
            lu.fold(step=step, now=now)
        snap = reg.snapshot()
        assert snap["util/steps_per_sec_ema"] == pytest.approx(10.0)
        assert snap["util/steps_per_sec_baseline"] == pytest.approx(10.0)
        assert snap["util/throughput_regression"] == 0.0
        # throughput collapses to ~0.1 steps/s; the fast EMA chases it
        # down while the slow baseline remembers 10 — the latch comes up
        for _ in range(3):
            now += 10.0
            step += 1
            lu.fold(step=step, now=now)
        snap = reg.snapshot()
        assert snap["util/steps_per_sec_ema"] < 0.7 * snap[
            "util/steps_per_sec_baseline"
        ]
        assert snap["util/throughput_regression"] == 1.0

    def test_same_step_refold_never_poisons_the_ema(self):
        """The end-of-run flush re-folds at the final step: a zero-step
        window must contribute NO rate sample (a rate-0 sample would drag
        the EMA down and spuriously latch the sentinel on every clean
        shutdown)."""
        reg, lu = self._lu()
        now, step = 0.0, 0
        for _ in range(6):
            now += 10.0
            step += 100
            lu.fold(step=step, now=now)
        before = reg.snapshot()
        lu.fold(step=step, now=now + 30.0)   # the final-flush double fold
        after = reg.snapshot()
        assert after["util/steps_per_sec_ema"] == before[
            "util/steps_per_sec_ema"
        ]
        assert after["util/throughput_regression"] == 0.0

    def test_no_rate_before_two_folds(self):
        """The first fold has no prior step — fractions publish but the
        EMA stays unarmed (no bogus rate from a half-open interval)."""
        reg, lu = self._lu()
        lu.fold(step=50, now=10.0)
        assert reg.snapshot()["util/steps_per_sec_ema"] == 0.0


class TestPoolUtilization:
    def test_cadence_gated_fold(self):
        reg = telemetry.Registry()
        pool = utilization.make_actor(reg, interval_s=100.0)
        t0 = pool._last_fold
        pool.phase("env_step", 1.0)
        assert pool.maybe_fold(now=t0 + 1.0) is None      # not due
        fractions = pool.maybe_fold(now=t0 + 101.0)       # due: folds
        assert fractions is not None
        assert abs(sum(fractions.values()) - 1.0) < 1e-9
        assert reg.snapshot()["util/actor/env_step"] > 0.0


# ---------------------------------------------------------------------------
# off-path cost: the faults.get() discipline


class TestOffPathDiscipline:
    def test_factories_return_none_but_keys_exist(self):
        """Disabled, every factory still eager-creates its keys (the
        schema tier holds for ANY JSONL) and returns None — a call site
        pays exactly one `is not None` pointer test."""
        utilization.enabled = False
        reg = telemetry.Registry()
        assert utilization.make_learner(reg) is None
        assert utilization.make_actor(reg) is None
        assert utilization.make_serve(reg) is None
        snap = reg.snapshot()
        for key in (
            "util/armed", "util/duty_cycle", "util/steps_per_sec_ema",
            "util/phase/dispatch_inflight", "util/phase/host_other",
            "util/actor/ship_wait", "util/serve/window_wait",
        ):
            assert key in snap, key
        # the duty-cycle gauge reads its NEUTRAL 1.0, not a 0.0 that
        # would trip learner_duty_cycle_low on a disabled run
        assert snap["util/duty_cycle"] == 1.0
        assert snap["util/armed"] == 0.0

    def test_enabled_factories_return_accountants(self):
        reg = telemetry.Registry()
        assert utilization.make_learner(reg) is not None
        assert utilization.make_actor(reg) is not None
        assert utilization.make_serve(reg) is not None


# ---------------------------------------------------------------------------
# snapshot frames + fleet rollup


class TestFleetIntegration:
    def test_util_namespace_ships_on_snapshots(self):
        assert "util/" in fleet.SNAPSHOT_PREFIXES

    def test_snapshot_round_trip_carries_util_gauges(self):
        payload = fleet.encode_snapshot(
            3, "actor", 1, {},
            {"util/actor/ship_wait": 0.25, "util/actor/env_step": 0.5},
            pid=9,
        )
        snap = fleet.decode_snapshot(payload)
        assert snap["gauges"]["util/actor/ship_wait"] == 0.25
        assert snap["gauges"]["util/actor/env_step"] == 0.5

    def test_ship_wait_rollup_across_peers(self):
        reg = telemetry.Registry()
        agg = fleet.FleetAggregator(
            registry=reg, interval_s=0.1, emit_event=None
        )
        t = time.monotonic()
        agg.ingest(fleet.encode_snapshot(
            0, "actor", 0, {}, {"util/actor/ship_wait": 0.1}, pid=1))
        agg.ingest(fleet.encode_snapshot(
            1, "actor", 0, {}, {"util/actor/ship_wait": 0.3}, pid=2))
        agg.tick(now=t)
        snap = reg.snapshot()
        assert snap["fleet/agg/ship_wait/min"] == pytest.approx(0.1)
        assert snap["fleet/agg/ship_wait/max"] == pytest.approx(0.3)
        assert snap["fleet/agg/ship_wait/mean"] == pytest.approx(0.2)
        # per-peer mirrors exist for the utilization report's peer rows
        assert snap["fleet/a0/util/actor/ship_wait"] == pytest.approx(0.1)


# ---------------------------------------------------------------------------
# alert rules


def _engine(rule_names):
    rules = tuple(r for r in alerts.RULES if r.name in rule_names)
    assert len(rules) == len(rule_names)
    events = []
    engine = alerts.AlertEngine(
        rules=rules, registry=telemetry.Registry(), emit=events.append
    )
    return engine, events


class TestAlertRules:
    def test_rules_exist_with_runbook_anchors(self):
        by_name = {r.name: r for r in alerts.RULES}
        duty = by_name["learner_duty_cycle_low"]
        assert duty.key == "util/duty_cycle"
        assert duty.runbook == "rb:duty-cycle-low"
        reg = by_name["throughput_regression"]
        assert reg.key == "util/throughput_regression"
        assert reg.runbook == "rb:throughput-regression"

    def test_duty_cycle_low_arms_and_debounces(self):
        engine, events = _engine(["learner_duty_cycle_low"])
        t = 1000.0
        # neutral pre-arm value: never fires
        fired, _ = engine.evaluate({"util/duty_cycle": 1.0}, now=t)
        assert fired == []
        # low duty cycle must HOLD for for_s before firing (debounce)
        fired, _ = engine.evaluate({"util/duty_cycle": 0.05}, now=t + 1)
        assert fired == []
        fired, _ = engine.evaluate({"util/duty_cycle": 0.05}, now=t + 122)
        assert fired == ["learner_duty_cycle_low"]
        # recovery resolves
        _, resolved = engine.evaluate({"util/duty_cycle": 0.8}, now=t + 123)
        assert resolved == ["learner_duty_cycle_low"]
        assert [e["state"] for e in events] == ["fired", "resolved"]

    def test_throughput_regression_latch_fires(self):
        engine, _ = _engine(["throughput_regression"])
        t = 2000.0
        fired, _ = engine.evaluate(
            {"util/throughput_regression": 0.0}, now=t)
        assert fired == []
        fired, _ = engine.evaluate(
            {"util/throughput_regression": 1.0}, now=t + 1)
        assert fired == []   # for_s=60 debounce
        fired, _ = engine.evaluate(
            {"util/throughput_regression": 1.0}, now=t + 62)
        assert fired == ["throughput_regression"]


# ---------------------------------------------------------------------------
# schema tier


class TestSchemaTier:
    def _line(self, extra=None):
        scalars = {k: 0.0 for k in _script_module(
            "check_telemetry_schema").UTILIZATION_KEYS}
        scalars["util/duty_cycle"] = 1.0
        if extra:
            scalars.update(extra)
        return json.dumps({"ts": 1.0, "step": 0, "scalars": scalars})

    def test_require_utilization_round_trip(self):
        schema = _script_module("check_telemetry_schema")
        errors = schema.validate_lines(
            [self._line()],
            extra_required=schema.UTILIZATION_KEYS,
            base_required=(),
        )
        assert errors == []

    def test_missing_key_is_a_violation(self):
        schema = _script_module("check_telemetry_schema")
        scalars = json.loads(self._line())
        del scalars["scalars"]["util/phase/ingest_wait"]
        errors = schema.validate_lines(
            [json.dumps(scalars)],
            extra_required=schema.UTILIZATION_KEYS,
            base_required=(),
        )
        assert any("util/phase/ingest_wait" in e for e in errors)


# ---------------------------------------------------------------------------
# utilization report console


class TestUtilizationReport:
    def _write(self, tmp_path, scalars):
        path = tmp_path / "learner.jsonl"
        path.write_text(
            json.dumps({"ts": time.time(), "step": 7, "scalars": scalars})
            + "\n"
        )
        return str(path)

    def test_armed_run_renders_table_and_ok(self, tmp_path, capsys):
        report = _script_module("utilization_report")
        scalars = {
            "util/armed": 1.0,
            "util/duty_cycle": 0.62,
            "util/steps_per_sec_ema": 9.5,
            "util/steps_per_sec_baseline": 10.0,
            "util/throughput_regression": 0.0,
            "util/phase/dispatch_inflight": 0.62,
            "util/phase/ingest_wait": 0.2,
            "util/phase/gather": 0.08,
            "util/phase/advantage_pass": 0.04,
            "util/phase/publish_stall": 0.02,
            "util/phase/checkpoint_stall": 0.0,
            "util/phase/host_other": 0.04,
            # an external actor peer's mirrored fractions
            "fleet/a0/util/actor/env_step": 0.5,
            "fleet/a0/util/actor/ship_wait": 0.3,
        }
        assert report.main([self._write(tmp_path, scalars)]) == 0
        out = capsys.readouterr().out
        assert "learner" in out and "a0" in out
        line = [
            l for l in out.splitlines()
            if l.startswith("UTILIZATION_STATUS ")
        ]
        status = json.loads(line[0][len("UTILIZATION_STATUS "):])
        assert status["ok"] is True
        assert status["duty_cycle"] == 0.62
        assert status["phases"]["ingest_wait"] == 0.2
        assert status["peers"]["a0"]["ship_wait"] == 0.3

    def test_unarmed_run_exits_nonzero(self, tmp_path, capsys):
        report = _script_module("utilization_report")
        scalars = {"util/armed": 0.0, "util/duty_cycle": 1.0}
        assert report.main([self._write(tmp_path, scalars)]) == 1
        assert "unarmed" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# bugfix sweep: report consoles must degrade cleanly on fuzzed logs


class TestReportConsolesDegradeCleanly:
    def test_trace_report_survives_fuzzed_events(self, tmp_path):
        """The four crash shapes from the sweep: a 1-element hop entry, a
        null publish version, a null hop timestamp, and a non-numeric
        publish_ts — each must degrade to 'evidence absent', not a
        ValueError/TypeError."""
        from scripts.trace_report import build_report

        lines = [
            {"event": "chunk", "tid": "t1", "origin_pid": 1, "actor": 0,
             "wv": 3, "hops": [["collect", 1.0], ["encode"]]},
            {"event": "publish", "version": None, "ts": 1.0},
            {"event": "chunk", "tid": "s1", "origin_pid": 2, "actor": 0,
             "wv": 3, "hops": [["encode", 1.0], ["done", None]]},
            {"event": "apply", "version": 3, "pid": 1,
             "publish_ts": "not-a-number", "ts": 2.0},
            {"event": "chunk", "tid": "t2", "origin_pid": 1, "actor": 0,
             "wv": None, "hops": [["encode", 1.0], ["dispatch", 2.0]]},
        ]
        p = tmp_path / "fuzz.trace.jsonl"
        p.write_text("\n".join(json.dumps(l) for l in lines) + "\n")
        rep = build_report([str(tmp_path)])   # must not raise
        assert rep["chunks_seen"] >= 1

    def test_trace_report_zero_complete_chunks(self, tmp_path):
        from scripts.trace_report import main as report_main

        p = tmp_path / "sparse.trace.jsonl"
        p.write_text(
            json.dumps({"event": "chunk", "tid": "x",
                        "hops": [["collect", 1.0]]}) + "\n"
        )
        # no complete chunk → nonzero by design, but NO crash
        assert report_main(["--json", str(tmp_path)]) in (0, 1)

    def test_outcome_report_survives_non_numeric_ts(self, tmp_path, capsys):
        report = _script_module("outcome_report")
        p = tmp_path / "learner.jsonl"
        p.write_text(
            json.dumps({"ts": "not-a-number", "step": 4,
                        "scalars": {"outcome/episodes_total": 0.0}}) + "\n"
        )
        # zero episodes → rc 1 by design, but render must not TypeError
        assert report.main([str(p)]) == 1
        assert "OUTCOME_STATUS" in capsys.readouterr().out

    def test_fleet_status_survives_non_numeric_ts(self, tmp_path, capsys):
        status = _script_module("fleet_status")
        p = tmp_path / "learner.jsonl"
        p.write_text(
            json.dumps({"ts": None, "step": "x", "scalars": {}}) + "\n"
        )
        assert status.main([str(p)]) == 0
        assert "FLEET_STATUS" in capsys.readouterr().out
