"""Transformer-core tests: recurrent-cell contract, step≡sequence parity,
episode resets, and end-to-end training through the device actor.

The core must be indistinguishable from the LSTM at the framework contract
level (carried state, chunked sequences, done resets) — SURVEY.md §5.7's
state-carry discipline with a KV-cache carry instead of (h, c).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dotaclient_tpu.config import default_config
from dotaclient_tpu.models import init_params, make_policy
from dotaclient_tpu.models.policy import dummy_obs_batch, mask_carry


def tf_config(**model_kw):
    cfg = default_config()
    return dataclasses.replace(
        cfg,
        model=dataclasses.replace(
            cfg.model, core="transformer", n_layers=2, n_heads=4,
            context_window=8, dtype="float32", **model_kw,
        ),
    )


@pytest.fixture(scope="module")
def setup():
    cfg = tf_config()
    policy = make_policy(cfg.model, cfg.obs, cfg.actions)
    params = init_params(policy, jax.random.PRNGKey(0))
    return cfg, policy, params


def rand_obs(cfg, batch, time=None, seed=0):
    rng = np.random.default_rng(seed)
    obs = dict(dummy_obs_batch(batch, cfg.obs, cfg.actions, time=time))
    obs["units"] = jnp.asarray(rng.normal(size=obs["units"].shape).astype(np.float32))
    obs["globals"] = jnp.asarray(rng.normal(size=obs["globals"].shape).astype(np.float32))
    return obs


class TestTransformerCore:
    def test_initial_state_layout(self, setup):
        cfg, policy, _ = setup
        carry = policy.initial_state(3)
        valid, caches = carry
        assert valid.shape == (3, cfg.model.context_window)
        assert len(caches) == cfg.model.n_layers
        assert caches[0][0].shape == (3, cfg.model.context_window, cfg.model.hidden_dim)

    def test_step_changes_carry_and_outputs(self, setup):
        cfg, policy, params = setup
        obs = rand_obs(cfg, 2)
        carry = policy.initial_state(2)
        logits, value, carry2 = policy.apply(params, obs, carry, method="step")
        assert value.shape == (2,)
        assert logits["action_type"].shape == (2, cfg.actions.n_action_types)
        # cache rolled: last slot now valid
        assert float(carry2[0][:, -1].min()) == 1.0
        assert float(jnp.abs(carry2[1][0][0][:, -1]).max()) > 0.0

    def test_sequence_equals_steps(self, setup):
        """scan-of-cell ≡ explicit per-step loop (the LSTM parity property,
        inherited structurally — pinned anyway)."""
        cfg, policy, params = setup
        B, T = 2, 6
        obs_seq = rand_obs(cfg, B, time=T, seed=1)
        carry = policy.initial_state(B)
        logits_seq, values_seq, _ = policy.apply(
            params, obs_seq, carry, method="sequence"
        )
        vals, logs = [], []
        c = carry
        for t in range(T):
            obs_t = {k: v[:, t] for k, v in obs_seq.items()}
            lg, vv, c = policy.apply(params, obs_t, c, method="step")
            vals.append(vv)
            logs.append(lg["action_type"])
        np.testing.assert_allclose(
            np.asarray(values_seq), np.stack([np.asarray(v) for v in vals], 1),
            rtol=1e-5, atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(logits_seq["action_type"]),
            np.stack([np.asarray(l) for l in logs], 1),
            rtol=1e-5, atol=1e-5,
        )

    def test_done_reset_matches_fresh_start(self, setup):
        """After a mid-sequence done, outputs must equal a fresh-carry run of
        the post-done suffix (the cache must not leak across episodes)."""
        cfg, policy, params = setup
        B, T = 2, 6
        cut = 3
        obs_seq = rand_obs(cfg, B, time=T, seed=2)
        dones = jnp.zeros((B, T), jnp.float32).at[:, cut - 1].set(1.0)
        carry = policy.initial_state(B)
        logits_seq, values_seq, _ = policy.apply(
            params, obs_seq, carry, dones, method="sequence"
        )
        suffix = {k: v[:, cut:] for k, v in obs_seq.items()}
        logits_fresh, values_fresh, _ = policy.apply(
            params, suffix, policy.initial_state(B), method="sequence"
        )
        np.testing.assert_allclose(
            np.asarray(values_seq[:, cut:]), np.asarray(values_fresh),
            rtol=1e-5, atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(logits_seq["action_type"][:, cut:]),
            np.asarray(logits_fresh["action_type"]),
            rtol=1e-5, atol=1e-5,
        )

    def test_mask_carry_zeroes_all_leaves(self, setup):
        cfg, policy, params = setup
        obs = rand_obs(cfg, 2)
        carry = policy.initial_state(2)
        _, _, carry = policy.apply(params, obs, carry, method="step")
        masked = mask_carry(carry, jnp.asarray([0.0, 1.0]))
        for leaf in jax.tree.leaves(masked):
            assert float(jnp.abs(leaf[0]).max()) == 0.0  # row 0 reset
        assert float(jnp.abs(masked[0][1]).max()) > 0.0  # row 1 kept


class TestTransformerTraining:
    @pytest.mark.slow   # tier-1 duration audit (ISSUE 6): ~61s on the reference container
    def test_device_actor_and_train_step(self):
        """core="transformer" trains end-to-end on the smoke config
        (VERDICT round 1 item 7's bar)."""
        from dotaclient_tpu.train.learner import Learner

        cfg = tf_config()
        cfg = dataclasses.replace(
            cfg,
            env=dataclasses.replace(cfg.env, n_envs=4, max_dota_time=30.0),
            ppo=dataclasses.replace(cfg.ppo, rollout_len=8, batch_rollouts=8),
            buffer=dataclasses.replace(cfg.buffer, capacity_rollouts=32, min_fill=8),
            log_every=1000,
        )
        lrn = Learner(cfg, actor="device")
        stats = lrn.train(4)
        assert stats["optimizer_steps"] >= 4

    def test_vec_pool_supports_transformer(self):
        import jax as _jax
        from dotaclient_tpu.actor.vec_runtime import VecActorPool

        cfg = tf_config()
        cfg = dataclasses.replace(
            cfg,
            env=dataclasses.replace(cfg.env, n_envs=2, max_dota_time=30.0),
            ppo=dataclasses.replace(cfg.ppo, rollout_len=4),
        )
        policy = make_policy(cfg.model, cfg.obs, cfg.actions)
        params = init_params(policy, _jax.random.PRNGKey(0))
        out = []
        pool = VecActorPool(cfg, policy, params, seed=0, rollout_sink=out.extend)
        pool.run(4, refresh_every=0)
        assert out
        meta, arrays = out[0]
        valid, caches = arrays["carry0"]
        assert valid.shape == (cfg.model.context_window,)
        assert caches[0][0].shape == (
            cfg.model.context_window, cfg.model.hidden_dim
        )

    def test_scalar_pool_rejects_transformer(self):
        from dotaclient_tpu.actor.runtime import ActorPool

        cfg = tf_config()
        with pytest.raises(NotImplementedError):
            ActorPool(cfg, None, None)
