"""Pipeline-complete checkpointing: kill-and-resume equivalence.

SURVEY.md §5.4 / VERDICT round 1 item 9: a restore must resume the EXACT
pipeline — params, optimizer state, counters, the HBM trajectory ring with
its cursors, and the device actor's full state (sim worlds, recurrent
carries, PRNG, episode accumulators). The pin: train A for k steps,
checkpoint, keep training A; build B from the checkpoint alone; A and B must
produce identical subsequent metrics.
"""

import dataclasses

import jax
import numpy as np
import pytest

from dotaclient_tpu.config import default_config
from dotaclient_tpu.train.learner import Learner


def small_config():
    cfg = default_config()
    return dataclasses.replace(
        cfg,
        env=dataclasses.replace(cfg.env, n_envs=4, max_dota_time=30.0),
        ppo=dataclasses.replace(cfg.ppo, rollout_len=8, batch_rollouts=8),
        buffer=dataclasses.replace(cfg.buffer, capacity_rollouts=32, min_fill=8),
        log_every=1,
        checkpoint_every=1_000_000,  # only explicit/force saves
    )


class TestKillAndResume:
    @pytest.mark.slow   # tier-1 duration audit (ISSUE 6): ~114s on the reference container
    def test_resume_reproduces_metrics(self, tmp_path):
        cfg = small_config()
        ckdir = str(tmp_path / "ck")

        # A: train, snapshot the full pipeline at step 3, keep training
        # (A itself has no checkpoint dir, so step 3 stays the latest)
        from dotaclient_tpu.utils.checkpoint import CheckpointManager

        a = Learner(cfg, seed=3, actor="device")
        a.train(3)
        mgr = CheckpointManager(ckdir)
        mgr.save(a.state, cfg, force=True, pipeline=a._pipeline_state())
        mgr.wait()
        a.train(3)
        a_metrics = dict(a._last_metrics)

        # B: a fresh process-equivalent, restored from the checkpoint alone
        b = Learner(
            cfg, checkpoint_dir=ckdir, restore=True, seed=999,  # seed unused
            actor="device",
        )
        assert b._host_step == 3
        b.train(3)
        b_metrics = dict(b._last_metrics)

        for k in ("loss", "policy_loss", "value_loss", "entropy", "reward_mean"):
            assert a_metrics[k] == pytest.approx(b_metrics[k], rel=1e-5), (
                f"metric {k} diverged after resume: {a_metrics[k]} vs {b_metrics[k]}"
            )

    @pytest.mark.slow   # tier-1 duration audit (ISSUE 6): ~43s on the reference container
    def test_fused_mode_resume_reproduces_metrics(self, tmp_path):
        """Fused mode has no buffer; its pipeline state is the train state
        plus the device actor's full state — resume must still reproduce
        identical subsequent metrics."""
        cfg = small_config()
        ckdir = str(tmp_path / "ck")
        from dotaclient_tpu.utils.checkpoint import CheckpointManager

        a = Learner(cfg, seed=5, actor="fused")
        a.train(3)
        mgr = CheckpointManager(ckdir)
        mgr.save(a.state, cfg, force=True, pipeline=a._pipeline_state())
        mgr.wait()
        a.train(3)
        a_metrics = dict(a._last_metrics)

        b = Learner(
            cfg, checkpoint_dir=ckdir, restore=True, seed=999, actor="fused"
        )
        assert b._host_step == 3
        b.train(3)
        b_metrics = dict(b._last_metrics)
        for k in ("loss", "policy_loss", "value_loss", "entropy", "reward_mean"):
            assert a_metrics[k] == pytest.approx(b_metrics[k], rel=1e-5), (
                f"metric {k} diverged after fused resume: "
                f"{a_metrics[k]} vs {b_metrics[k]}"
            )

    def test_best_checkpoint_tracks_peak_win_rate(self, tmp_path):
        """The best/ rotation captures the peak windowed win-rate and does
        not overwrite it when the metric later falls (the 0.714-peak →
        0.16-final trajectory in BASELINE.md is the motivating case)."""
        cfg = dataclasses.replace(
            small_config(),
            # tiny noise guard so the short CPU run qualifies
            checkpoint_best_min_episodes=1,
            env=dataclasses.replace(
                small_config().env, n_envs=4, max_dota_time=4.0
            ),
        )
        ckdir = str(tmp_path / "ck")
        lrn = Learner(cfg, checkpoint_dir=ckdir, seed=3, actor="fused")
        assert lrn._best_dir is not None
        # lazy: no stray empty best/ tree before a qualifying save
        assert lrn.ckpt_best is None
        lrn.train(6)
        # Force a qualifying peak through the real code path, then a drop.
        lrn._best_win = -1.0
        lrn.device_actor._recent = {
            "episodes": 10.0, "wins": 9.0, "ep_return_sum": 0.0,
        }
        stats = lrn.device_actor.stats()
        assert stats["win_rate_recent"] == pytest.approx(0.9)
        lrn._maybe_save_best(stats)          # the real hook
        assert lrn._best_win == pytest.approx(0.9)
        best_step_at_peak = lrn.ckpt_best.latest_step()
        assert best_step_at_peak is not None
        lrn.train(3)   # real windows are ~0 wins: must NOT displace best
        assert lrn._best_win == pytest.approx(0.9)
        assert lrn.ckpt_best.latest_step() == best_step_at_peak
        # The best checkpoint restores as an init_from source.
        lrn.ckpt_best.wait()
        b = Learner(cfg, init_from=str(tmp_path / "ck" / "best"),
                    actor="fused")
        assert b._init_from_step == best_step_at_peak
        # A resumed run must inherit the best-so-far marker (persisted in
        # best_meta.json) — NOT reset to -1 and let a collapsed window
        # overwrite the captured peak.
        resumed = Learner(cfg, checkpoint_dir=ckdir, restore=True,
                          actor="fused")
        assert resumed._best_win == pytest.approx(0.9)

    def test_restore_with_toggled_kl_target_fails_loudly(self, tmp_path):
        """kl_target changes the opt_state layout (injected lr leaf); a
        --restore across that toggle must raise the translated error, not
        orbax's raw tree diff."""
        cfg = small_config()
        ckdir = str(tmp_path / "ck")
        lrn = Learner(cfg, checkpoint_dir=ckdir, actor="fused")
        lrn.train(1)
        cfg2 = dataclasses.replace(
            cfg, ppo=dataclasses.replace(cfg.ppo, kl_target=1e-3)
        )
        with pytest.raises(ValueError, match="OPTIMIZER layout"):
            Learner(cfg2, checkpoint_dir=ckdir, restore=True, actor="fused")

    def test_best_checkpoint_disabled_by_zero(self, tmp_path):
        cfg = dataclasses.replace(
            small_config(), checkpoint_best_min_episodes=0
        )
        lrn = Learner(cfg, checkpoint_dir=str(tmp_path / "ck"), actor="fused")
        assert lrn.ckpt_best is None and lrn._best_dir is None

    @pytest.mark.slow   # tier-1 duration audit (ISSUE 6): ~103s on the reference container
    def test_restore_without_pipeline_still_works(self, tmp_path):
        """Weights-only checkpoints (no pipeline entry) restore cleanly."""
        cfg = small_config()
        ckdir = str(tmp_path / "ck")
        a = Learner(cfg, seed=0, actor="device")
        a.train(2)
        from dotaclient_tpu.utils.checkpoint import CheckpointManager

        mgr = CheckpointManager(ckdir)
        mgr.save(a.state, cfg, force=True)  # weights-only, no pipeline
        mgr.wait()
        b = Learner(cfg, checkpoint_dir=ckdir, restore=True, actor="device")
        assert b._host_step == 2
        stats = b.train(2)
        assert stats["optimizer_steps"] >= 2

    def test_buffer_contents_survive(self, tmp_path):
        """In-flight experience is not lost across a restore."""
        from dotaclient_tpu.buffer import TrajectoryBuffer
        from dotaclient_tpu.parallel import make_mesh

        cfg = small_config()
        a = Learner(cfg, seed=1, actor="device")
        for _ in range(2):  # 2 × n_envs rollouts ≥ min_fill
            chunk, _ = a.device_actor.collect(a.state.params)
            a.buffer.add_device(chunk, 0)
        assert a.buffer.size >= cfg.buffer.min_fill
        state = a.buffer.state_dict()

        mesh = make_mesh(cfg.mesh)
        fresh = TrajectoryBuffer(cfg, mesh)
        assert fresh.size == 0
        fresh.load_state_dict(jax.tree.map(np.asarray, state))
        assert fresh.size == a.buffer.size
        batch = fresh.take(batch_size=8)
        assert batch is not None
        np.testing.assert_array_equal(
            np.asarray(batch["valid"]), np.ones_like(np.asarray(batch["valid"]))
        )

    @pytest.mark.slow   # tier-1 duration audit (ISSUE 6): ~79s on the reference container
    def test_aligned_periodic_and_final_save(self, tmp_path):
        """A run whose length is a multiple of checkpoint_every must not
        crash at the end-of-run pipeline save (the periodic save already
        wrote that step; the pipeline save supersedes it in place)."""
        cfg = dataclasses.replace(small_config(), checkpoint_every=2)
        ckdir = str(tmp_path / "ck")
        a = Learner(cfg, checkpoint_dir=ckdir, seed=4, actor="device")
        a.train(2)  # periodic save at step 2, then forced pipeline save at 2
        a.ckpt.wait()

        # the surviving step-2 checkpoint is the pipeline-complete one
        b = Learner(cfg, checkpoint_dir=ckdir, restore=True, actor="device")
        assert b._host_step == 2
        restored, reason = b.ckpt.restore_pipeline(b._pipeline_state())
        assert restored is not None and reason == ""

    def test_weights_only_resave_of_existing_step_supersedes(self, tmp_path):
        """Re-saving an existing step replaces it (never raises
        StepAlreadyExistsError): a divergence-rollback run legitimately
        re-reaches old step numbers with NEW content (ISSUE 6), so the
        newest save always supersedes."""
        cfg = small_config()
        ckdir = str(tmp_path / "ck")
        from dotaclient_tpu.utils.checkpoint import CheckpointManager

        a = Learner(cfg, seed=5, actor="device")
        a.train(1)
        mgr = CheckpointManager(ckdir)
        assert mgr.save(a.state, cfg, force=True)
        mgr.wait()
        assert mgr.save(a.state, cfg, force=True)
        mgr.wait()
        assert mgr.latest_step() == int(np.asarray(a.state.step))
        # the replacement restores clean (fresh integrity manifest too)
        params, step = mgr.restore_weights()
        assert step == int(np.asarray(a.state.step))

    @pytest.mark.slow   # tier-1 duration audit (ISSUE 6): ~50s on the reference container
    def test_cross_config_restore_degrades_to_weights_only(self, tmp_path):
        """Restoring a checkpoint into a DIFFERENT game shape (1v1 pipeline
        state into a 5v5 learner — the curriculum-transfer path) must keep
        the weights but reject the wrong-shaped pipeline leaves; orbax's
        StandardRestore does not enforce template shapes on its own."""
        cfg = small_config()
        ckdir = str(tmp_path / "ck")
        a = Learner(cfg, checkpoint_dir=ckdir, seed=6, actor="fused")
        a.train(1)
        a.ckpt.wait()

        big = dataclasses.replace(
            cfg, env=dataclasses.replace(cfg.env, team_size=5)
        )
        b = Learner(big, checkpoint_dir=ckdir, restore=True, actor="fused")
        assert b._host_step == 1              # weights/counters restored
        L = b.device_actor.n_lanes
        assert L == cfg.env.n_envs * 5
        # actor state must be the fresh 5v5 shapes, not the 1v1 leaves
        assert b.device_actor.state.carry[0].shape[0] == L
        b.train(1)                            # and the fused step must run

    @pytest.mark.slow   # tier-1 duration audit (ISSUE 6): ~57s on the reference container
    def test_init_from_seeds_weights_fresh_run(self, tmp_path):
        """init_from seeds params from a SOURCE dir, starts counters and
        optimizer fresh, never writes to the source, and is mutually
        exclusive with restore."""
        cfg = small_config()
        src_dir = str(tmp_path / "src")
        a = Learner(cfg, checkpoint_dir=src_dir, seed=7, actor="fused")
        a.train(1)
        a.ckpt.wait()
        src_steps = set(a.ckpt._mgr.all_steps())

        big = dataclasses.replace(
            cfg, env=dataclasses.replace(cfg.env, team_size=5)
        )
        dst_dir = str(tmp_path / "dst")
        b = Learner(big, checkpoint_dir=dst_dir, init_from=src_dir,
                    actor="fused")
        assert b._host_step == 0 and b._init_from_step == 1
        # seeded params == source params, optimizer moments fresh
        for la, lb in zip(
            jax.tree.leaves(a.state.params), jax.tree.leaves(b.state.params)
        ):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
        b.train(1)
        b.ckpt.wait()
        # destination got b's own checkpoint; source untouched
        assert b.ckpt.latest_step() == 1
        from dotaclient_tpu.utils.checkpoint import CheckpointManager

        src_check = CheckpointManager(src_dir)
        assert set(src_check._mgr.all_steps()) == src_steps

        with pytest.raises(ValueError, match="mutually exclusive"):
            Learner(big, checkpoint_dir=dst_dir, restore=True,
                    init_from=src_dir, actor="fused")

    def test_init_from_rejects_same_dir_and_wrong_core(self, tmp_path):
        cfg = small_config()
        src_dir = str(tmp_path / "src")
        a = Learner(cfg, checkpoint_dir=src_dir, seed=8, actor="fused")
        a.train(1)
        a.ckpt.wait()

        with pytest.raises(ValueError, match="SEPARATE source"):
            Learner(cfg, checkpoint_dir=src_dir, init_from=src_dir,
                    actor="fused")

        other_core = dataclasses.replace(
            cfg, model=dataclasses.replace(
                cfg.model, core="transformer", n_layers=1, context_window=4
            ),
        )
        with pytest.raises(ValueError, match="init_from checkpoint"):
            Learner(other_core, init_from=src_dir, actor="fused")
