"""Vectorized sim / featurizer / actor-pool tests.

The scalar ``LaneSim`` is the semantic reference; the vectorized sim shares
its rule constants by import and is held to the same *behavioral* outcomes
(statistical parity — resolution order differs by design, see module
docstring of ``envs.vec_lane_sim``).
"""

import dataclasses

import numpy as np
import pytest

from dotaclient_tpu.config import default_config
from dotaclient_tpu.envs import lane_sim
from dotaclient_tpu.envs.vec_lane_sim import VecLaneSim, VecSimSpec
from dotaclient_tpu.features.vec_featurizer import VecFeaturizer, VecRewards
from dotaclient_tpu.features import featurizer as F
from dotaclient_tpu.protos import dota_pb2 as pb


def make_sim(n=4, team_size=1, opp=pb.CONTROL_SCRIPTED_EASY, seed=0, **kw):
    spec = VecSimSpec(n_games=n, team_size=team_size, max_units=32, **kw)
    P = spec.n_players
    hero = np.ones((n, P), np.int32)
    ctrl = np.full((n, P), pb.CONTROL_AGENT, np.int32)
    ctrl[:, team_size:] = opp
    return VecLaneSim(spec, hero, ctrl, seed=seed)


def noop_actions(sim):
    N, P = sim.spec.n_games, sim.spec.n_players
    a = {
        k: np.zeros((N, P), np.int64 if k == "target_slot" else np.int32)
        for k in ("type", "move_x", "move_y", "target_slot", "ability")
    }
    a["type"][:] = -1
    return a


class TestVecSimBasics:
    def test_initial_layout(self):
        sim = make_sim(n=3)
        # slot 0/1 heroes, 2/3 towers, creeps after
        assert (sim.unit_type[:, 0] == pb.UNIT_HERO).all()
        assert (sim.unit_type[:, 1] == pb.UNIT_HERO).all()
        assert (sim.unit_type[:, 2] == pb.UNIT_TOWER).all()
        assert (sim.unit_type[:, 3] == pb.UNIT_TOWER).all()
        assert (sim.team[:, 0] == lane_sim.TEAM_RADIANT).all()
        assert (sim.team[:, 1] == lane_sim.TEAM_DIRE).all()
        # one wave spawned per team
        n_creeps = (sim.unit_type == pb.UNIT_LANE_CREEP).sum(1)
        assert (n_creeps == 2 * lane_sim.CREEPS_PER_WAVE).all()

    def test_waves_spawn_over_time(self):
        sim = make_sim(n=2)
        a = noop_actions(sim)
        creeps0 = sim.alive[(sim.unit_type == pb.UNIT_LANE_CREEP)].sum()
        for _ in range(int(35 / 0.2)):
            sim.step(a)
        # creeps fight each other; after the second wave the total spawned
        # count must exceed one wave per team
        assert sim.dota_time[0] > lane_sim.CREEP_WAVE_PERIOD
        assert creeps0 > 0

    def test_scripted_hard_beats_easy(self):
        sim = make_sim(n=16, opp=pb.CONTROL_SCRIPTED_HARD, max_dota_time=300.0)
        # make Radiant scripted-easy instead of agent
        sim.control_modes[:, 0] = pb.CONTROL_SCRIPTED_EASY
        a = noop_actions(sim)
        for _ in range(1600):
            if sim.done.all():
                break
            sim.step(a)
        assert sim.done.all()
        hard_wins = (sim.winning_team == lane_sim.TEAM_DIRE).sum()
        # win margin is tower-HP-at-timeout noisy; kills are the robust
        # dominance signal (hard bot kites/retreats, easy bot feeds)
        assert hard_wins >= 10, f"hard bot won only {hard_wins}/16"
        assert sim.kills[:, 1].sum() > 3 * sim.kills[:, 0].sum()

    def test_deterministic_given_seed(self):
        s1 = make_sim(n=2, opp=pb.CONTROL_SCRIPTED_HARD, seed=7)
        s2 = make_sim(n=2, opp=pb.CONTROL_SCRIPTED_HARD, seed=7)
        s1.control_modes[:, 0] = pb.CONTROL_SCRIPTED_EASY
        s2.control_modes[:, 0] = pb.CONTROL_SCRIPTED_EASY
        a = noop_actions(s1)
        for _ in range(200):
            s1.step(a)
            s2.step(a)
        np.testing.assert_array_equal(s1.health, s2.health)
        np.testing.assert_array_equal(s1.gold, s2.gold)
        np.testing.assert_array_equal(s1.x, s2.x)

    def test_attack_deals_damage_and_lasthit_gold(self):
        sim = make_sim(n=1)
        # teleport radiant hero next to a dire creep, weaken the creep
        dire_creeps = np.nonzero(
            (sim.unit_type[0] == pb.UNIT_LANE_CREEP)
            & (sim.team[0] == lane_sim.TEAM_DIRE)
        )[0]
        c = dire_creeps[0]
        sim.x[0, 0] = sim.x[0, c]
        sim.y[0, 0] = sim.y[0, c]
        sim.health[0, c] = 1.0
        a = noop_actions(sim)
        a["type"][0, 0] = pb.ACTION_ATTACK_UNIT
        a["target_slot"][0, 0] = c
        gold0 = sim.gold[0, 0]
        lh0 = sim.last_hits[0, 0]
        sim.step(a)
        assert not sim.alive[0, c]
        assert sim.last_hits[0, 0] == lh0 + 1
        assert sim.gold[0, 0] >= gold0 + lane_sim.GOLD_PER_LASTHIT

    def test_deny_own_low_creep(self):
        sim = make_sim(n=1)
        rad_creeps = np.nonzero(
            (sim.unit_type[0] == pb.UNIT_LANE_CREEP)
            & (sim.team[0] == lane_sim.TEAM_RADIANT)
        )[0]
        c = rad_creeps[0]
        sim.x[0, 0] = sim.x[0, c]
        sim.y[0, 0] = sim.y[0, c]
        sim.health[0, c] = 1.0  # < 50% -> deniable
        a = noop_actions(sim)
        a["type"][0, 0] = pb.ACTION_ATTACK_UNIT
        a["target_slot"][0, 0] = c
        gold0 = sim.gold[0, 0]
        sim.step(a)
        assert not sim.alive[0, c]
        assert sim.denies[0, 0] == 1
        # denies give no gold (passive tick may add a hair)
        assert sim.gold[0, 0] < gold0 + lane_sim.GOLD_PER_LASTHIT

    def test_deny_refused_on_healthy_creep(self):
        sim = make_sim(n=1)
        rad_creeps = np.nonzero(
            (sim.unit_type[0] == pb.UNIT_LANE_CREEP)
            & (sim.team[0] == lane_sim.TEAM_RADIANT)
        )[0]
        c = rad_creeps[0]
        sim.x[0, 0] = sim.x[0, c]
        sim.y[0, 0] = sim.y[0, c]
        hp0 = sim.health[0, c]
        a = noop_actions(sim)
        a["type"][0, 0] = pb.ACTION_ATTACK_UNIT
        a["target_slot"][0, 0] = c
        sim.step(a)
        # healthy own creep cannot be attacked: no damage from the hero
        assert sim.health[0, c] >= hp0 - 25.0  # creep-vs-creep chip at most

    def test_nuke_cast(self):
        sim = make_sim(n=1)
        a = noop_actions(sim)
        # move enemy hero into nuke range
        sim.x[0, 1] = sim.x[0, 0] + 100.0
        sim.y[0, 1] = sim.y[0, 0]
        hp0 = sim.health[0, 1]
        mana0 = sim.mana[0, 0]
        a["type"][0, 0] = pb.ACTION_CAST
        a["target_slot"][0, 0] = 1
        a["ability"][0, 0] = lane_sim.NUKE_SLOT
        sim.step(a)
        assert sim.health[0, 1] < hp0
        assert sim.mana[0, 0] <= mana0 - lane_sim.NUKE_MANA + 1.0
        assert sim.ability_cd[0, 0] > 0.0

    def test_hero_kill_credit_and_respawn(self):
        sim = make_sim(n=1, max_dota_time=60.0)
        sim.x[0, 1] = sim.x[0, 0] + 100.0
        sim.health[0, 1] = 1.0
        a = noop_actions(sim)
        a["type"][0, 0] = pb.ACTION_ATTACK_UNIT
        a["target_slot"][0, 0] = 1
        k0, g0 = sim.kills[0, 0], sim.gold[0, 0]
        sim.step(a)
        assert not sim.alive[0, 1]
        assert sim.kills[0, 0] == k0 + 1
        assert sim.deaths[0, 1] == 1
        assert sim.gold[0, 0] >= g0 + lane_sim.GOLD_PER_HERO_KILL
        assert sim.respawn_at[0, 1] > sim.dota_time[0]
        # run clock until respawn
        b = noop_actions(sim)
        for _ in range(100):
            if sim.alive[0, 1]:
                break
            sim.step(b)
        assert sim.alive[0, 1]
        assert sim.health[0, 1] == sim.health_max[0, 1]

    def test_tower_kill_ends_game(self):
        sim = make_sim(n=2)
        t = sim.tower_slot(lane_sim.TEAM_DIRE)
        sim.health[0, t] = 1.0
        sim.x[0, 0] = sim.x[0, t] + 100.0
        sim.y[0, 0] = 0.0
        a = noop_actions(sim)
        a["type"][0, 0] = pb.ACTION_ATTACK_UNIT
        a["target_slot"][0, 0] = t
        sim.step(a)
        assert sim.done[0]
        assert sim.winning_team[0] == lane_sim.TEAM_RADIANT
        assert not sim.done[1]  # other game unaffected

    def test_timeout_adjudication(self):
        sim = make_sim(n=1, max_dota_time=1.0)
        t = sim.tower_slot(lane_sim.TEAM_DIRE)
        sim.health[0, t] -= 500.0
        a = noop_actions(sim)
        for _ in range(10):
            sim.step(a)
        assert sim.done[0]
        assert sim.winning_team[0] == lane_sim.TEAM_RADIANT

    def test_reset_rows(self):
        sim = make_sim(n=3)
        a = noop_actions(sim)
        for _ in range(50):
            sim.step(a)
        sim.reset(np.array([1]))
        assert sim.dota_time[1] == 0.0
        assert sim.dota_time[0] > 0.0
        assert sim.alive[1, :2].all()
        assert (sim.gold[1, :2] == 0.0).all()

    def test_tower_attacks_diving_hero_despite_far_creeps(self):
        """Regression: tower target choice filters to in-range enemies FIRST;
        an out-of-range creep must not shadow an in-range hero."""
        sim = make_sim(n=1)
        t = sim.tower_slot(lane_sim.TEAM_DIRE)
        # radiant hero dives the dire tower
        sim.x[0, 0] = sim.x[0, t] - 300.0
        sim.y[0, 0] = 0.0
        # push all radiant creeps far out of the tower's range
        rad_creeps = (sim.unit_type[0] == pb.UNIT_LANE_CREEP) & (
            sim.team[0] == lane_sim.TEAM_RADIANT
        )
        sim.x[0, rad_creeps] = -lane_sim.LANE_HALF_LENGTH
        # and dire creeps likewise (so nothing else distracts/kills)
        dire_creeps = (sim.unit_type[0] == pb.UNIT_LANE_CREEP) & (
            sim.team[0] == lane_sim.TEAM_DIRE
        )
        sim.x[0, dire_creeps] = -lane_sim.LANE_HALF_LENGTH
        hp0 = sim.health[0, 0]
        sim.step(noop_actions(sim))
        assert sim.health[0, 0] < hp0, "tower ignored the diving hero"

    def test_xp_no_double_levelup_on_simultaneous_kills(self):
        """Regression: duplicate (game, player) pairs in one XP grant must
        not double-apply level-up stat gains."""
        sim = make_sim(n=1)
        dmg0 = sim.damage[0, 0]
        sim._grant_xp_slots(
            np.array([0, 0]), np.array([0, 0]),
            np.array([lane_sim.XP_PER_LEVEL / 2] * 2, np.float32),
        )
        # total xp == one level threshold -> exactly one level gained
        assert sim.level[0, 0] == 2
        assert sim.damage[0, 0] == pytest.approx(dmg0 + 4.0)

    def test_xp_levels_closed_form(self):
        sim = make_sim(n=1)
        sim._grant_xp_slots(np.array([0]), np.array([0]),
                            np.array([lane_sim.XP_PER_LEVEL], np.float32))
        assert sim.level[0, 0] == 2
        sim._grant_xp_slots(np.array([0]), np.array([0]),
                            np.array([lane_sim.XP_PER_LEVEL * 5], np.float32))
        # xp = 220*6 -> level 7
        assert sim.level[0, 0] == 7
        hp_gain = (sim.health_max[0, 0]
                   - lane_sim.HERO_STATS[1][0])
        assert hp_gain == pytest.approx(40.0 * 6)


class TestVecFeaturizer:
    def test_shapes_and_masks(self):
        cfg = default_config()
        sim = make_sim(n=3)
        feat = VecFeaturizer(sim, cfg.obs, cfg.actions, [0])
        obs = feat.featurize_all()
        L, U = 3, cfg.obs.max_units
        assert obs["units"].shape == (L, U, cfg.obs.unit_features)
        assert obs["unit_mask"].shape == (L, U)
        assert obs["mask_action_type"].shape == (L, cfg.actions.n_action_types)
        assert obs["hero_id"].shape == (L,)
        # noop always legal; self never attackable
        assert obs["mask_action_type"][:, pb.ACTION_NOOP].all()
        assert not obs["mask_target_unit"][:, 0].any()
        # slot 0 is self: is_self feature set
        self_col = F.UNIT_FEATURES.index("is_self")
        assert (obs["units"][:, 0, self_col] == 1.0).all()
        assert (obs["units"][:, 1:, self_col] == 0.0).all()

    def test_semantics_match_scalar_featurizer(self):
        """Same game state featurized through the proto path and the vector
        path must agree on per-unit semantic content (matched by handle) and
        on globals/action masks."""
        cfg = default_config()
        sim = make_sim(n=2, opp=pb.CONTROL_SCRIPTED_HARD, seed=3)
        a = noop_actions(sim)
        for _ in range(30):
            sim.step(a)
        feat = VecFeaturizer(sim, cfg.obs, cfg.actions, [0])
        vec_obs = feat.featurize_all()
        g = 0
        ws = sim.world_state(g, lane_sim.TEAM_RADIANT)
        ref = F.featurize(ws, 0, cfg.obs, cfg.actions)

        # map: vec obs slot -> sim slot -> proto handle (slot+1)
        perm = feat.perm[0]
        vec_by_handle = {}
        for obs_slot in range(cfg.obs.max_units):
            if vec_obs["unit_mask"][g, obs_slot]:
                vec_by_handle[int(perm[obs_slot]) + 1] = obs_slot
        ref_by_handle = {
            int(ref.unit_handles[s]): s
            for s in range(cfg.obs.max_units)
            if ref.unit_mask[s]
        }
        assert set(vec_by_handle) == set(ref_by_handle)
        for handle, vs in vec_by_handle.items():
            rs = ref_by_handle[handle]
            np.testing.assert_allclose(
                vec_obs["units"][g, vs], ref.units[rs], rtol=1e-5, atol=1e-6,
                err_msg=f"unit features differ for handle {handle}",
            )
            assert vec_obs["mask_target_unit"][g, vs] == ref.mask_target_unit[rs]
            assert vec_obs["mask_cast_target"][g, vs] == ref.mask_cast_target[rs]
        np.testing.assert_allclose(
            vec_obs["globals"][g], ref.globals, rtol=1e-5, atol=1e-6
        )
        np.testing.assert_array_equal(
            vec_obs["mask_action_type"][g], ref.mask_action_type
        )
        np.testing.assert_array_equal(
            vec_obs["mask_ability"][g], ref.mask_ability
        )

    def test_rewards_match_scalar_reward(self):
        """Vector rewards over one interval == scalar shaped_reward from the
        exported worldstates."""
        from dotaclient_tpu.features.reward import shaped_reward

        cfg = default_config()
        sim = make_sim(n=2, opp=pb.CONTROL_SCRIPTED_HARD, seed=5)
        a = noop_actions(sim)
        for _ in range(10):
            sim.step(a)
        rewards = VecRewards(sim, [0])
        ws_prev = [sim.world_state(g, lane_sim.TEAM_RADIANT) for g in range(2)]
        for _ in range(5):
            sim.step(a)
        r_vec = rewards.compute()
        for g in range(2):
            ws_cur = sim.world_state(g, lane_sim.TEAM_RADIANT)
            r_ref, _ = shaped_reward(ws_prev[g], ws_cur, 0)
            assert r_vec[g] == pytest.approx(r_ref, rel=1e-4, abs=1e-5), (
                f"game {g}: vec {r_vec[g]} != scalar {r_ref}"
            )

    def test_actions_to_sim_roundtrip(self):
        cfg = default_config()
        sim = make_sim(n=2, team_size=1)
        feat = VecFeaturizer(sim, cfg.obs, cfg.actions, [0])
        packed = np.zeros((2, 5), np.int32)
        packed[0] = [pb.ACTION_ATTACK_UNIT, 0, 0, 3, 0]  # obs slot 3
        packed[1] = [pb.ACTION_MOVE, 8, 2, 0, 0]
        sim_a = feat.actions_to_sim(packed)
        assert sim_a["type"][0, 0] == pb.ACTION_ATTACK_UNIT
        assert sim_a["target_slot"][0, 0] == feat.perm[0, 3]
        assert sim_a["type"][1, 0] == pb.ACTION_MOVE
        assert sim_a["move_x"][1, 0] == 8
        # scripted player untouched
        assert sim_a["type"][0, 1] == -1


class TestVecActorPool:
    def _pool(self, n_envs=4, opponent="scripted_easy", team_size=1, **ppo_kw):
        import jax
        from dotaclient_tpu.models import init_params, make_policy
        from dotaclient_tpu.actor.vec_runtime import VecActorPool

        cfg = default_config()
        cfg = dataclasses.replace(
            cfg,
            env=dataclasses.replace(
                cfg.env, n_envs=n_envs, opponent=opponent,
                team_size=team_size, max_dota_time=30.0,
            ),
            ppo=dataclasses.replace(cfg.ppo, rollout_len=8, **ppo_kw),
        )
        policy = make_policy(cfg.model, cfg.obs, cfg.actions)
        params = init_params(policy, jax.random.PRNGKey(0))
        out = []
        pool = VecActorPool(cfg, policy, params, seed=0, rollout_sink=out.extend)
        return cfg, pool, out

    def test_chunks_have_contract_shapes(self):
        cfg, pool, out = self._pool()
        pool.run(8, refresh_every=0)
        assert out, "no rollouts after T steps"
        meta, arrays = out[0]
        T = cfg.ppo.rollout_len
        assert arrays["obs"]["units"].shape == (
            T + 1, cfg.obs.max_units, cfg.obs.unit_features
        )
        assert arrays["rewards"].shape == (T,)
        assert arrays["valid"].shape == (T,)
        assert arrays["carry0"][0].shape == (cfg.model.hidden_dim,)
        assert meta["length"] > 0
        assert set(arrays["actions"]) == set(cfg.actions.head_sizes)

    def test_chunks_feed_train_step(self):
        import jax
        from dotaclient_tpu.parallel import make_mesh
        from dotaclient_tpu.buffer import TrajectoryBuffer
        from dotaclient_tpu.train.ppo import init_train_state, make_train_step
        from dotaclient_tpu.models import init_params, make_policy

        cfg, pool, out = self._pool(n_envs=8, batch_rollouts=8)
        cfg = dataclasses.replace(
            cfg,
            buffer=dataclasses.replace(cfg.buffer, capacity_rollouts=32, min_fill=8),
        )
        mesh = make_mesh(cfg.mesh)
        buffer = TrajectoryBuffer(cfg, mesh)
        state = init_train_state(pool.params, cfg.ppo)
        step = make_train_step(pool.policy, cfg, mesh)
        pool.run(16, refresh_every=0)
        assert buffer.add(out, current_version=0) > 0
        batch = buffer.take(current_version=0)
        assert batch is not None
        state2, metrics = step(state, batch)
        assert np.isfinite(float(metrics["loss"]))

    def test_episode_boundary_resets(self):
        cfg, pool, out = self._pool(n_envs=2)
        # run past the 30s timeout -> episodes end and reset
        pool.run(int(35 / 0.2), refresh_every=0)
        assert pool.episodes_done >= 2
        assert pool.stats()["episodes_done"] >= 2
        # done-terminated chunks exist and are marked
        done_chunks = [
            (m, a) for m, a in out if a["dones"][: m["length"]].any()
        ]
        assert done_chunks
        m, a = done_chunks[0]
        # after the done step, padding: valid 0
        last = int(np.nonzero(a["dones"])[0][0])
        assert a["valid"][last] == 1.0
        if last + 1 < cfg.ppo.rollout_len:
            assert (a["valid"][last + 1:] == 0.0).all()

    def test_no_reset_reward_spike(self):
        """Regression: the terminal→fresh-state delta at episode reset must
        not be credited as reward to the new episode's first step."""
        cfg, pool, out = self._pool(n_envs=2)
        # enrich the hero so the reset delta would be large if mis-credited
        pool.sim.gold[:, 0] = 2000.0
        pool.sim.xp[:, 0] = 2000.0
        pool.rewards.snapshot()
        steps = int(35 / 0.2)
        worst = 0.0
        for _ in range(steps):
            pool.step()
            worst = min(worst, float(pool._rew_buf.min()))
        assert pool.episodes_done >= 2
        # a legitimate single-step reward is bounded (win term ±5 plus small
        # shaping); the spurious reset delta would be ≈ -12 or worse
        assert worst > -9.0, f"reset delta leaked into rewards: {worst}"

    def test_selfplay_both_teams_ship(self):
        cfg, pool, out = self._pool(opponent="selfplay")
        assert pool.n_lanes == cfg.env.n_envs * 2
        pool.run(8, refresh_every=0)
        assert len(out) >= pool.n_lanes

    def test_5v5_lanes(self):
        cfg, pool, out = self._pool(n_envs=2, opponent="selfplay", team_size=5)
        assert pool.n_lanes == 2 * 10
        pool.run(4, refresh_every=0)
        assert pool.env_steps == 4 * 20
