"""Shared CLI override parser (utils/overrides.py) — both entrypoints'
``--ppo/--reward/--league`` flags ride on it."""

import pytest

from dotaclient_tpu.config import LeagueConfig, PPOConfig, RewardConfig
from dotaclient_tpu.utils.overrides import parse_dataclass_overrides


class TestParseOverrides:
    def test_types_follow_field_declarations(self):
        out = parse_dataclass_overrides(
            PPOConfig,
            "learning_rate=1e-5,rollout_len=8,adv_norm=none,anchor_kl_coef=0.05",
            "--ppo",
        )
        assert out == {
            "learning_rate": 1e-5,
            "rollout_len": 8,
            "adv_norm": "none",
            "anchor_kl_coef": 0.05,
        }
        assert isinstance(out["rollout_len"], int)

    def test_reward_and_league_fields(self):
        assert parse_dataclass_overrides(RewardConfig, "win=25", "--reward") == {
            "win": 25.0
        }
        out = parse_dataclass_overrides(
            LeagueConfig, "anchor_prob=0.25,snapshot_every=200", "--league"
        )
        assert out == {"anchor_prob": 0.25, "snapshot_every": 200}

    def test_bool_fields_accept_words_and_digits(self):
        for text, want in (
            ("enabled=true", True),
            ("enabled=1", True),
            ("enabled=false", False),
            ("enabled=0", False),
        ):
            out = parse_dataclass_overrides(LeagueConfig, text, "--league")
            assert out == {"enabled": want}
            assert isinstance(out["enabled"], bool)
        with pytest.raises(ValueError, match="bad bool"):
            parse_dataclass_overrides(LeagueConfig, "enabled=maybe", "--league")

    def test_unknown_field_raises_with_flag_name(self):
        with pytest.raises(ValueError, match=r"--ppo.*bogus"):
            parse_dataclass_overrides(PPOConfig, "bogus=1", "--ppo")

    def test_bad_value_raises(self):
        with pytest.raises(ValueError, match="bad int"):
            parse_dataclass_overrides(PPOConfig, "rollout_len=abc", "--ppo")

    def test_adv_norm_enum_checked_at_parse_time(self):
        with pytest.raises(ValueError, match="adv_norm"):
            parse_dataclass_overrides(PPOConfig, "adv_norm=bogus", "--ppo")
