"""Pallas fused-LSTM kernel: numerics parity with the reference scan
(interpreter mode on the CPU test mesh; the compiled-TPU parity run lives in
the BASELINE.md bench) and gradient parity through the recompute VJP."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dotaclient_tpu.ops.pallas import (
    HAVE_PALLAS,
    lstm_sequence,
    lstm_sequence_reference,
)

pytestmark = pytest.mark.skipif(not HAVE_PALLAS, reason="pallas unavailable")


def inputs(B=8, T=6, D=32, H=64, seed=0, reset_p=0.2):
    rng = np.random.default_rng(seed)
    f = lambda *s: jnp.asarray(rng.normal(size=s).astype(np.float32) * 0.3)
    return (
        f(B, T, D), f(B, H), f(B, H),
        f(D, 4 * H), f(H, 4 * H), f(4 * H),
        jnp.asarray((rng.random((B, T)) < reset_p).astype(np.float32)),
    )


class TestPallasLSTM:
    def test_forward_parity(self):
        args = inputs()
        hs_r, (hT_r, cT_r) = lstm_sequence_reference(*args)
        hs_p, (hT_p, cT_p) = lstm_sequence(*args, interpret_ok=True)
        np.testing.assert_allclose(np.asarray(hs_r), np.asarray(hs_p),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(hT_r), np.asarray(hT_p),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(cT_r), np.asarray(cT_p),
                                   rtol=1e-5, atol=1e-6)

    def test_resets_cut_state(self):
        """A reset at step t must make steps ≥ t independent of the carry."""
        x, h0, c0, wx, wh, b, _ = inputs(reset_p=0.0)
        resets = jnp.zeros(x.shape[:2], jnp.float32).at[:, 3].set(1.0)
        hs_a, _ = lstm_sequence(x, h0, c0, wx, wh, b, resets, interpret_ok=True)
        hs_b, _ = lstm_sequence(x, 17.0 + h0, c0 - 5.0, wx, wh, b, resets, interpret_ok=True)
        assert not np.allclose(np.asarray(hs_a[:, 0]), np.asarray(hs_b[:, 0]))
        np.testing.assert_allclose(
            np.asarray(hs_a[:, 3:]), np.asarray(hs_b[:, 3:]),
            rtol=1e-5, atol=1e-6,
        )

    def test_gradient_parity(self):
        x, h0, c0, wx, wh, b, resets = inputs(seed=3)

        def loss(fn):
            def inner(wx_, wh_, b_):
                hs, (hT, cT) = fn(x, h0, c0, wx_, wh_, b_, resets)
                return (hs ** 2).sum() + (hT * cT).sum()
            return inner

        g_p = jax.grad(
            loss(lambda *a: lstm_sequence(*a, interpret_ok=True)),
            argnums=(0, 1, 2),
        )(wx, wh, b)
        g_r = jax.grad(loss(lstm_sequence_reference), argnums=(0, 1, 2))(wx, wh, b)
        for a, r in zip(g_p, g_r):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(r), rtol=1e-4, atol=1e-5
            )

    def test_use_pallas_false_is_reference(self):
        args = inputs(seed=5)
        hs_a, _ = lstm_sequence(*args, use_pallas=False)
        hs_b, _ = lstm_sequence_reference(*args)
        np.testing.assert_array_equal(np.asarray(hs_a), np.asarray(hs_b))
