"""Fleet health plane tests (ISSUE 13): snapshot codec round-trips on
both wire lanes (CRC/quarantine semantics unchanged for the new frame
kind), counter-delta merge across peer restart, alert rule
debounce/for-duration/resolve semantics, the rules↔runbook lint
cross-check on a doctored OPERATIONS.md, the --require-fleet schema
tier, and the fleet_status console on a canned JSONL."""

import ast
import json
import os
import time

import numpy as np
import pytest

from dotaclient_tpu.utils import alerts, fleet, telemetry

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _schema_module():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(_REPO, "scripts", "check_telemetry_schema.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _fleet_status_module():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "fleet_status", os.path.join(_REPO, "scripts", "fleet_status.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _no_leaked_publisher():
    """Every test starts and ends with the fleet fanout OFF (the
    in-process default); a leaked publisher would change other tests'
    pool hot paths."""
    fleet.shutdown()
    yield
    fleet.shutdown()


# ---------------------------------------------------------------------------
# snapshot codec


class TestSnapshotCodec:
    def test_round_trip(self):
        payload = fleet.encode_snapshot(
            7, "actor", 3,
            {"actor/env_steps": 1234.0, "transport/reconnects_total": 2.0},
            {"actor/weight_refresh_lag": 5.0},
            pid=42,
        )
        snap = fleet.decode_snapshot(payload)
        assert snap == {
            "peer": "a7",
            "kind": "actor",
            "pid": 42,
            "seq": 3,
            "counters": {
                "actor/env_steps": 1234.0,
                "transport/reconnects_total": 2.0,
            },
            "gauges": {"actor/weight_refresh_lag": 5.0},
        }

    def test_serve_kind_and_filtering(self):
        payload = fleet.encode_snapshot(
            9, "serve", 0,
            # span keys and foreign namespaces must NOT ship
            {"serve/requests_total": 10.0, "span/not/shipped": 1.0,
             "league/eval_win": 1.0},
            {"serve/p99_latency_ms": 12.5},
        )
        snap = fleet.decode_snapshot(payload)
        assert snap["peer"] == "s9"
        assert snap["kind"] == "serve"
        assert snap["counters"] == {"serve/requests_total": 10.0}
        assert snap["gauges"] == {"serve/p99_latency_ms": 12.5}

    def test_garbage_decodes_to_none(self):
        assert fleet.decode_snapshot(b"not a frame") is None


# ---------------------------------------------------------------------------
# both wire lanes


class TestSocketLane:
    def test_snapshot_rides_kind5_and_rollouts_unaffected(self):
        from dotaclient_tpu.transport.socket_transport import (
            SocketTransport,
            TransportServer,
        )
        from dotaclient_tpu.transport.serialize import encode_rollout_bytes

        server = TransportServer(port=0)
        received = []
        server.metrics_handler = lambda p: received.append(
            fleet.decode_snapshot(p)
        )
        host, port = server.address
        actor = None
        try:
            actor = SocketTransport(host, port)
            actor.publish_metrics_bytes(
                fleet.encode_snapshot(1, "actor", 0, {"actor/env_steps": 8.0}, {})
            )
            actor.publish_rollout_bytes(
                bytes(
                    encode_rollout_bytes(
                        {"rewards": np.zeros(4, np.float32)},
                        model_version=0, env_id=0, rollout_id=0, length=4,
                        total_reward=0.0,
                    )
                )
            )
            deadline = time.time() + 5.0
            rollouts = []
            while time.time() < deadline and (not received or not rollouts):
                rollouts += server.consume_decoded(16, timeout=0.1)
            assert received and received[0]["peer"] == "a1"
            assert received[0]["counters"] == {"actor/env_steps": 8.0}
            assert len(rollouts) == 1   # the metrics frame never reaches
            # the experience path
        finally:
            if actor is not None:
                actor.close()
            server.close()

    def test_corrupt_metrics_frame_counts_and_streaks(self):
        """CRC/quarantine semantics are UNCHANGED for the new kind: a
        corrupt metrics frame is dropped + counted and advances the
        poison streak exactly like a corrupt rollout."""
        from dotaclient_tpu.transport.socket_transport import (
            _KIND_METRICS,
            SocketTransport,
            TransportServer,
            _send_frame,
        )

        tel = telemetry.get_registry()
        server = TransportServer(port=0, poison_frame_limit=2)
        received = []
        server.metrics_handler = lambda p: received.append(p)
        host, port = server.address
        actor = None
        try:
            before = tel.counter("transport/frames_corrupt_total").value
            q_before = tel.counter("transport/peers_quarantined").value
            actor = SocketTransport(host, port)
            payload = fleet.encode_snapshot(1, "actor", 0, {}, {})
            _send_frame(actor._sock, _KIND_METRICS, payload, crc=0xBAD)
            _send_frame(actor._sock, _KIND_METRICS, payload, crc=0xBAD)
            deadline = time.time() + 5.0
            while (
                time.time() < deadline
                and tel.counter("transport/peers_quarantined").value
                <= q_before
            ):
                time.sleep(0.05)
            assert (
                tel.counter("transport/frames_corrupt_total").value
                >= before + 2
            )
            assert (
                tel.counter("transport/peers_quarantined").value
                == q_before + 1
            )
            assert received == []   # corrupt frames never reach the sink
        finally:
            if actor is not None:
                actor.close()
            server.close()


class TestShmLane:
    def _lane(self, tag, **kw):
        from dotaclient_tpu.transport import ShmTransport, ShmTransportServer

        name = f"t-fleet-{os.getpid()}-{tag}"
        server = ShmTransportServer(
            name=name, slots=1, ring_bytes=1 << 16, weights_bytes=1 << 16,
            **kw,
        )
        actor = ShmTransport(name, slots=1)
        return server, actor

    def test_flag_bit_routes_to_handler(self):
        from dotaclient_tpu.transport.serialize import encode_rollout_bytes

        server, actor = self._lane("route")
        received = []
        server.metrics_handler = lambda p: received.append(
            fleet.decode_snapshot(p)
        )
        try:
            actor.publish_metrics_bytes(
                fleet.encode_snapshot(2, "actor", 1, {"actor/env_steps": 4.0}, {})
            )
            actor.publish_rollout_bytes(
                bytes(
                    encode_rollout_bytes(
                        {"rewards": np.zeros(4, np.float32)},
                        model_version=0, env_id=0, rollout_id=9, length=4,
                        total_reward=0.0,
                    )
                )
            )
            rollouts = server.consume_decoded(16, timeout=1.0)
            assert received and received[0]["peer"] == "a2"
            assert received[0]["seq"] == 1
            # the rollout still flows; the metrics frame never mixes in
            assert len(rollouts) == 1
            assert rollouts[0][0]["rollout_id"] == 9
        finally:
            actor.close()
            server.close()

    def test_corrupt_metrics_frame_streaks_to_quarantine(self):
        from dotaclient_tpu.utils import faults

        tel = telemetry.get_registry()
        before = tel.counter("transport/frames_corrupt_total").value
        q_before = tel.counter("transport/peers_quarantined").value
        # every publish corrupts: the metrics path routes through the
        # same fault site as rollouts (shared framing by construction)
        faults.configure("transport.corrupt_frame@1+1")
        try:
            server, actor = self._lane("poison", poison_frame_limit=2)
            received = []
            server.metrics_handler = lambda p: received.append(p)
            try:
                actor.publish_metrics_bytes(
                    fleet.encode_snapshot(0, "actor", 0, {}, {})
                )
                actor.publish_metrics_bytes(
                    fleet.encode_snapshot(0, "actor", 1, {}, {})
                )
                assert server.consume_decoded(16, timeout=0.2) == []
                assert received == []
                assert (
                    tel.counter("transport/frames_corrupt_total").value
                    >= before + 2
                )
                assert (
                    tel.counter("transport/peers_quarantined").value
                    == q_before + 1
                )
            finally:
                actor.close()
                server.close()
        finally:
            faults.configure(None)


# ---------------------------------------------------------------------------
# aggregator merge semantics


class TestAggregatorMerge:
    def _agg(self, **kw):
        reg = telemetry.Registry()
        events = []
        agg = fleet.FleetAggregator(
            registry=reg, interval_s=0.1, emit_event=events.append, **kw
        )
        return reg, agg, events

    def test_counter_delta_merge_across_restart(self):
        """The acceptance pin: a restarted pid must not double-count. The
        old incarnation folded 150 cumulative steps in; the fresh pid's
        cumulative counter restarts from 0, so its first snapshot ADDS
        its own total instead of re-adding history."""
        reg, agg, _ = self._agg()
        t = time.monotonic()
        agg.ingest(fleet.encode_snapshot(
            0, "actor", 0, {"actor/env_steps": 100.0}, {}, pid=111))
        agg.ingest(fleet.encode_snapshot(
            0, "actor", 1, {"actor/env_steps": 150.0}, {}, pid=111))
        agg.tick(now=t)
        assert reg.snapshot()["fleet/a0/actor/env_steps"] == 150.0
        # restart: same peer id (seed), fresh pid, counters from zero
        agg.ingest(fleet.encode_snapshot(
            0, "actor", 0, {"actor/env_steps": 30.0}, {}, pid=222))
        agg.tick(now=t + 0.1)
        assert reg.snapshot()["fleet/a0/actor/env_steps"] == 180.0
        # and only ONE peer row exists (the restart reused it)
        assert reg.snapshot()["fleet/peers"] == 1.0

    def test_lost_frame_loses_nothing(self):
        """Receiver-side deltas over cumulative totals: a dropped
        snapshot's increment arrives with the next one."""
        reg, agg, _ = self._agg()
        agg.ingest(fleet.encode_snapshot(
            0, "actor", 0, {"actor/env_steps": 10.0}, {}, pid=1))
        # seq 1 lost; seq 2 carries the cumulative total
        agg.ingest(fleet.encode_snapshot(
            0, "actor", 2, {"actor/env_steps": 50.0}, {}, pid=1))
        agg.tick()
        assert reg.snapshot()["fleet/a0/actor/env_steps"] == 50.0

    def test_rollups_and_stale_peers(self):
        reg, agg, _ = self._agg(stale_after_s=0.5)
        t = time.monotonic()
        for peer, lag in ((0, 2.0), (1, 6.0)):
            agg.ingest(fleet.encode_snapshot(
                peer, "actor", 0, {}, {"actor/weight_refresh_lag": lag},
                pid=peer + 1,
            ))
        agg.tick(now=t)
        snap = reg.snapshot()
        assert snap["fleet/peers"] == 2.0
        assert snap["fleet/peers_stale"] == 0.0
        assert snap["fleet/agg/weight_staleness/min"] == 2.0
        assert snap["fleet/agg/weight_staleness/max"] == 6.0
        assert snap["fleet/agg/weight_staleness/mean"] == 4.0
        # silence: both peers stop reporting past the stale window
        agg.tick(now=t + 1.0)
        snap = reg.snapshot()
        assert snap["fleet/peers"] == 0.0
        assert snap["fleet/peers_stale"] == 2.0
        # rollups over an empty live set read 0, never stale values
        assert snap["fleet/agg/weight_staleness/max"] == 0.0

    def test_env_fps_rate(self):
        reg, agg, _ = self._agg()
        t = time.monotonic()
        agg.ingest(
            fleet.encode_snapshot(
                0, "actor", 0, {"actor/env_steps": 0.0}, {}, pid=1
            ),
            recv_ts=t,
        )
        agg.ingest(
            fleet.encode_snapshot(
                0, "actor", 1, {"actor/env_steps": 100.0}, {}, pid=1
            ),
            recv_ts=t + 2.0,
        )
        agg.tick(now=t + 2.0)
        assert reg.snapshot()["fleet/a0/env_fps"] == pytest.approx(50.0)

    def test_bad_snapshot_counted_not_raised(self):
        reg, agg, _ = self._agg()
        assert agg.ingest(b"\x00\x01garbage") is False
        assert reg.snapshot()["fleet/bad_snapshots_total"] == 1.0

    def test_eager_keys_at_construction(self):
        reg = telemetry.Registry()
        fleet.FleetAggregator(registry=reg)
        snap = reg.snapshot()
        for key in (
            "fleet/peers", "fleet/peers_stale", "fleet/snapshots_total",
            "fleet/bad_snapshots_total", "alerts/fired_total",
            "alerts/resolved_total", "alerts/active",
        ):
            assert key in snap, key
        for name in fleet.AGG_KEYS:
            assert f"fleet/agg/{name}" in snap


# ---------------------------------------------------------------------------
# alert engine semantics


def _rule(**kw):
    base = dict(
        name="r", key="x", kind="threshold", value=5.0, runbook="rb:x"
    )
    base.update(kw)
    return alerts.AlertRule(**base)


class TestAlertEngine:
    def _engine(self, rule):
        reg = telemetry.Registry()
        events = []
        eng = alerts.AlertEngine(
            rules=(rule,), registry=reg, emit=events.append
        )
        return reg, eng, events

    def test_threshold_for_duration_debounce(self):
        reg, eng, events = self._engine(_rule(for_s=10.0))
        assert eng.evaluate({"x": 9.0}, now=0.0) == ([], [])
        assert eng.evaluate({"x": 9.0}, now=5.0) == ([], [])   # pending
        fired, _ = eng.evaluate({"x": 9.0}, now=10.0)
        assert fired == ["r"]
        assert reg.snapshot()["alerts/active"] == 1.0
        assert events[-1]["state"] == "fired"
        assert events[-1]["runbook"] == "rb:x"
        # a dip resets the debounce clock entirely
        eng2 = self._engine(_rule(for_s=10.0))[1]
        eng2.evaluate({"x": 9.0}, now=0.0)
        eng2.evaluate({"x": 1.0}, now=5.0)    # condition clears
        assert eng2.evaluate({"x": 9.0}, now=12.0) == ([], [])  # re-arms

    def test_resolve_and_counters(self):
        reg, eng, events = self._engine(_rule())
        eng.evaluate({"x": 9.0}, now=0.0)
        _, resolved = eng.evaluate({"x": 1.0}, now=1.0)
        assert resolved == ["r"]
        snap = reg.snapshot()
        assert snap["alerts/fired_total"] == 1.0
        assert snap["alerts/resolved_total"] == 1.0
        assert snap["alerts/active"] == 0.0
        assert [e["state"] for e in events] == ["fired", "resolved"]

    def test_rate_rule_window(self):
        reg, eng, _ = self._engine(
            _rule(kind="rate", value=1.0, window_s=10.0)
        )
        assert eng.evaluate({"x": 0.0}, now=0.0) == ([], [])
        # 5 per second: over the 1/s bound
        fired, _ = eng.evaluate({"x": 50.0}, now=10.0)
        assert fired == ["r"]
        # plateau: rate decays to zero inside the window → resolves
        _, resolved = eng.evaluate({"x": 50.0}, now=25.0)
        assert resolved == ["r"]

    def test_rate_counter_reset_restarts_window(self):
        _, eng, _ = self._engine(_rule(kind="rate", value=0.0, window_s=60.0))
        eng.evaluate({"x": 100.0}, now=0.0)
        # process restart: the counter fell — must NOT read as negative
        # rate nor as a giant positive one
        assert eng.evaluate({"x": 1.0}, now=1.0) == ([], [])

    def test_stale_rule(self):
        _, eng, _ = self._engine(_rule(kind="stale", value=5.0))
        assert eng.evaluate({"x": 3.0}, now=0.0) == ([], [])
        assert eng.evaluate({"x": 3.0}, now=4.0) == ([], [])
        fired, _ = eng.evaluate({"x": 3.0}, now=6.0)
        assert fired == ["r"]
        # the value moving again resolves it
        _, resolved = eng.evaluate({"x": 4.0}, now=7.0)
        assert resolved == ["r"]

    def test_pattern_key_aggregation(self):
        _, eng, _ = self._engine(
            _rule(key="fleet/*/serve/p99_latency_ms", value=100.0, agg="max")
        )
        fired, _ = eng.evaluate(
            {
                "fleet/s1/serve/p99_latency_ms": 50.0,
                "fleet/s2/serve/p99_latency_ms": 150.0,
            },
            now=0.0,
        )
        assert fired == ["r"]

    def test_missing_key_is_silent(self):
        _, eng, _ = self._engine(_rule())
        assert eng.evaluate({}, now=0.0) == ([], [])
        assert eng.evaluate({}, now=100.0) == ([], [])

    def test_runbook_anchor_mandatory(self):
        with pytest.raises(ValueError, match="runbook"):
            alerts.AlertEngine(
                rules=(_rule(runbook=""),), registry=telemetry.Registry()
            )

    def test_shipped_rules_construct(self):
        eng = alerts.AlertEngine(registry=telemetry.Registry())
        assert len(eng.rules) >= 10
        eng.evaluate({}, now=0.0)   # no data anywhere: no rule fires
        assert eng.active_rules() == []


# ---------------------------------------------------------------------------
# rules ↔ runbook cross-check (the alert-drift lint pass)


class TestAlertDrift:
    def _inputs(self):
        from dotaclient_tpu.lint import alert_drift as ad

        alerts_src = open(
            os.path.join(_REPO, "dotaclient_tpu", "utils", "alerts.py")
        ).read()
        doc = open(os.path.join(_REPO, "docs", "OPERATIONS.md")).read()
        tree = ast.parse(alerts_src)
        rules, problems = ad.extract_rules(tree)
        assert problems == []
        waivers = ad.extract_waivers(tree)
        return ad, rules, waivers, doc

    def test_clean_on_head(self):
        ad, rules, waivers, doc = self._inputs()
        assert len(rules) >= 10, "the shipped rule table extracted"
        assert waivers, "the waiver list extracted"
        assert ad.drift_findings(rules, waivers, doc) == []

    def test_deleted_runbook_anchor_fails(self):
        """The acceptance pin: doctor the REAL OPERATIONS.md by deleting
        one anchor token — the rule pointing at it must flag, and the
        now-anchorless row must flag too."""
        ad, rules, waivers, doc = self._inputs()
        assert "`rb:staleness-spike`" in doc
        doctored = doc.replace("`rb:staleness-spike`", "", 1)
        findings = ad.drift_findings(rules, waivers, doctored)
        msgs = [f.message for f in findings]
        assert any(
            "rb:staleness-spike" in m and "does not exist" in m for m in msgs
        ), msgs
        assert any("carries no `rb:<anchor>`" in m for m in msgs)

    def test_unwatched_failure_mode_fails(self):
        """A new runbook row with an anchor but neither rule nor waiver
        must flag — documenting a failure mode forces the decision."""
        ad, rules, waivers, doc = self._inputs()
        doctored = doc.replace(
            "| failure | detection signal (telemetry) | automatic response | operator action |",
            "| failure | detection signal (telemetry) | automatic response | operator action |\n"
            "|---|---|---|---|\n"
            "| made-up failure `rb:made-up` | a key | nothing | read this |",
            1,
        )
        findings = ad.drift_findings(rules, waivers, doctored)
        assert any(
            f.context == "rb:made-up" and "neither an alert rule" in f.message
            for f in findings
        )

    def test_stale_waiver_fails(self):
        ad, rules, waivers, doc = self._inputs()
        with_ghost = {**waivers, "rb:does-not-exist": "why"}
        findings = ad.drift_findings(rules, with_ghost, doc)
        assert any(f.context == "rb:does-not-exist" for f in findings)
        # a waiver covering a RULED anchor is stale the other way
        with_covered = {**waivers, "rb:staleness-spike": "why"}
        findings2 = ad.drift_findings(rules, with_covered, doc)
        assert any("a rule now covers it" in f.message for f in findings2)

    def test_catalog_mirrors_rules(self):
        ad, rules, waivers, doc = self._inputs()
        # drop one catalog row → the rule must flag as uncatalogued
        doctored = "\n".join(
            l for l in doc.splitlines()
            if not l.startswith("| `weight_staleness_high`")
        )
        findings = ad.drift_findings(rules, waivers, doctored)
        assert any(
            f.context == "weight_staleness_high"
            and "no row in the" in f.message
            for f in findings
        )


# ---------------------------------------------------------------------------
# schema tier + console


class TestSchemaTier:
    def test_require_fleet_round_trip(self):
        schema = _schema_module()
        reg = telemetry.Registry()
        fleet.FleetAggregator(registry=reg)   # eager keys, thread not started
        scalars = dict(reg.snapshot())
        line = json.dumps({"ts": 1.0, "step": 0, "scalars": scalars})
        errs = schema.validate_lines(
            [line], extra_required=schema.FLEET_KEYS, base_required=()
        )
        assert errs == []
        scalars.pop("fleet/peers_stale")
        line = json.dumps({"ts": 1.0, "step": 0, "scalars": scalars})
        errs = schema.validate_lines(
            [line], extra_required=schema.FLEET_KEYS, base_required=()
        )
        assert any("fleet/peers_stale" in e for e in errs)

    def test_alert_event_lines_are_tolerated(self):
        """ALERT events ride the same JSONL; the envelope validator must
        skip them, never fail them."""
        schema = _schema_module()
        reg = telemetry.Registry()
        fleet.FleetAggregator(registry=reg)
        lines = [
            json.dumps({"ts": 1.0, "event": "ALERT", "state": "fired",
                        "rule": "x", "runbook": "rb:x"}),
            json.dumps({"ts": 2.0, "step": 0, "scalars": dict(reg.snapshot())}),
        ]
        errs = schema.validate_lines(
            lines, extra_required=schema.FLEET_KEYS, base_required=()
        )
        assert errs == []

    def test_fleet_keys_match_aggregator(self):
        """The tier list and the aggregator's eager key set cannot
        drift: every tier key must exist at bare construction."""
        schema = _schema_module()
        reg = telemetry.Registry()
        fleet.FleetAggregator(registry=reg)
        snap = reg.snapshot()
        for key in schema.FLEET_KEYS:
            assert key in snap, key


class TestFleetStatus:
    def _canned(self, tmp_path):
        reg = telemetry.Registry()
        agg = fleet.FleetAggregator(registry=reg, interval_s=0.1)
        t = time.monotonic()
        agg.ingest(fleet.encode_snapshot(
            0, "actor", 0,
            {"actor/env_steps": 500.0, "transport/reconnects_total": 1.0},
            {"actor/weight_refresh_lag": 2.0}, pid=11), recv_ts=t)
        agg.ingest(fleet.encode_snapshot(
            1, "actor", 0, {"actor/env_steps": 300.0},
            {"actor/weight_refresh_lag": 4.0}, pid=12), recv_ts=t)
        agg.tick(now=t)
        path = tmp_path / "learner.jsonl"
        sink = telemetry.JsonlSink(str(path))
        sink.emit_event({"event": "ALERT", "state": "fired",
                         "rule": "corrupt_frame_rate", "severity": "warn",
                         "runbook": "rb:corrupt-frames", "value": 1.0,
                         "threshold": 0.02, "summary": "s"})
        sink.emit_event({"event": "ALERT", "state": "fired",
                         "rule": "fleet_peer_stale", "severity": "page",
                         "runbook": "rb:fleet-peer-stale", "value": 1.0,
                         "threshold": 0.0, "summary": "s"})
        sink.emit_event({"event": "ALERT", "state": "resolved",
                         "rule": "fleet_peer_stale", "severity": "page",
                         "runbook": "rb:fleet-peer-stale", "value": 0.0,
                         "threshold": 0.0, "summary": "s"})
        sink.emit(7, reg.snapshot())
        sink.close()
        return path

    def test_one_shot_render_and_summary(self, tmp_path, capsys):
        fs = _fleet_status_module()
        path = self._canned(tmp_path)
        assert fs.main([str(path)]) == 0
        out = capsys.readouterr().out
        assert "a0" in out and "a1" in out
        status_lines = [
            l for l in out.splitlines() if l.startswith("FLEET_STATUS ")
        ]
        assert len(status_lines) == 1
        summary = json.loads(status_lines[0][len("FLEET_STATUS "):])
        assert summary["peers"] == ["a0", "a1"]
        assert summary["n_peers"] == 2
        assert summary["peers_stale"] == 0
        # resolved alerts are NOT active; the corrupt one still is
        assert [a["rule"] for a in summary["active_alerts"]] == [
            "corrupt_frame_rate"
        ]
        assert summary["active_alerts"][0]["runbook"] == "rb:corrupt-frames"
        assert summary["ok"] is True   # no stale peers, no active page

    def test_torn_tail_tolerated(self, tmp_path, capsys):
        fs = _fleet_status_module()
        path = self._canned(tmp_path)
        with open(path, "a") as f:
            f.write('{"ts": 3.0, "step": 9, "scal')   # SIGKILL mid-line
        assert fs.main([str(path)]) == 0
        out = capsys.readouterr().out
        assert "FLEET_STATUS " in out


# ---------------------------------------------------------------------------
# the disabled-cost pin


class TestPublisherPointerTest:
    def test_off_by_default(self):
        assert fleet.get() is None

    def test_configure_and_shutdown(self):
        pub = fleet.configure(peer_id=3, kind="actor", interval_s=1.0)
        assert fleet.get() is pub
        assert pub.peer_id == 3
        fleet.configure(peer_id=3, interval_s=0.0)   # <= 0 disables
        assert fleet.get() is None

    def test_pool_captures_pointer_at_construction(self):
        """With the fanout off, the pool's whole per-boundary cost is
        `self._fleet is None` (the faults.get()/tracing discipline)."""
        import dataclasses

        import jax

        from dotaclient_tpu.actor.vec_runtime import VecActorPool
        from dotaclient_tpu.config import default_config
        from dotaclient_tpu.models import init_params, make_policy

        cfg = default_config()
        cfg = dataclasses.replace(
            cfg,
            env=dataclasses.replace(cfg.env, n_envs=2, max_dota_time=30.0),
            ppo=dataclasses.replace(cfg.ppo, rollout_len=4),
        )
        policy = make_policy(cfg.model, cfg.obs, cfg.actions)
        params = init_params(policy, jax.random.PRNGKey(0))
        out = []
        pool = VecActorPool(cfg, policy, params, seed=0, rollout_sink=out.extend)
        assert pool._fleet is None
        # and with a publisher configured, a fresh pool captures it
        fleet.configure(peer_id=0, interval_s=100.0)
        pool2 = VecActorPool(cfg, policy, params, seed=0, rollout_sink=out.extend)
        assert pool2._fleet is fleet.get()

    def test_maybe_publish_cadence_and_transportless_degrade(self):
        class FakeTransport:
            def __init__(self):
                self.frames = []

            def publish_metrics_bytes(self, payload):
                self.frames.append(payload)

        reg = telemetry.Registry()
        reg.counter("actor/env_steps").inc(5)
        pub = fleet.FleetPublisher(0, "actor", interval_s=3600.0, registry=reg)
        t = FakeTransport()
        assert pub.maybe_publish(t) is True    # first call ships
        assert pub.maybe_publish(t) is False   # inside the interval
        assert len(t.frames) == 1
        snap = fleet.decode_snapshot(t.frames[0])
        assert snap["counters"]["actor/env_steps"] == 5.0
        # a lane without a metrics channel (AMQP, in-proc): silent no-op
        assert pub.maybe_publish(object(), force=True) is False
