"""Outcome attribution plane tests (ISSUE 15).

Covers: the episode-record schema and host recording, the in-graph
done-masked reductions pinned BITWISE against host-loop recording (the
PR 10/11 parity-digest pattern) and against the numpy-sim oracle in
lockstep, window_stats episode accounting across lane resets, outcome
counters riding the fleet snapshot frames (delta-merge across restarts,
priority-aware leaf cut), the OutcomeAggregator's windowed curves +
arming discipline, the outcome alert rules end to end through the
engine, the --require-outcome schema tier, the JSONL sink's
crash-mid-write torn-tail seal (bugfix sweep), the outcome_report and
bench_trajectory consoles, and the alert-drift rule-key extension.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from dotaclient_tpu.config import default_config
from dotaclient_tpu.outcome import (
    BUCKETS,
    N_LEN_BUCKETS,
    REWARD_TERMS,
    OutcomeAggregator,
    ensure_actor_metrics,
    len_bucket,
    opponent_bucket,
    record_episode,
)
from dotaclient_tpu.outcome.records import counter_totals
from dotaclient_tpu.utils import alerts, fleet, telemetry

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _script_module(name):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# records: schema + host recording


class TestRecords:
    def test_opponent_bucket_mapping(self):
        assert opponent_bucket("scripted_easy") == "vs_scripted"
        assert opponent_bucket("scripted_hard") == "vs_scripted"
        assert opponent_bucket("selfplay") == "vs_selfplay"
        assert opponent_bucket("league") == "vs_league"

    def test_len_bucket_convention(self):
        # [2^i, 2^(i+1)) buckets, clipped; degenerate lengths land in 0
        assert len_bucket(0) == 0
        assert len_bucket(1) == 0
        assert len_bucket(2) == 1
        assert len_bucket(3) == 1
        assert len_bucket(256) == 8
        assert len_bucket(10**9) == N_LEN_BUCKETS - 1

    def test_record_episode_counters(self):
        reg = telemetry.Registry()
        ensure_actor_metrics(reg)
        record_episode(reg, "vs_scripted", True, 150, side="radiant")
        record_episode(reg, "vs_scripted", False, 150, side="radiant")
        record_episode(reg, "vs_league", True, 3, side="dire")
        snap = reg.snapshot()
        assert snap["outcome/episodes/vs_scripted"] == 2.0
        assert snap["outcome/wins/vs_scripted"] == 1.0
        assert snap["outcome/episodes/vs_league"] == 1.0
        assert snap["outcome/wins/vs_league"] == 1.0
        assert snap["outcome/episodes_side/radiant"] == 2.0
        assert snap["outcome/episodes_side/dire"] == 1.0
        assert snap["outcome/ep_len_sum"] == 303.0
        assert snap["outcome/ep_len_hist/07"] == 2.0   # 150 ∈ [128, 256)
        assert snap["outcome/ep_len_hist/01"] == 1.0   # 3 ∈ [2, 4)

    def test_counter_totals_merges_fleet_mirrors(self):
        totals = counter_totals(
            {
                "outcome/episodes/vs_scripted": 3.0,
                "fleet/a0/outcome/episodes/vs_scripted": 5.0,
                "fleet/a1/outcome/episodes/vs_scripted": 2.0,
                "fleet/a0/actor/env_steps": 999.0,   # not an outcome key
                "buffer/ingested": 7.0,
            }
        )
        assert totals == {"outcome/episodes/vs_scripted": 10.0}


# ---------------------------------------------------------------------------
# in-graph reductions: the parity digests


class TestIngraphParity:
    def test_reductions_match_host_recording_bitwise(self):
        """The device-path reduction and host-loop recording must agree
        BITWISE on identical episode streams (counts are integers — any
        drift is a real bug, not float noise)."""
        import jax

        from dotaclient_tpu.outcome import ingraph

        rng = np.random.default_rng(0)
        T, N = 64, 16
        done = rng.random((T, N)) < 0.08
        win = rng.random((T, N)) < 0.5
        ep_len = np.where(done, rng.integers(1, 2000, size=(T, N)), 0)

        dev = jax.jit(ingraph.chunk_outcome_stats)(
            done, win, ep_len.astype(np.int32)
        )
        dev = jax.device_get(dev)

        reg = telemetry.Registry()
        ensure_actor_metrics(reg)
        for t in range(T):
            for n in range(N):
                if done[t, n]:
                    record_episode(
                        reg, "vs_scripted", bool(win[t, n]),
                        int(ep_len[t, n]),
                    )
        snap = reg.snapshot()
        assert float(dev["out_eps_vs_scripted"]) == snap[
            "outcome/episodes/vs_scripted"
        ]
        assert float(dev["out_wins_vs_scripted"]) == snap[
            "outcome/wins/vs_scripted"
        ]
        assert float(dev["out_ep_len_sum"]) == snap["outcome/ep_len_sum"]
        for i in range(N_LEN_BUCKETS):
            assert float(dev["out_ep_len_hist"][i]) == snap[
                f"outcome/ep_len_hist/{i:02d}"
            ], f"hist bucket {i}"

    def test_bucket_masks_by_mode(self):
        from dotaclient_tpu.outcome import ingraph

        m = ingraph.bucket_masks(4, "scripted_hard", 0)
        assert bool(np.all(np.asarray(m["vs_scripted"])))
        m = ingraph.bucket_masks(4, "selfplay", 0)
        assert bool(np.all(np.asarray(m["vs_selfplay"])))
        m = ingraph.bucket_masks(4, "league", 1)
        assert np.asarray(m["vs_scripted"]).tolist() == [
            True, False, False, False,
        ]
        assert np.asarray(m["vs_league"]).tolist() == [
            False, True, True, True,
        ]

    def test_sim_lockstep_outcome_parity(self):
        """Drive the numpy sim (the semantic oracle) and the JAX sim in
        lockstep to the timeout horizon (wave-free window, so zero RNG
        divergence): the in-graph reduction over the jax stream must
        match host-loop recording over the vec stream bitwise."""
        import jax
        import jax.numpy as jnp

        from dotaclient_tpu.envs.lane_sim import TEAM_RADIANT
        from dotaclient_tpu.outcome import ingraph
        from tests.test_jax_sim import make_pair, noop

        # 20 s horizon = 100 steps < the 140-step wave-free bound
        spec, vsim, jstate = make_pair(n=4, max_dota_time=20.0)

        import dotaclient_tpu.envs.jax_lane_sim as J

        step = jax.jit(lambda s, a: J.step(spec, s, a))
        acts = noop(4, 2)
        jacts = {k: jnp.asarray(v) for k, v in acts.items()}

        reg = telemetry.Registry()
        ensure_actor_metrics(reg)
        host_prev_done = np.zeros(4, bool)
        host_steps = np.zeros(4, np.int64)
        dev_done, dev_win, dev_len = [], [], []
        j_prev_done = np.zeros(4, bool)
        j_steps = np.zeros(4, np.int64)
        for _ in range(120):
            vsim.step(acts)
            jstate = step(jstate, jacts)
            # host side: the VecActorPool recording semantics
            host_steps += ~host_prev_done
            now_done = np.asarray(vsim.done) & ~host_prev_done
            for g in np.nonzero(now_done)[0]:
                record_episode(
                    reg, "vs_scripted",
                    int(vsim.winning_team[g]) == TEAM_RADIANT,
                    int(host_steps[g]),
                )
            host_prev_done |= now_done
            # device side: the DeviceActor scan-body semantics
            jd = np.asarray(jstate.done)
            new_done = jd & ~j_prev_done
            j_steps += ~j_prev_done
            dev_done.append(new_done)
            dev_win.append(
                new_done & (np.asarray(jstate.winning_team) == TEAM_RADIANT)
            )
            dev_len.append(np.where(new_done, j_steps, 0))
            j_prev_done |= new_done
        dev = jax.device_get(
            jax.jit(ingraph.chunk_outcome_stats)(
                jnp.asarray(np.stack(dev_done)),
                jnp.asarray(np.stack(dev_win)),
                jnp.asarray(np.stack(dev_len), jnp.int32),
            )
        )
        snap = reg.snapshot()
        assert snap["outcome/episodes/vs_scripted"] == 4.0
        assert float(dev["out_eps_vs_scripted"]) == snap[
            "outcome/episodes/vs_scripted"
        ]
        assert float(dev["out_wins_vs_scripted"]) == snap[
            "outcome/wins/vs_scripted"
        ]
        assert float(dev["out_ep_len_sum"]) == snap["outcome/ep_len_sum"]
        for i in range(N_LEN_BUCKETS):
            assert float(dev["out_ep_len_hist"][i]) == snap[
                f"outcome/ep_len_hist/{i:02d}"
            ]

    @pytest.mark.slow   # ~11s: 25 jitted collects + drain
    def test_device_actor_outcome_matches_legacy_counts(self):
        """The device actor's folded outcome counters must equal its own
        legacy episodes/wins accounting bitwise — two accounting paths,
        one truth."""
        import jax

        from dotaclient_tpu.actor.device_rollout import DeviceActor
        from dotaclient_tpu.models import init_params, make_policy

        cfg = default_config()
        cfg = dataclasses.replace(
            cfg,
            env=dataclasses.replace(
                cfg.env, n_envs=4, max_dota_time=30.0
            ),
            ppo=dataclasses.replace(cfg.ppo, rollout_len=8),
        )
        policy = make_policy(cfg.model, cfg.obs, cfg.actions)
        params = init_params(policy, jax.random.PRNGKey(0))
        reg = telemetry.Registry()
        da = DeviceActor(cfg, policy, seed=0, registry=reg)
        for _ in range(25):
            da.collect(params)
        da.drain_stats()
        assert da.episodes_done >= 4
        snap = reg.snapshot()
        assert snap["outcome/episodes/vs_scripted"] == float(
            da.episodes_done
        )
        assert snap["outcome/wins/vs_scripted"] == float(da.wins)
        hist_total = sum(
            snap[f"outcome/ep_len_hist/{i:02d}"]
            for i in range(N_LEN_BUCKETS)
        )
        assert hist_total == float(da.episodes_done)
        assert snap["outcome/episodes_side/radiant"] == float(
            da.episodes_done
        )


class TestLearnerIntegration:
    @pytest.mark.slow   # fused program compile dominates
    def test_fused_learner_outcome_counts(self):
        """Fused mode runs the same in-graph reductions INSIDE its one
        donated program; the end-of-call drain must fold them into the
        outcome counters, matching the legacy episode accounting."""
        from dotaclient_tpu.train.learner import Learner

        cfg = default_config()
        cfg = dataclasses.replace(
            cfg,
            env=dataclasses.replace(
                cfg.env, n_envs=8, opponent="scripted_easy",
                max_dota_time=30.0,
            ),
            ppo=dataclasses.replace(cfg.ppo, rollout_len=8),
            log_every=1_000_000,
        )
        reg = telemetry.get_registry()
        base = dict(reg.counters_and_gauges()[0])
        lrn = Learner(cfg, actor="fused")
        try:
            lrn.train(40)
        finally:
            if lrn._snap_engine is not None:
                lrn._snap_engine.stop()
        now = reg.counters_and_gauges()[0]

        def delta(key):
            return now.get(key, 0.0) - base.get(key, 0.0)

        assert lrn.device_actor.episodes_done >= 2
        assert delta("outcome/episodes/vs_scripted") == float(
            lrn.device_actor.episodes_done
        )
        assert delta("outcome/wins/vs_scripted") == float(
            lrn.device_actor.wins
        )

    @pytest.mark.slow   # a real device-mode learner run with JSONL record
    def test_learner_device_outcome_curves_in_jsonl(self, tmp_path):
        """The acceptance shape: a short real run produces non-empty
        outcome curves in the learner JSONL and the --require-outcome
        tier validates it."""
        from dotaclient_tpu.train.learner import Learner

        schema = _script_module("check_telemetry_schema")
        path = str(tmp_path / "learner.jsonl")
        cfg = default_config()
        cfg = dataclasses.replace(
            cfg,
            env=dataclasses.replace(
                cfg.env, n_envs=8, opponent="scripted_easy",
                max_dota_time=30.0,
            ),
            ppo=dataclasses.replace(
                cfg.ppo, rollout_len=8, batch_rollouts=8
            ),
            buffer=dataclasses.replace(
                cfg.buffer, capacity_rollouts=32, min_fill=8
            ),
            log_every=4,
        )
        lrn = Learner(cfg, actor="device", metrics_jsonl=path)
        try:
            lrn.train(40)
        finally:
            if lrn._snap_engine is not None:
                lrn._snap_engine.stop()
        lines = telemetry.load_jsonl(path)
        errs = schema.validate_lines(
            lines, extra_required=schema.OUTCOME_KEYS
        )
        assert errs == []
        report = _script_module("outcome_report")
        points, union, last_ts = report.parse_stream(lines)
        _text, status = report.render(points, union, last_ts, 40)
        assert status["ok"] is True
        assert status["episodes_total"] >= 8
        assert status["curve_points"] >= 1
        assert status["buckets"]["vs_scripted"]["episodes"] >= 8


# ---------------------------------------------------------------------------
# window stats: episode accounting across lane resets (host pools)


class TestWindowStatsAccounting:
    def _pool(self, n_envs=2):
        import jax

        from dotaclient_tpu.actor.vec_runtime import VecActorPool
        from dotaclient_tpu.models import init_params, make_policy

        cfg = default_config()
        cfg = dataclasses.replace(
            cfg,
            model=dataclasses.replace(cfg.model, dtype="float32"),
            env=dataclasses.replace(
                cfg.env, n_envs=n_envs, max_dota_time=15.0
            ),
            ppo=dataclasses.replace(cfg.ppo, rollout_len=8),
        )
        policy = make_policy(cfg.model, cfg.obs, cfg.actions)
        params = init_params(policy, jax.random.PRNGKey(0))
        sink = []
        return VecActorPool(
            cfg, policy, params, seed=0, rollout_sink=sink.extend
        )

    def test_vec_pool_outcome_across_resets(self):
        """Episodes spanning multiple resets: the outcome counters, the
        legacy counters, and the windowed drain must all agree — and the
        per-game step accounting must restart at each reset (the
        histogram total equals the episode count; lengths stay in the
        horizon's bucket instead of accumulating across episodes)."""
        pool = self._pool()
        reg = telemetry.get_registry()
        base = dict(reg.counters_and_gauges()[0])

        def delta(key):
            now = reg.counters_and_gauges()[0].get(key, 0.0)
            return now - base.get(key, 0.0)

        # window 1: at least one full episode per env
        steps = 0
        while pool.episodes_done < 2 and steps < 400:
            pool.step()
            steps += 1
        w1 = pool.drain_stats()
        assert w1["episodes_recent"] == float(pool.episodes_done)
        eps_after_w1 = pool.episodes_done
        # window 2: more episodes AFTER the resets
        steps = 0
        while pool.episodes_done < eps_after_w1 + 2 and steps < 400:
            pool.step()
            steps += 1
        w2 = pool.drain_stats()
        assert w2["episodes_recent"] == float(
            pool.episodes_done - eps_after_w1
        )
        assert delta("outcome/episodes/vs_scripted") == float(
            pool.episodes_done
        )
        assert delta("outcome/wins/vs_scripted") == float(pool.wins)
        # 15 s horizon = 75 env steps → bucket 6 ([64,128)); a counter
        # leaking across resets would land episodes in higher buckets
        hist = [
            delta(f"outcome/ep_len_hist/{i:02d}")
            for i in range(N_LEN_BUCKETS)
        ]
        assert sum(hist) == float(pool.episodes_done)
        assert hist[6] == float(pool.episodes_done)
        # every episode ran to the SAME timeout horizon (~76 env steps at
        # 15 s / 0.2 s-per-step): a per-game counter leaking across
        # resets would inflate later episodes' lengths
        mean_len = delta("outcome/ep_len_sum") / pool.episodes_done
        assert 64.0 <= mean_len < 128.0
        # identical horizons ⇒ identical lengths: the sum divides evenly
        assert delta("outcome/ep_len_sum") % pool.episodes_done == 0.0

    def test_reward_terms_accumulate(self):
        pool = self._pool()
        reg = telemetry.get_registry()
        base = dict(reg.counters_and_gauges()[0])
        for _ in range(30):
            pool.step()
        now = reg.counters_and_gauges()[0]
        moved = [
            t for t in REWARD_TERMS
            if now.get(f"outcome/reward_sum/{t}", 0.0)
            != base.get(f"outcome/reward_sum/{t}", 0.0)
        ]
        assert moved, "no reward term ever accumulated"

    def test_mixin_records_through_registry(self):
        from dotaclient_tpu.actor.window_stats import WindowedStatsMixin

        class FakePool(WindowedStatsMixin):
            episodes_done = 0
            wins = 0
            episode_rewards: list = []

            def stats(self):
                return self.windowed_entries()

        reg = telemetry.Registry()
        ensure_actor_metrics(reg)
        pool = FakePool()
        pool.record_episode_outcome(
            "vs_selfplay", True, 9, side="dire", registry=reg
        )
        snap = reg.snapshot()
        assert snap["outcome/episodes/vs_selfplay"] == 1.0
        assert snap["outcome/wins/vs_selfplay"] == 1.0
        assert snap["outcome/ep_len_hist/03"] == 1.0   # 9 ∈ [8, 16)


# ---------------------------------------------------------------------------
# transport: outcome counters inside fleet snapshot frames


class TestFleetTransport:
    def test_snapshot_ships_outcome_counters(self):
        reg = telemetry.Registry()
        ensure_actor_metrics(reg)
        record_episode(reg, "vs_scripted", True, 100)
        counters, gauges = reg.counters_and_gauges()
        payload = fleet.encode_snapshot(0, "actor", 0, counters, gauges)
        snap = fleet.decode_snapshot(payload)
        assert snap is not None
        assert snap["counters"]["outcome/episodes/vs_scripted"] == 1.0
        assert snap["counters"]["outcome/wins/vs_scripted"] == 1.0

    def test_cut_priority_protects_operational_keys(self):
        """Over the leaf cap, outcome histogram buckets drop FIRST and
        operational keys (alert rule sources) survive — alphabetical
        truncation would have silently blinded transport/* rules."""
        counters = {f"outcome/ep_len_hist/{i:02d}": float(i) for i in range(12)}
        counters.update(
            {f"outcome/reward_sum/fake_{i:02d}": 1.0 for i in range(70)}
        )
        counters["transport/reconnects_total"] = 7.0
        counters["trace/dropped_total"] = 1.0
        gauges = {"actor/weight_refresh_lag": 2.0}
        payload = fleet.encode_snapshot(3, "actor", 1, counters, gauges)
        snap = fleet.decode_snapshot(payload)
        assert snap["counters"]["transport/reconnects_total"] == 7.0
        assert snap["counters"]["trace/dropped_total"] == 1.0
        assert snap["gauges"]["actor/weight_refresh_lag"] == 2.0
        # the overflow was absorbed by the outcome namespace, hist first
        assert not any(
            k.startswith("outcome/ep_len_hist/")
            for k in snap["counters"]
        )

    def test_delta_merge_across_restart_no_double_count(self):
        """A supervisor-restarted actor re-counts its episodes from zero;
        the per-peer delta merge must add, never re-add."""
        reg = telemetry.Registry()
        agg = fleet.FleetAggregator(registry=reg, interval_s=0.05)
        c1 = {"outcome/episodes/vs_scripted": 5.0}
        agg.ingest(fleet.encode_snapshot(0, "actor", 0, c1, {}, pid=111))
        agg.tick(now=0.0)
        c2 = {"outcome/episodes/vs_scripted": 2.0}   # fresh pid, from zero
        agg.ingest(fleet.encode_snapshot(0, "actor", 0, c2, {}, pid=222))
        agg.tick(now=1.0)
        counters, _ = reg.counters_and_gauges()
        assert counters["fleet/a0/outcome/episodes/vs_scripted"] == 7.0
        totals = counter_totals(counters)
        assert totals["outcome/episodes/vs_scripted"] == 7.0


# ---------------------------------------------------------------------------
# the aggregator: windowed curves, arming, alerts


class TestOutcomeAggregator:
    def test_eager_keys_and_priors(self):
        reg = telemetry.Registry()
        OutcomeAggregator(registry=reg)
        snap = reg.snapshot()
        assert snap["outcome/win_rate/vs_scripted"] == 0.5
        assert snap["outcome/win_rate/vs_league"] == 0.5
        assert snap["outcome/win_rate/overall"] == 0.5
        assert snap["outcome/stream_age_s"] == -1.0
        assert snap["outcome/episode_len_anomaly"] == 0.0
        for term in REWARD_TERMS:
            assert f"outcome/reward/{term}" in snap

    def test_windowed_win_rate_and_stream_age(self):
        reg = telemetry.Registry()
        ensure_actor_metrics(reg)
        agg = OutcomeAggregator(registry=reg, window_s=60.0, min_episodes=4)
        agg.tick(now=0.0)
        assert reg.snapshot()["outcome/stream_age_s"] == -1.0   # unarmed
        for i in range(4):
            record_episode(reg, "vs_scripted", i < 3, 150)
        agg.tick(now=1.0)
        snap = reg.snapshot()
        assert snap["outcome/win_rate/vs_scripted"] == 0.75
        assert snap["outcome/win_rate/overall"] == 0.75
        assert snap["outcome/win_rate/vs_league"] == 0.5   # prior holds
        assert snap["outcome/episodes_total"] == 4.0
        assert snap["outcome/stream_age_s"] == 0.0
        assert snap["outcome/episode_len_p50"] == 256.0   # 150's bucket bound
        # silence: the age grows on wall clock, the rates HOLD
        agg.tick(now=50.0)
        snap = reg.snapshot()
        assert snap["outcome/stream_age_s"] == 49.0
        assert snap["outcome/win_rate/vs_scripted"] == 0.75

    def test_window_expiry_drops_old_episodes(self):
        reg = telemetry.Registry()
        ensure_actor_metrics(reg)
        agg = OutcomeAggregator(registry=reg, window_s=10.0, min_episodes=2)
        for _ in range(4):
            record_episode(reg, "vs_scripted", True, 100)
        agg.tick(now=0.0)
        agg.tick(now=1.0)
        for _ in range(2):
            record_episode(reg, "vs_scripted", False, 100)
        agg.tick(now=20.0)   # the t=0/1 samples age out of the window
        snap = reg.snapshot()
        assert snap["outcome/episodes_recent"] == 2.0
        assert snap["outcome/win_rate/vs_scripted"] == 0.0

    def test_anomaly_binary(self):
        reg = telemetry.Registry()
        ensure_actor_metrics(reg)
        agg = OutcomeAggregator(registry=reg, min_episodes=2)
        agg.tick(now=0.0)
        for _ in range(4):
            record_episode(reg, "vs_scripted", False, 1)   # instant resets
        agg.tick(now=1.0)
        snap = reg.snapshot()
        assert snap["outcome/episode_len_p50"] == 2.0
        assert snap["outcome/episode_len_anomaly"] == 1.0
        for _ in range(12):
            record_episode(reg, "vs_scripted", False, 100)
        agg.tick(now=2.0)
        assert reg.snapshot()["outcome/episode_len_anomaly"] == 0.0

    def test_reward_term_means(self):
        from dotaclient_tpu.outcome.records import add_reward_terms

        reg = telemetry.Registry()
        ensure_actor_metrics(reg)
        agg = OutcomeAggregator(registry=reg, min_episodes=1)
        agg.tick(now=0.0)
        for _ in range(2):
            record_episode(reg, "vs_scripted", True, 10)
        add_reward_terms(reg, {"gold": 6.0, "win": 10.0})
        agg.tick(now=1.0)
        snap = reg.snapshot()
        assert snap["outcome/reward/gold"] == 3.0
        assert snap["outcome/reward/win"] == 5.0
        assert snap["outcome/reward/xp"] == 0.0

    def _outcome_rules(self):
        return tuple(
            r for r in alerts.RULES
            if r.name in (
                "win_rate_collapse", "episode_len_anomaly",
                "outcome_stream_stale",
            )
        )

    def test_stream_stale_alert_fires_and_resolves(self):
        reg = telemetry.Registry()
        ensure_actor_metrics(reg)
        agg = OutcomeAggregator(registry=reg, min_episodes=1)
        engine = alerts.AlertEngine(
            rules=self._outcome_rules(), registry=reg
        )

        def evaluate(now):
            counters, gauges = reg.counters_and_gauges()
            return engine.evaluate({**counters, **gauges}, now)

        # unarmed: silence forever must NOT fire (age = -1)
        agg.tick(now=0.0)
        fired, _ = evaluate(1000.0)
        assert "outcome_stream_stale" not in fired
        # armed, then silent past the threshold: fires
        record_episode(reg, "vs_scripted", True, 100)
        agg.tick(now=1000.0)
        evaluate(1000.0)
        agg.tick(now=1100.0)
        fired, _ = evaluate(1100.0)
        assert "outcome_stream_stale" in fired
        # a fresh episode resolves
        record_episode(reg, "vs_scripted", True, 100)
        agg.tick(now=1101.0)
        _, resolved = evaluate(1101.0)
        assert "outcome_stream_stale" in resolved

    def test_win_rate_collapse_alert(self):
        reg = telemetry.Registry()
        ensure_actor_metrics(reg)
        agg = OutcomeAggregator(
            registry=reg, window_s=1000.0, min_episodes=8
        )
        engine = alerts.AlertEngine(
            rules=self._outcome_rules(), registry=reg
        )

        def evaluate(now):
            counters, gauges = reg.counters_and_gauges()
            return engine.evaluate({**counters, **gauges}, now)

        # no scripted games ever: the 0.5 prior can never collapse
        agg.tick(now=0.0)
        evaluate(0.0)
        fired, _ = evaluate(500.0)
        assert fired == []
        # 8 losses: condition true, debounced 120 s, then fires
        for _ in range(8):
            record_episode(reg, "vs_scripted", False, 100)
        agg.tick(now=501.0)
        fired, _ = evaluate(501.0)
        assert fired == []   # debounce holding
        fired, _ = evaluate(622.0)
        assert "win_rate_collapse" in fired


# ---------------------------------------------------------------------------
# schema tier + consoles


class TestSchemaAndConsoles:
    def test_require_outcome_round_trip(self):
        schema = _script_module("check_telemetry_schema")
        reg = telemetry.Registry()
        ensure_actor_metrics(reg)
        OutcomeAggregator(registry=reg)
        scalars = dict(reg.snapshot())
        line = json.dumps({"ts": 1.0, "step": 0, "scalars": scalars})
        errs = schema.validate_lines(
            [line], extra_required=schema.OUTCOME_KEYS, base_required=()
        )
        assert errs == []
        scalars.pop("outcome/win_rate/vs_scripted")
        line = json.dumps({"ts": 1.0, "step": 0, "scalars": scalars})
        errs = schema.validate_lines(
            [line], extra_required=schema.OUTCOME_KEYS, base_required=()
        )
        assert any("outcome/win_rate/vs_scripted" in e for e in errs)

    def test_outcome_keys_all_eager(self):
        """Every OUTCOME_KEYS tier entry must exist after nothing more
        than learner-construction-time calls (the --require-outcome
        determinism contract)."""
        schema = _script_module("check_telemetry_schema")
        reg = telemetry.Registry()
        ensure_actor_metrics(reg)
        OutcomeAggregator(registry=reg)
        snap = reg.snapshot()
        missing = [k for k in schema.OUTCOME_KEYS if k not in snap]
        assert missing == []

    def _canned_jsonl(self, tmp_path, with_outcome=True):
        path = tmp_path / "learner.jsonl"
        reg = telemetry.Registry()
        ensure_actor_metrics(reg)
        agg = OutcomeAggregator(registry=reg, min_episodes=2)
        lines = []
        if with_outcome:
            agg.tick(now=0.0)
            for i in range(6):
                record_episode(reg, "vs_scripted", i % 2 == 0, 150)
            agg.tick(now=1.0)
        sc = dict(reg.snapshot())
        # an external peer's mirrored counters ride the same stream
        sc["fleet/a7/outcome/episodes/vs_scripted"] = 4.0
        sc["fleet/a7/outcome/wins/vs_scripted"] = 1.0
        lines.append(json.dumps({"ts": 1.0, "step": 10, "scalars": sc}))
        path.write_text("\n".join(lines) + "\n")
        return str(path)

    def test_outcome_report_on_canned_jsonl(self, tmp_path, capsys):
        report = _script_module("outcome_report")
        rc = report.main([self._canned_jsonl(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 0
        status_line = [
            l for l in out.splitlines() if l.startswith("OUTCOME_STATUS ")
        ][0]
        status = json.loads(status_line[len("OUTCOME_STATUS "):])
        assert status["ok"] is True
        # local 6 + mirrored 4
        assert status["buckets"]["vs_scripted"]["episodes"] == 10.0
        assert status["buckets"]["vs_scripted"]["wins"] == 4.0
        assert status["win_rate_vs_scripted"] == 0.5
        assert "win-rate curves" in out

    def test_outcome_report_empty_stream(self, tmp_path, capsys):
        report = _script_module("outcome_report")
        rc = report.main([self._canned_jsonl(tmp_path, with_outcome=False)])
        out = capsys.readouterr().out
        assert rc == 1
        status = json.loads(
            [
                l for l in out.splitlines()
                if l.startswith("OUTCOME_STATUS ")
            ][0][len("OUTCOME_STATUS "):]
        )
        assert status["ok"] is False

    def test_fleet_status_outcome_panel(self, tmp_path, capsys):
        fs = _script_module("fleet_status")
        path = self._canned_jsonl(tmp_path)
        rc = fs.main([path])
        out = capsys.readouterr().out
        assert rc == 0
        assert "outcome: win_rate vs_scripted" in out
        line = [
            l for l in out.splitlines() if l.startswith("FLEET_STATUS ")
        ][0]
        summary = json.loads(line[len("FLEET_STATUS "):])
        assert summary["outcome"]["episodes_total"] == 6
        assert summary["outcome"]["win_rate_vs_scripted"] == 0.5


# ---------------------------------------------------------------------------
# JSONL sink: crash-mid-write bugfix sweep


class TestJsonlTornTail:
    def test_sink_seals_torn_tail_before_appending(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        with open(path, "w") as f:
            f.write(json.dumps({"ts": 1.0, "step": 0, "scalars": {}}) + "\n")
            f.write('{"ts": 2.0, "step": 1, "scal')   # SIGKILL mid-write
        sink = telemetry.JsonlSink(path)
        sink.emit(2, {"a": 1.0})
        sink.close()
        lines = telemetry.load_jsonl(path)
        parsed = [json.loads(l) for l in lines]   # every line must parse
        assert [p["step"] for p in parsed] == [0, 2]

    def test_sink_append_to_clean_file_unchanged(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        with open(path, "w") as f:
            f.write(json.dumps({"ts": 1.0, "step": 0, "scalars": {}}) + "\n")
        sink = telemetry.JsonlSink(path)
        sink.emit(1, {})
        sink.close()
        assert len(telemetry.load_jsonl(path)) == 2

    def test_load_jsonl_tolerates_torn_utf8(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        good = json.dumps({"ts": 1.0, "step": 0, "scalars": {}}) + "\n"
        with open(path, "wb") as f:
            f.write(good.encode())
            f.write('{"x": "é'.encode()[:-1])   # cut mid-codepoint
        lines = telemetry.load_jsonl(path)   # must not raise
        assert len(lines) == 1
        assert json.loads(lines[0])["step"] == 0

    def test_seal_whole_file_fragment(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        with open(path, "w") as f:
            f.write('{"torn')   # the only content is the fragment
        sink = telemetry.JsonlSink(path)
        sink.emit(5, {})
        sink.close()
        lines = telemetry.load_jsonl(path)
        assert len(lines) == 1
        assert json.loads(lines[0])["step"] == 5


# ---------------------------------------------------------------------------
# bench trajectory


class TestBenchTrajectory:
    def _write(self, tmp_path, name, body):
        (tmp_path / name).write_text(json.dumps(body))

    def test_trajectory_fingerprint_rules(self, tmp_path, capsys):
        traj = _script_module("bench_trajectory")
        host_a = {
            "platform": "Linux-x", "device_kind": "cpu",
            "device_count": 1, "forced_host": False, "jax": "0.9",
            "libtpu": None,
        }
        host_b = {**host_a, "device_kind": "TPU v5 lite"}
        # r01: the driver-wrapper shape, no fingerprint
        self._write(
            tmp_path, "BENCH_r01.json",
            {"n": 1, "rc": 0, "cmd": "x", "tail": "",
             "parsed": {"metric": "m", "value": 100.0, "unit": "f/s",
                        "vs_baseline": 1.0}},
        )
        # r02/r03: flat shape, same host; r04: unlike host
        for name, value, host in (
            ("BENCH_r02.json", 110.0, host_a),
            ("BENCH_r03.json", 121.0, host_a),
            ("BENCH_r04.json", 9000.0, host_b),
        ):
            self._write(
                tmp_path, name,
                {"metric": "m", "value": value, "unit": "f/s",
                 "vs_baseline": 1.0, "host": host,
                 "stages": {"fleet_overhead": 0.01,
                            "outcome_overhead": 0.005,
                            "learner_dispatch_ema_s": 0.5}},
            )
        rc = traj.main(["--dir", str(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 0
        line = [
            l for l in out.splitlines() if l.startswith("BENCH_TRAJECTORY ")
        ][0]
        t = json.loads(line[len("BENCH_TRAJECTORY "):])
        assert len(t["records"]) == 4
        # exactly ONE headline comparison: r02 → r03 (like hosts); the
        # unknown-host r01 and the unlike-host r04 never compare
        assert len(t["headline_comparisons"]) == 1
        c = t["headline_comparisons"][0]
        assert (c["from"], c["to"]) == ("BENCH_r02.json", "BENCH_r03.json")
        assert c["headline_ratio"] == 1.1
        # ratio stages tracked; absolute-time stages are NOT
        assert "outcome_overhead" in t["ratio_stages"]
        assert "learner_dispatch_ema_s" not in t["ratio_stages"]


# ---------------------------------------------------------------------------
# alert-drift extension: rule keys must be emitted


class TestAlertDriftRuleKeys:
    def test_ghost_key_flags(self):
        from dotaclient_tpu.lint.alert_drift import rule_key_findings

        rules = [
            {"name": "ok_rule", "runbook": "rb:x", "line": 1,
             "key": "outcome/stream_age_s"},
            {"name": "ghost", "runbook": "rb:y", "line": 2,
             "key": "outcome/never_emitted_key"},
            {"name": "pattern", "runbook": "rb:z", "line": 3,
             "key": "fleet/*/serve/p99_latency_ms"},
        ]
        findings = rule_key_findings(
            rules, {"outcome/stream_age_s"}
        )
        assert len(findings) == 1
        assert findings[0].context == "outcome/never_emitted_key"

    def test_shipped_rules_keys_emitted_on_head(self):
        """Every shipped rule's key resolves against the real extraction
        — the lint pass's clean-on-HEAD guarantee, pinned directly."""
        import ast as ast_mod

        from dotaclient_tpu.lint.alert_drift import (
            extract_rules,
            rule_key_findings,
        )
        from dotaclient_tpu.lint.core import FileCtx, package_py_files
        from dotaclient_tpu.lint.telemetry_drift import extract_emitted

        files = {}
        for rel in package_py_files():
            with open(os.path.join(_REPO, rel)) as f:
                src = f.read()
            files[rel] = FileCtx(rel, src)
        emitted, _, _ = extract_emitted(files)
        with open(
            os.path.join(_REPO, "dotaclient_tpu", "utils", "alerts.py")
        ) as f:
            rules, _ = extract_rules(ast_mod.parse(f.read()))
        assert rule_key_findings(rules, emitted) == []
