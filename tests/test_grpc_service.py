"""gRPC env service: full reset/observe/act cycle over a real localhost socket."""

import asyncio

import grpc
import pytest

from dotaclient_tpu.envs import lane_sim, service
from dotaclient_tpu.protos import dota_pb2 as pb


def _config():
    return pb.GameConfig(
        ticks_per_observation=6, max_dota_time=120.0, seed=3,
        hero_picks=[
            pb.HeroPick(team_id=lane_sim.TEAM_RADIANT, hero_id=1,
                        control_mode=pb.CONTROL_AGENT),
            pb.HeroPick(team_id=lane_sim.TEAM_DIRE, hero_id=1,
                        control_mode=pb.CONTROL_SCRIPTED_EASY),
        ],
    )


def test_grpc_reset_observe_act_cycle():
    async def main():
        server, port = await service.serve_env()
        client = service.DotaServiceClient.connect(f"127.0.0.1:{port}")
        try:
            init = await client.reset(_config())
            assert init.status == pb.STATUS_OK
            assert len(init.world_states) == 1
            ws0 = init.world_states[0]
            assert any(u.unit_type == pb.UNIT_HERO for u in ws0.units)

            hero = next(u for u in ws0.units
                        if u.unit_type == pb.UNIT_HERO
                        and u.team_id == lane_sim.TEAM_RADIANT)
            for _ in range(5):
                await client.act(pb.Actions(
                    team_id=lane_sim.TEAM_RADIANT,
                    actions=[pb.Action(player_id=hero.player_id,
                                       type=pb.ACTION_MOVE, move_x=8, move_y=4)],
                ))
            obs = await client.observe(lane_sim.TEAM_RADIANT)
            assert obs.status == pb.STATUS_OK
            hero_now = next(u for u in obs.world_state.units
                            if u.player_id == hero.player_id)
            assert hero_now.location.x > hero.location.x, "hero should have moved +x"

            # second reset reuses the same server
            init2 = await client.reset(_config())
            assert init2.world_states[0].tick == 0
        finally:
            await client.close()
            await server.stop(None)

    asyncio.run(main())


def test_grpc_observe_before_reset_fails_cleanly():
    async def main():
        server, port = await service.serve_env()
        client = service.DotaServiceClient.connect(f"127.0.0.1:{port}")
        try:
            resp = await client.observe(lane_sim.TEAM_RADIANT)
            assert resp.status == pb.STATUS_FAILED
        finally:
            await client.close()
            await server.stop(None)

    asyncio.run(main())
