"""graftlint: the multi-pass static-analysis framework (ISSUE 9).

Covers, per pass: a positive fixture (the pass flags its target pattern),
an annotated-ok fixture (`# lint-ok: <rule>(<why>)` waives it), and a
baseline-suppressed fixture (the fingerprint mechanism). The
thread-ownership fixtures encode the three PR 5–6 race shapes
(`_pending_best` swap, `_last_verdict_m` cross-thread fold state, the
lock-guarded sync-gate fold) that motivated the pass; the use-after-donate
fixtures encode the TPU-silent-corruption repro. The tier-1 wrapper test
runs the real lint on HEAD (non-strict; LINT_STRICT=1 escalates to
--strict, the TIER1_DURATION_STRICT pattern), which is the acceptance
criterion: `python -m dotaclient_tpu.lint` exits 0 with >= 4 passes.

Everything here is pure AST analysis — no jax, no devices — so the whole
module runs in well under a second.
"""

from __future__ import annotations

import os

import pytest

from dotaclient_tpu.lint import ALL_RULES
from dotaclient_tpu.lint.core import (
    REPO_ROOT,
    Diagnostic,
    FileCtx,
    Rule,
    fingerprint,
    load_baseline,
    run_rules,
)
from dotaclient_tpu.lint import (
    config_drift,
    donation,
    host_sync,
    ownership,
    telemetry_drift,
)


def run_in_memory(rule, files_dict, baseline=(), strict=False):
    """Mirror of core.run_rules over in-memory sources: returns (new,
    suppressed) lists of (Diagnostic, fingerprint)."""
    files = {p: FileCtx(p, src) for p, src in files_dict.items()}
    new, suppressed = [], []
    for d in rule.check(files):
        ctx = files.get(d.path)
        if ctx is not None and d.line and ctx.waived(d.line, rule.id):
            continue
        fp = fingerprint(d, ctx)
        if not strict and fp in baseline:
            suppressed.append((d, fp))
        else:
            new.append((d, fp))
    return new, suppressed


# ---------------------------------------------------------------------------
# framework core


class TestFrameworkCore:
    def _fake_rule(self):
        class FakeRule(Rule):
            id = "fake"
            summary = "test"

            def paths(self):
                return ["mod.py"]

            def check(self, files):
                out = []
                for i, line in enumerate(files["mod.py"].lines, 1):
                    if "BAD" in line:
                        out.append(Diagnostic("mod.py", i, "fake", "boom"))
                return out

        return FakeRule()

    def test_positive_waiver_and_baseline(self, tmp_path):
        rule = self._fake_rule()
        src = "x = BAD\n"
        new, supp = run_in_memory(rule, {"mod.py": src})
        assert len(new) == 1 and new[0][0].rule == "fake"
        # annotated-ok: same line and line-above spellings
        assert run_in_memory(
            rule, {"mod.py": "x = BAD  # lint-ok: fake(known)\n"}
        ) == ([], [])
        assert run_in_memory(
            rule, {"mod.py": "# lint-ok: fake(known)\nx = BAD\n"}
        ) == ([], [])
        # baseline-suppressed; --strict un-suppresses
        fp = new[0][1]
        new2, supp2 = run_in_memory(rule, {"mod.py": src}, baseline=(fp,))
        assert new2 == [] and len(supp2) == 1
        new3, _ = run_in_memory(
            rule, {"mod.py": src}, baseline=(fp,), strict=True
        )
        assert len(new3) == 1

    def test_waiver_is_rule_scoped(self):
        rule = self._fake_rule()
        new, _ = run_in_memory(
            rule, {"mod.py": "x = BAD  # lint-ok: other-rule(nope)\n"}
        )
        assert len(new) == 1, "a waiver for another rule must not suppress"

    def test_waiver_comment_block_walkup(self):
        """A multi-line why in a contiguous comment block above the
        finding still waives — the why is encouraged to be thorough."""
        rule = self._fake_rule()
        src = (
            "# lint-ok: fake(a long explanation that\n"
            "# continues over several comment lines\n"
            "# before the flagged statement)\n"
            "x = BAD\n"
        )
        assert run_in_memory(rule, {"mod.py": src}) == ([], [])
        # ... but a non-comment line breaks the block
        src2 = "# lint-ok: fake(why)\ny = 1\nx = BAD\n"
        new, _ = run_in_memory(rule, {"mod.py": src2})
        assert len(new) == 1

    def test_waiver_requires_a_why(self):
        rule = self._fake_rule()
        new, _ = run_in_memory(
            rule, {"mod.py": "x = BAD  # lint-ok: fake()\n"}
        )
        assert len(new) == 1, "an empty why must not waive"

    def test_fingerprint_survives_line_drift(self):
        rule = self._fake_rule()
        (d1, fp1), = run_in_memory(rule, {"mod.py": "x = BAD\n"})[0]
        (d2, fp2), = run_in_memory(
            rule, {"mod.py": "# pushed down\n\n\nx = BAD\n"}
        )[0]
        assert d1.line != d2.line and fp1 == fp2, (
            "baseline identity hashes the source line, not its number"
        )

    def test_run_rules_on_disk(self, tmp_path):
        (tmp_path / "mod.py").write_text("x = BAD\n")
        rule = self._fake_rule()
        result = run_rules([rule], str(tmp_path), baseline=[])
        assert result.failed and result.per_rule["fake"] == 1
        fp = result.new[0][1]
        result2 = run_rules([rule], str(tmp_path), baseline=[fp])
        assert not result2.failed and len(result2.suppressed) == 1
        # stale entries are reported, never fatal — but only for rules
        # that actually ran (a --rule subset must not cry stale about
        # entries belonging to the rules it skipped)
        result3 = run_rules(
            [rule], str(tmp_path), baseline=[fp, "zz|fake|dead", "zz|other|x"]
        )
        assert result3.stale_baseline == ["zz|fake|dead"]


# ---------------------------------------------------------------------------
# host-sync (migrated pass; the script-level surface is pinned by
# tests/test_telemetry.py — here: the framework integration)


class TestHostSyncPass:
    def test_flags_and_both_annotation_spellings(self):
        src = (
            "def hot(m):\n"
            "    a = float(m['loss'])\n"
            "    b = float(m['x'])  # host-sync-ok: host int\n"
            "    c = float(m['y'])  # lint-ok: host-sync(host int)\n"
            "    return a, b, c\n"
        )
        findings = host_sync.scan_source(src, set(), "x.py")
        assert len(findings) == 1 and findings[0][0] == 2

    def test_rule_scans_its_module_list(self):
        rule = host_sync.HostSyncRule()
        bad = "def anywhere(m):\n    return float(m)\n"
        new, _ = run_in_memory(
            rule, {"dotaclient_tpu/train/snapshot.py": bad}
        )
        assert len(new) == 1 and "float()" in new[0][0].message
        # allowed function in an ALLOWED_FUNCS module stays clean
        ok = "def restore(m):\n    return float(m)\n"
        assert run_in_memory(
            rule, {"dotaclient_tpu/utils/checkpoint.py": ok}
        ) == ([], [])


# ---------------------------------------------------------------------------
# use-after-donate


DONATE_HEADER = "import jax\nstep = jax.jit(lambda s, b: s, donate_argnums=(0,))\n"


class TestUseAfterDonate:
    def _analyze(self, body, factories=None):
        ctx = FileCtx("dotaclient_tpu/x.py", DONATE_HEADER + body)
        return donation.analyze_module(ctx, factories or {})

    def test_flags_read_after_donate(self):
        """The TPU-silent-corruption repro: works on the CPU sandbox,
        corrupts on hardware — only the lint can catch it."""
        out = self._analyze(
            "def train(state, batch):\n"
            "    new_state, m = step(state, batch)\n"
            "    return new_state, state.loss\n"
        )
        assert len(out) == 1
        assert "state.loss" in out[0].message and "donated" in out[0].message

    def test_rebind_in_statement_is_the_idiom(self):
        out = self._analyze(
            "def train(state, batch):\n"
            "    state, m = step(state, batch)\n"
            "    return state.params\n"
        )
        assert out == []

    def test_later_rebind_ends_the_taint(self):
        out = self._analyze(
            "def train(state, batch, fresh):\n"
            "    out = step(state, batch)\n"
            "    state = fresh\n"
            "    return state.params\n"
        )
        assert out == []

    def test_attribute_extension_flags(self):
        """Donating `self.state` kills `self.state.params` too."""
        src = (
            "import jax\n"
            "class L:\n"
            "    def __init__(self):\n"
            "        self.step = jax.jit(f, donate_argnums=(0,))\n"
            "    def bad(self, batch):\n"
            "        out, m = self.step(self.state, batch)\n"
            "        return self.state.params\n"
        )
        out = donation.analyze_module(FileCtx("dotaclient_tpu/x.py", src), {})
        assert len(out) == 1 and "self.state.params" in out[0].message

    def test_factory_registry_cross_module(self):
        maker = (
            "import jax\n"
            "def make_step(f):\n"
            "    fn = jax.jit(f, donate_argnums=(0,))\n"
            "    return fn\n"
        )
        user = (
            "from m import make_step\n"
            "step = make_step(None)\n"
            "def train(s, b):\n"
            "    s2 = step(s, b)\n"
            "    return s.x\n"
        )
        files = {
            "dotaclient_tpu/m.py": FileCtx("dotaclient_tpu/m.py", maker),
            "dotaclient_tpu/u.py": FileCtx("dotaclient_tpu/u.py", user),
        }
        registry = donation.build_factory_registry(files)
        assert registry.get("make_step") == (0,)
        out = donation.analyze_module(files["dotaclient_tpu/u.py"], registry)
        assert len(out) == 1 and "'s.x'" in out[0].message

    def test_real_factories_are_registered(self):
        """The live registry must know the real donating factories —
        otherwise the pass is vacuous on the code that matters."""
        files = {}
        for rel in (
            "dotaclient_tpu/train/ppo.py",
        ):
            with open(os.path.join(REPO_ROOT, rel)) as f:
                files[rel] = FileCtx(rel, f.read())
        registry = donation.build_factory_registry(files)
        assert registry.get("make_train_step") == (0,)
        assert registry.get("make_epoch_step") == (0,)

    def test_instrument_jit_wrapper_is_transparent(self):
        """ISSUE 12: wrapping a donating jit (or factory call) in
        ``tracing.instrument_jit(...)`` must NOT drop its taint tracking
        — the wrapper is call-transparent, so a read-after-donate through
        it is exactly as corrupting as through the bare jit."""
        direct = (
            "import jax\n"
            "class B:\n"
            "    def __init__(self):\n"
            "        self.step = tracing.instrument_jit(\n"
            "            jax.jit(run, donate_argnums=(0,)), 'step')\n"
            "    def train(self, state, batch):\n"
            "        out = self.step(state, batch)\n"
            "        return state.params\n"
        )
        out = self._analyze(direct)
        assert len(out) == 1 and "'state.params'" in out[0].message
        via_factory = (
            "class L:\n"
            "    def __init__(self):\n"
            "        self.step = tracing.instrument_jit(\n"
            "            make_train_step(policy), 'train_step')\n"
            "    def train(self, state, batch):\n"
            "        out = self.step(state, batch)\n"
            "        return state.params\n"
        )
        out = self._analyze(via_factory, {"make_train_step": (0,)})
        assert len(out) == 1 and "'state.params'" in out[0].message

    def test_untrackable_donation_specs_flag_at_definition(self):
        """A donation the pass cannot position-track must say so — silent
        blindness to a donating callable is worse than any false
        positive (review finding: `donate_argnums=DONATE` used to slip
        through with no taint AND no diagnostic)."""
        for spec in (
            "donate_argnums=DONATE",
            "donate_argnums=(0, N)",
            "donate_argnames=('state',)",
        ):
            src = (
                f"import jax\n"
                f"step = jax.jit(run, {spec})\n"
                f"def train(state, batch):\n"
                f"    out = step(state, batch)\n"
                f"    return state.params\n"
            )
            out = donation.analyze_module(
                FileCtx("dotaclient_tpu/x.py", src), {}
            )
            assert out and "not statically trackable" in out[0].message, spec

    def test_waiver(self):
        rule = donation.UseAfterDonateRule()
        src = (
            DONATE_HEADER
            + "def train(state, batch):\n"
            + "    out = step(state, batch)\n"
            + "    # lint-ok: use-after-donate(read races the dispatch on\n"
            + "    # purpose in this debug-only helper)\n"
            + "    return state.loss\n"
        )
        new, _ = run_in_memory(rule, {"dotaclient_tpu/x.py": src})
        assert new == []


# ---------------------------------------------------------------------------
# thread-ownership — the three PR 5-6 race shapes are the fixtures


RACE_MAP = {
    "Learner": ownership.ClassMap(
        default_thread="train",
        methods={"_finish_metrics": "engine"},
        attrs={
            "_pending_best": "lock:_pending_best_lock",
            "_last_verdict_m": "train",
            "_monitor_state": "lock:_lock",
        },
        holds={"_fold_locked": ("_lock",)},
    ),
}


def scan_race(src):
    return ownership.scan_source_with_map("x.py", src, RACE_MAP)


class TestThreadOwnership:
    def test_race_shape_pending_best_unlocked_swap(self):
        """PR 5 race: the snapshot thread's metrics continuation wrote
        _pending_best while the train thread read-and-cleared it — an
        unsynchronized swap could drop a qualifying peak. The fixed code
        holds _pending_best_lock on both sides; the unlocked shape must
        flag."""
        bad = (
            "class Learner:\n"
            "    def _finish_metrics(self, scalars):\n"
            "        self._pending_best = dict(scalars)\n"
        )
        out = scan_race(bad)
        assert len(out) == 1 and "_pending_best_lock" in out[0].message
        good = (
            "class Learner:\n"
            "    def _finish_metrics(self, scalars):\n"
            "        with self._pending_best_lock:\n"
            "            self._pending_best = dict(scalars)\n"
        )
        assert scan_race(good) == []

    def test_race_shape_last_verdict_cross_thread(self):
        """PR 6 race: _last_verdict_m is train-owned sync-gate state
        (cleared by rollback, folded by sync boundaries); any engine-
        thread touch is the regression shape."""
        bad = (
            "class Learner:\n"
            "    def _finish_metrics(self, scalars):\n"
            "        self._last_verdict_m = None\n"
        )
        out = scan_race(bad)
        assert len(out) == 1
        assert "train thread" in out[0].message
        assert "engine thread" in out[0].message

    def test_race_shape_sync_gate_fold_outside_lock(self):
        """PR 6 race: the sync-mode gate folded verdicts on knowledge read
        outside the monitor's lock — lock-guarded attrs accessed outside
        `with self._lock:` must flag; the holds= contract (the *_locked
        helper convention) and the with-block both satisfy it."""
        bad = (
            "class Learner:\n"
            "    def gate(self):\n"
            "        return self._monitor_state\n"
        )
        assert len(scan_race(bad)) == 1
        good = (
            "class Learner:\n"
            "    def gate(self):\n"
            "        with self._lock:\n"
            "            return self._monitor_state\n"
            "    def _fold_locked(self):\n"
            "        return self._monitor_state\n"
        )
        assert scan_race(good) == []

    def test_closure_resolves_to_innermost_declared_def(self):
        src = (
            "class Learner:\n"
            "    def _make(self):\n"
            "        def _finish_metrics(host):\n"
            "            self._last_verdict_m = host\n"
            "        return _finish_metrics\n"
        )
        out = scan_race(src)
        assert len(out) == 1, "the nested engine-thread def must not hide"

    def test_init_is_exempt(self):
        src = (
            "class Learner:\n"
            "    def __init__(self):\n"
            "        self._pending_best = None\n"
            "        self._monitor_state = {}\n"
        )
        assert scan_race(src) == []

    def test_waiver(self):
        src = (
            "class Learner:\n"
            "    def _finish_metrics(self, s):\n"
            "        # lint-ok: thread-ownership(handoff after barrier)\n"
            "        self._last_verdict_m = s\n"
        )
        rule = ownership.ThreadOwnershipRule()
        files = {"x.py": FileCtx("x.py", src)}
        diags = ownership.scan_source_with_map("x.py", src, RACE_MAP)
        assert diags, "sanity: the access itself flags"
        assert files["x.py"].waived(diags[0].line, "thread-ownership")

    def test_shipped_map_covers_the_mandated_classes(self):
        """ISSUE 9 names the surfaces: Learner, SnapshotEngine,
        HealthMonitor, both transports; ISSUE 12 adds the trace writer."""
        declared = {
            cls for maps in ownership.OWNERSHIP.values() for cls in maps
        }
        for cls in (
            "Learner",
            "SnapshotEngine",
            "HealthMonitor",
            "TransportServer",
            "ShmTransportServer",
            "TraceWriter",
            "FleetAggregator",   # ISSUE 13: ingest/evaluate/read split
        ):
            assert cls in declared, f"{cls} missing from OWNERSHIP"

    def test_race_shape_trace_writer_producer_touches_file(self):
        """ISSUE 12 regression fixture: trace events are enqueued
        lock-free on producer threads and drained by ONE writer thread
        that alone owns the file — the obvious 'quick fix' of writing
        the file directly from the enqueue path is the race shape the
        shipped map must flag (and the baseline stays empty)."""
        trace_map = ownership.OWNERSHIP["dotaclient_tpu/utils/tracing.py"]
        bad = (
            "class TraceWriter:\n"
            "    def enqueue(self, event):\n"
            "        self._f.write(str(event))\n"   # producer → file: race
        )
        out = ownership.scan_source_with_map("x.py", bad, trace_map)
        assert len(out) == 1
        assert "writer thread" in out[0].message
        assert "producer thread" in out[0].message
        good = (
            "class TraceWriter:\n"
            "    def enqueue(self, event):\n"
            "        self._queue.append(event)\n"
            "    def _run(self):\n"
            "        self._f.write('x')\n"
        )
        assert ownership.scan_source_with_map("x.py", good, trace_map) == []

    def test_race_shape_fleet_ingest_touches_rule_state(self):
        """ISSUE 13 regression fixture: the fleet aggregator's ingest
        runs on transport READER threads and may only park snapshots in
        the locked inbox — an unlocked cross-thread touch of the
        merge/alert state (`_peers`, `_engine`) from the ingest path is
        the race shape the shipped map must flag (baseline stays empty)."""
        fleet_map = ownership.OWNERSHIP["dotaclient_tpu/utils/fleet.py"]
        bad = (
            "class FleetAggregator:\n"
            "    def ingest(self, payload):\n"
            "        self._peers['x'] = payload\n"      # reader → agg state
            "        self._engine.evaluate({})\n"       # reader → rule state
        )
        out = ownership.scan_source_with_map("x.py", bad, fleet_map)
        assert len(out) == 2
        assert all("agg thread" in d.message for d in out)
        assert all("reader thread" in d.message for d in out)
        # the shipped split is clean: park under the lock, merge on agg
        good = (
            "class FleetAggregator:\n"
            "    def ingest(self, payload):\n"
            "        with self._lock:\n"
            "            self._inbox.append(payload)\n"
            "    def tick(self):\n"
            "        with self._lock:\n"
            "            batch, self._inbox = self._inbox, []\n"
            "        self._peers.clear()\n"
            "        self._engine.evaluate({})\n"
        )
        assert ownership.scan_source_with_map("x.py", good, fleet_map) == []


# ---------------------------------------------------------------------------
# telemetry-drift


class TestTelemetryDrift:
    def _emit(self, src):
        files = {"dotaclient_tpu/x.py": FileCtx("dotaclient_tpu/x.py", src)}
        return telemetry_drift.extract_emitted(files)

    def test_extraction_idioms(self):
        src = (
            "class T:\n"
            "    def go(self):\n"
            "        self._tel.counter('a/one').inc()\n"
            "        with self._tel.span('b/two'):\n"
            "            pass\n"
            "        for key in ('c/three', 'c/four'):\n"
            "            self._tel.gauge(key)\n"
            "        for k in kinds:\n"
            "            self._tel.counter(f'snapshot/{k}_coalesced')\n"
        )
        keys, _sites, problems = self._emit(src)
        assert keys == {
            "a/one", "span/b/two", "c/three", "c/four",
            "snapshot/publish_coalesced", "snapshot/checkpoint_coalesced",
            "snapshot/metrics_coalesced",
        }
        assert problems == []

    def test_unresolvable_key_flags(self):
        keys, _sites, problems = self._emit(
            "def go(tel, name):\n    tel.counter(f'x/{name}_total')\n"
        )
        assert keys == set() and len(problems) == 1
        assert "not statically resolvable" in problems[0].message

    def test_doc_key_extraction(self):
        doc = (
            "Keys: `transport/queue_depth`, the set "
            "`buffer/dropped_{overflow,stale}`, spans `actor/collect`, "
            "wildcards `league/eval_*` and `snapshot/<kind>_coalesced`; "
            "not keys: `envs/lane_sim.py`, `obs/hero_id`, `deploy/`.\n"
        )
        exact, patterns = telemetry_drift.extract_doc_keys(doc)
        assert exact == {
            "transport/queue_depth", "buffer/dropped_overflow",
            "buffer/dropped_stale", "actor/collect",
        }
        assert any(p.match("league/eval_win_rate") for p in patterns)
        assert any(p.match("snapshot/metrics_coalesced") for p in patterns)

    def test_drift_directions(self):
        emitted = {"transport/queue_depth", "span/actor/collect", "x/rogue"}
        sites = [(k, 1, "dotaclient_tpu/x.py") for k in emitted]
        doc = "`transport/queue_depth` `actor/collect` `transport/ghost`\n"
        tiers = {"FAKE_KEYS": ["transport/queue_depth", "buffer/never"]}
        out = telemetry_drift.drift_findings(emitted, sites, doc, tiers)
        msgs = "\n".join(d.message for d in out)
        assert "'buffer/never' is required by schema tier FAKE_KEYS" in msgs
        assert "'transport/ghost' is documented" in msgs
        assert "'x/rogue' is emitted" in msgs
        # the satisfied keys produce no findings
        contexts = {d.context for d in out}
        assert "transport/queue_depth" not in contexts
        assert "span/actor/collect" not in contexts

    def test_span_leaf_tier_keys_resolve_to_roots(self):
        emitted = {"span/learner/dispatch"}
        tiers = {"REQUIRED_KEYS": ["span/learner/dispatch/mean_s"]}
        out = telemetry_drift.drift_findings(
            emitted, [], "`learner/dispatch`\n", tiers
        )
        assert out == []

    def test_reverting_pr7_doc_additions_fails_the_pass(self):
        """Acceptance criterion: strip the quantized-experience-plane key
        documentation (the PR 7 additions) from the REAL ARCHITECTURE.md
        and the drift pass must fail on the real emitted set."""
        rule = telemetry_drift.TelemetryDriftRule()
        files = {}
        for rel in rule.paths():
            path = os.path.join(REPO_ROOT, rel)
            if os.path.exists(path):
                with open(path) as f:
                    files[rel] = FileCtx(rel, f.read())
        # sanity: the real tree is clean
        assert rule.check(files) == []
        doc = files[telemetry_drift.ARCHITECTURE_MD]
        stripped = "\n".join(
            line
            for line in doc.source.splitlines()
            if "transport/rollout_" not in line
        )
        files[telemetry_drift.ARCHITECTURE_MD] = FileCtx(
            telemetry_drift.ARCHITECTURE_MD, stripped
        )
        findings = rule.check(files)
        flagged = {d.context for d in findings}
        assert {
            "transport/rollout_bytes_total",
            "transport/rollout_raw_bytes_total",
            "transport/rollout_compression_ratio",
        } <= flagged, "undocumenting the PR 7 keys must fail the pass"


# ---------------------------------------------------------------------------
# config-drift


CFG_SRC = (
    "import dataclasses\n"
    "@dataclasses.dataclass(frozen=True)\n"
    "class BufferConfig:\n"
    "    capacity: int = 4\n"
    "    min_fill: int = 2\n"
)

CLI_SRC = (
    "def main():\n"
    "    import argparse\n"
    "    p = argparse.ArgumentParser()\n"
    "    p.add_argument('--steps', type=int)\n"
    "    p.add_argument('--buffer', type=str)\n"
)


class TestConfigDrift:
    def test_extractors(self):
        assert config_drift.dataclass_fields(CFG_SRC) == {
            "BufferConfig": ["capacity", "min_fill"]
        }
        assert config_drift.cli_flags(CLI_SRC) == {"--steps", "--buffer"}
        doc = (
            "Run with `--steps 5` or --buffer k=v; env "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 is not a "
            "flag, nor is ---rule.\n"
        )
        flags = config_drift.documented_flags(doc)
        assert set(flags) == {"--steps", "--buffer"}

    def test_knob_table_parsing(self):
        doc = (
            "### `--buffer` (BufferConfig)\n\n"
            "| knob | default | what |\n|---|---|---|\n"
            "| `capacity` | 4 | slots |\n"
            "| `min_fill` | 2 | gate |\n"
        )
        tables = config_drift.knob_tables(doc)
        assert tables["--buffer"][0] == "BufferConfig"
        assert set(tables["--buffer"][1]) == {"capacity", "min_fill"}

    def test_knob_table_closed_by_next_heading(self):
        """A later unrelated backticked-first-column table must not be
        misattributed to the last knob table (review finding)."""
        doc = (
            "### `--buffer` (BufferConfig)\n\n"
            "| knob | default | what |\n|---|---|---|\n"
            "| `capacity` | 4 | slots |\n"
            "\n## Some later section\n\n"
            "| `some_metric` | 1 |\n"
        )
        tables = config_drift.knob_tables(doc)
        assert set(tables["--buffer"][1]) == {"capacity"}

    def _drift(self, doc):
        fields = config_drift.dataclass_fields(CFG_SRC)
        flags = {
            "dotaclient_tpu/train/learner.py": config_drift.cli_flags(
                CLI_SRC
            ),
        }
        return config_drift.drift_findings(fields, flags, doc)

    def test_missing_and_stale_knob_rows(self):
        doc = (
            "`--steps` `--buffer`\n"
            "### `--buffer` (BufferConfig)\n\n"
            "| knob | default | what |\n|---|---|---|\n"
            "| `capacity` | 4 | slots |\n"
            "| `renamed_away` | 0 | gone |\n"
        )
        msgs = "\n".join(d.message for d in self._drift(doc))
        assert "BufferConfig.min_fill is reachable" in msgs
        assert "'renamed_away' but BufferConfig has no such field" in msgs

    def test_documented_flag_must_exist(self):
        doc = (
            "`--steps` `--buffer` `--does-not-exist`\n"
            "### `--buffer` (BufferConfig)\n\n"
            "| knob | default | what |\n|---|---|---|\n"
            "| `capacity` | 4 | slots |\n"
            "| `min_fill` | 2 | gate |\n"
        )
        msgs = "\n".join(d.message for d in self._drift(doc))
        assert "--does-not-exist" in msgs and "no entrypoint" in msgs

    def test_operator_cli_flags_must_be_documented(self):
        doc = (
            "`--buffer`\n"
            "### `--buffer` (BufferConfig)\n\n"
            "| knob | default | what |\n|---|---|---|\n"
            "| `capacity` | 4 | slots |\n"
            "| `min_fill` | 2 | gate |\n"
        )
        msgs = "\n".join(d.message for d in self._drift(doc))
        assert "--steps is defined by dotaclient_tpu/train/learner.py" in msgs

    def test_learner_override_flag_parses(self):
        """The --learner K=V surface the pass documents must actually
        parse (satellite: LearnerConfig joined the override family)."""
        from dotaclient_tpu.config import LearnerConfig
        from dotaclient_tpu.utils.overrides import parse_dataclass_overrides

        out = parse_dataclass_overrides(
            LearnerConfig, "async_snapshots=false,snapshot_drain_timeout_s=5",
            "--learner",
        )
        assert out == {
            "async_snapshots": False, "snapshot_drain_timeout_s": 5.0,
        }
        with pytest.raises(ValueError, match="--learner"):
            parse_dataclass_overrides(LearnerConfig, "nope=1", "--learner")


# ---------------------------------------------------------------------------
# tier-1 wrapper — the acceptance criterion


class TestTier1Wrapper:
    def test_lint_clean_on_head(self, capsys):
        """`python -m dotaclient_tpu.lint` exits 0 on HEAD with every pass
        active. Non-strict by default; LINT_STRICT=1 escalates to --strict
        (baseline debt fails too), the TIER1_DURATION_STRICT pattern."""
        from dotaclient_tpu.lint.__main__ import main

        argv = ["--strict"] if os.environ.get("LINT_STRICT") == "1" else []
        rc = main(argv)
        out = capsys.readouterr()
        assert rc == 0, f"graftlint failed on HEAD:\n{out.err}"
        assert len(ALL_RULES) >= 4, "ISSUE 9 mandates >= 4 passes"
        assert "graftlint OK" in out.out

    def test_baseline_file_is_tracked_and_loadable(self):
        path = os.path.join(REPO_ROOT, "dotaclient_tpu/lint/baseline.txt")
        entries = load_baseline(path)
        for fp in entries:
            assert fp.count("|") == 2, f"malformed baseline entry {fp!r}"

    def test_rule_subset_update_preserves_other_rules_entries(self, tmp_path):
        """--rule X --update-baseline must not wipe other rules' baseline
        blocks or their tracking comments (review finding: it rewrote the
        file from only the selected rules' findings)."""
        from dotaclient_tpu.lint.core import (
            baseline_rule,
            load_baseline_blocks,
            write_baseline,
        )

        path = str(tmp_path / "baseline.txt")
        write_baseline(
            path,
            [
                (
                    "a.py|kept-rule|aaaaaaaaaaaa",
                    Diagnostic("a.py", 1, "kept-rule", "kept finding"),
                ),
                (
                    "b.py|run-rule|bbbbbbbbbbbb",
                    Diagnostic("b.py", 2, "run-rule", "regenerated"),
                ),
            ],
        )
        blocks = load_baseline_blocks(path)
        assert [fp for _c, fp in blocks] == [
            "a.py|kept-rule|aaaaaaaaaaaa", "b.py|run-rule|bbbbbbbbbbbb",
        ]
        # simulate `--rule run-rule --update-baseline` finding nothing:
        # the kept-rule block (comment included) must survive verbatim
        preserved = [
            (c, fp) for c, fp in blocks if baseline_rule(fp) != "run-rule"
        ]
        write_baseline(path, [], preserved=preserved)
        blocks2 = load_baseline_blocks(path)
        assert [fp for _c, fp in blocks2] == ["a.py|kept-rule|aaaaaaaaaaaa"]
        assert any("kept finding" in c for c in blocks2[0][0])

    def test_rule_catalog_lists_all_passes(self, capsys):
        from dotaclient_tpu.lint.__main__ import main

        assert main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for cls in ALL_RULES:
            assert cls.id in out

    def test_single_rule_selection(self, capsys):
        from dotaclient_tpu.lint.__main__ import main

        assert main(["--rule", "host-sync"]) == 0
        assert "[rules: host-sync]" in capsys.readouterr().out
