"""Pod-scale fused Anakin tests (PR 18): the lane-sharded one-dispatch
program.

The tentpole's contract, pinned from four sides:

* the COMPILED fused program takes its actor state lane-sharded (the
  ``input_shardings`` proof — a replicated layout means broadcast
  rollouts even when the numbers still agree);
* the lane-sharded rollout is BITWISE the 1-device rollout in-process
  (per-game keys partition random-bit generation with the games; stat
  partials reduce only the step axis — the rollout has no collective to
  reassociate; the cross-process ``--fused-parity`` digest allows 1e-7
  relative for backend tiling differences) and fused losses track
  within Adam-amplified reassociation tolerance;
* the shard-local minibatch permutation (``lane_minibatches``) is
  deterministic in (seed, step), partitions the lane set exactly, and
  never moves a lane across shards;
* actor state round-trips host-layout across mesh sizes (8→1 and 1→8),
  because the per-game partial shapes are shard-count independent.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dotaclient_tpu.config import default_config


def tiny_cfg(n_envs=8, opponent="scripted_easy", small_model=False):
    cfg = default_config()
    model = dataclasses.replace(cfg.model, dtype="float32")
    if small_model:
        # layout/error-path tests never check learned behaviour — a
        # narrow core keeps their construction cost out of tier-1
        model = dataclasses.replace(
            model, unit_embed_dim=8, hidden_dim=16, hero_embed_dim=4
        )
    return dataclasses.replace(
        cfg,
        model=model,
        ppo=dataclasses.replace(cfg.ppo, rollout_len=4, batch_rollouts=8),
        env=dataclasses.replace(
            cfg.env, n_envs=n_envs, opponent=opponent, max_dota_time=60.0
        ),
        buffer=dataclasses.replace(
            cfg.buffer, capacity_rollouts=16, min_fill=8
        ),
        log_every=1,
    )


def _build(cfg, mesh, seed=3):
    from dotaclient_tpu.actor.device_rollout import DeviceActor
    from dotaclient_tpu.models import init_params, make_policy
    from dotaclient_tpu.train.ppo import init_train_state, train_state_sharding

    policy = make_policy(cfg.model, cfg.obs, cfg.actions)
    actor = DeviceActor(
        cfg, policy, seed=seed, mesh=mesh, mesh_config=cfg.mesh
    )
    state = jax.device_put(
        init_train_state(
            init_params(policy, jax.random.PRNGKey(0)), cfg.ppo
        ),
        train_state_sharding(policy, cfg, mesh),
    )
    return policy, actor, state


class TestLaneShardedCompile:
    @pytest.mark.slow   # full fused compile at 8 devices, ~27s; the same
    # proof runs on every ci_gate pass via the fused-parity stage's probe
    def test_fused_step_pins_lane_sharded_actor_state(self):
        """The compiled program's actor-state argument must hold
        DATA-SHARDED lane arrays — sim worlds, carries, per-game keys,
        episode returns, stat partials — with only true scalars and the
        sim's batch-wide key replicated."""
        from dotaclient_tpu.parallel import make_mesh
        from dotaclient_tpu.train.fused import make_fused_step

        cfg = tiny_cfg()
        mesh = make_mesh(cfg.mesh)   # conftest's 8 forced host devices
        policy, actor, state = _build(cfg, mesh)
        assert actor.lane_shards == 8
        fused = make_fused_step(policy, cfg, mesh, actor)
        in_sh = fused.lower(
            state, actor.state, state.params
        ).compile().input_shardings[0]
        actor_sh = in_sh[1]
        assert not actor_sh.ep_return.is_fully_replicated
        assert not actor_sh.key.is_fully_replicated       # per-game [N, 2]
        assert not actor_sh.carry[0].is_fully_replicated  # lane-major LSTM
        assert actor_sh.sim.key.is_fully_replicated       # batch-wide [2]
        sharded = [
            s for s in jax.tree.leaves(actor_sh)
            if not s.is_fully_replicated
        ]
        # the bulk of the state must be partitioned, not a token leaf
        assert len(sharded) >= len(jax.tree.leaves(actor_sh)) // 2

    def test_degenerate_games_fall_back_to_replicated(self):
        """4 games on an 8-way mesh cannot lane-shard: the layout must
        degrade to replicated (lane_shards == 1) instead of failing."""
        from dotaclient_tpu.parallel import make_mesh

        cfg = tiny_cfg(n_envs=4, small_model=True)
        mesh = make_mesh(cfg.mesh)
        _, actor, _ = _build(cfg, mesh)
        assert actor.lane_shards == 1
        assert actor.lanes_per_shard == actor.n_lanes
        for leaf in jax.tree.leaves(actor.state):
            assert leaf.sharding.is_fully_replicated


class TestShardCountParity:
    @pytest.mark.slow   # two mesh sizes × (rollout + fused) compiles, ~1 min
    def test_rollout_bitwise_and_losses_close_8_vs_1(self):
        """Same seeds, 8-way lane-sharded vs 1-device: the rollout chunk
        must be BYTE-IDENTICAL (no collective in the rollout), and fused
        losses over 3 dispatches must agree within the Adam-amplified
        reassociation tolerance (the gradient psum reorders sums;
        ``1/(sqrt(v)+eps)`` amplifies ~1e-7 deltas on near-zero-gradient
        coordinates — scripts/run_multichip.py --fused-parity gates the
        same three tiers cross-process)."""
        from dotaclient_tpu.parallel import make_mesh
        from dotaclient_tpu.train.fused import make_fused_step

        cfg = tiny_cfg()
        cfg = dataclasses.replace(
            cfg, ppo=dataclasses.replace(cfg.ppo, minibatches=1)
        )
        mesh8 = make_mesh(cfg.mesh)
        mesh1 = make_mesh(cfg.mesh, devices=jax.devices()[:1])

        runs = {}
        for name, mesh in (("8", mesh8), ("1", mesh1)):
            policy, actor, state = _build(cfg, mesh)
            _, chunk, _ = jax.jit(actor._rollout_impl)(
                state.params, actor.state, state.params
            )
            fused = make_fused_step(policy, cfg, mesh, actor)
            ast, losses = actor.state, []
            for _ in range(3):
                state, ast, metrics, _stats = fused(
                    state, ast, state.params
                )
                losses.append(float(np.asarray(metrics["loss"])))
            runs[name] = (jax.device_get(chunk), losses)

        chunk8, losses8 = runs["8"]
        chunk1, losses1 = runs["1"]
        for a, b in zip(jax.tree.leaves(chunk8), jax.tree.leaves(chunk1)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(losses8, losses1):
            assert abs(a - b) <= max(1e-3, 2e-2 * abs(a)), (losses8, losses1)

    def test_outcome_partials_shard_local_and_reduce_invariant(self):
        """Per-game outcome partials computed on game slices equal the
        matching rows of the full-batch partials (nothing crosses the
        game axis), and the host-side reduction is bitwise independent
        of how the games were split."""
        from dotaclient_tpu.outcome import ingraph

        T, N = 6, 8
        rng = np.random.default_rng(7)
        ep_done = jnp.asarray(rng.random((T, N)) < 0.3)
        win = jnp.asarray(rng.random((T, N)) < 0.5)
        ep_len = jnp.asarray(
            rng.integers(1, 2000, size=(T, N)).astype(np.float32)
        ) * ep_done
        full = ingraph.chunk_outcome_partials(ep_done, win, ep_len)
        for s0, s1 in ((0, 4), (4, 8)):
            part = ingraph.chunk_outcome_partials(
                ep_done[:, s0:s1], win[:, s0:s1], ep_len[:, s0:s1]
            )
            for k, v in part.items():
                np.testing.assert_array_equal(
                    np.asarray(v), np.asarray(full[k][s0:s1])
                )
        reduced = ingraph.reduce_outcome_stats(full)
        direct = ingraph.chunk_outcome_stats(ep_done, win, ep_len)
        for k in reduced:
            np.testing.assert_array_equal(
                np.asarray(reduced[k]), np.asarray(direct[k])
            )


class TestShardLocalShuffle:
    def _lanes(self, L):
        return {"x": jnp.arange(L, dtype=jnp.int32)}

    def test_permutation_deterministic_and_partitioning(self):
        from dotaclient_tpu.train.fused import lane_minibatches

        L, S, M = 32, 8, 2
        a = lane_minibatches(self._lanes(L), jnp.asarray(5), 0, L, S, M)
        b = lane_minibatches(self._lanes(L), jnp.asarray(5), 0, L, S, M)
        np.testing.assert_array_equal(np.asarray(a["x"]), np.asarray(b["x"]))
        c = lane_minibatches(self._lanes(L), jnp.asarray(6), 0, L, S, M)
        assert not np.array_equal(np.asarray(a["x"]), np.asarray(c["x"]))
        # exact partition: every lane appears exactly once across the
        # minibatches
        flat = np.sort(np.asarray(a["x"]).ravel())
        np.testing.assert_array_equal(flat, np.arange(L))

    def test_permutation_never_crosses_shards(self):
        """Each minibatch takes exactly Ls/M lanes from every shard's
        contiguous lane block — the gather is local, so minibatching
        adds no collective."""
        from dotaclient_tpu.train.fused import lane_minibatches

        L, S, M = 32, 8, 2
        Ls = L // S
        out = np.asarray(
            lane_minibatches(self._lanes(L), jnp.asarray(11), 3, L, S, M)["x"]
        )
        assert out.shape == (M, L // M)
        for m in range(M):
            for s in range(S):
                in_block = np.sum(
                    (out[m] >= s * Ls) & (out[m] < (s + 1) * Ls)
                )
                assert in_block == Ls // M, (m, s, out[m])


class TestCrossShardCountActorRestore:
    @pytest.mark.slow   # two mesh sizes × rollout compiles, ~40s
    def test_actor_state_roundtrips_8_to_1_and_back(self):
        """The fused pipeline checkpoint stores the actor state as
        host-layout numpy (shard-count-free, because stats are per-game
        partials); re-committing through actor_state_sharding on a
        DIFFERENT mesh size must reproduce the source rollout bitwise —
        the learner's _restore_pipeline path in both directions."""
        from dotaclient_tpu.actor.device_rollout import actor_state_sharding
        from dotaclient_tpu.parallel import make_mesh

        cfg = tiny_cfg()
        mesh8 = make_mesh(cfg.mesh)
        mesh1 = make_mesh(cfg.mesh, devices=jax.devices()[:1])
        for src_mesh, dst_mesh in ((mesh8, mesh1), (mesh1, mesh8)):
            policy, actor, state = _build(cfg, src_mesh)
            # advance once so the restored state is non-trivial
            roll = jax.jit(actor._rollout_impl)
            ast, _chunk0, _ = roll(state.params, actor.state, state.params)
            host = jax.tree.map(
                lambda x: np.asarray(jax.device_get(x)), ast
            )
            _, dst_actor, dst_state = _build(cfg, dst_mesh)
            committed = jax.device_put(
                host, actor_state_sharding(host, dst_mesh, cfg.mesh)
            )
            # the SECOND rollout, from the same advanced state, on each
            # mesh — identical params (same init key), so byte-equal
            _, src_chunk, _ = roll(state.params, ast, state.params)
            _, dst_chunk, _ = jax.jit(dst_actor._rollout_impl)(
                dst_state.params, committed, dst_state.params
            )
            for a, b in zip(
                jax.tree.leaves(jax.device_get(src_chunk)),
                jax.tree.leaves(jax.device_get(dst_chunk)),
            ):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestDivisibilityError:
    def test_minibatch_lane_divisibility_pinned_message(self):
        """32 lanes / 8 shards / 3 minibatches cannot split: the fused
        constructor must raise a clear ValueError naming the operative
        product — never the opaque mid-compile XLA reshape error."""
        from dotaclient_tpu.parallel import make_mesh
        from dotaclient_tpu.train.fused import make_fused_step

        cfg = tiny_cfg(n_envs=32, small_model=True)
        cfg = dataclasses.replace(
            cfg, ppo=dataclasses.replace(cfg.ppo, minibatches=3)
        )
        mesh = make_mesh(cfg.mesh)
        policy, actor, _state = _build(cfg, mesh)
        with pytest.raises(ValueError, match="divisible"):
            make_fused_step(policy, cfg, mesh, actor)
