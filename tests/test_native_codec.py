"""Native rollout-codec tests: build, exact parity with the protobuf path,
zero-copy semantics, malformed-input fallback (SURVEY.md §2.2 row 3)."""

import numpy as np
import pytest

from dotaclient_tpu.transport.serialize import (
    decode_rollout,
    decode_rollout_bytes,
    encode_rollout,
    encode_rollout_bytes,
)
from dotaclient_tpu.protos import dota_pb2 as pb


@pytest.fixture(scope="module")
def native_lib():
    from dotaclient_tpu.native.build import load_library

    lib = load_library()
    if lib is None:
        pytest.skip("native toolchain unavailable")
    return lib


def sample_rollout(seed=0):
    rng = np.random.default_rng(seed)
    arrays = {
        "obs": {
            "units": rng.normal(size=(17, 32, 22)).astype(np.float32),
            "unit_mask": rng.random((17, 32)) > 0.5,
            "hero_id": np.arange(17, dtype=np.int32),
        },
        "rewards": rng.normal(size=(16,)).astype(np.float32),
        "dones": np.zeros((16,), np.float32),
        "carry0": (
            rng.normal(size=(128,)).astype(np.float32),
            rng.normal(size=(128,)).astype(np.float32),
        ),
    }
    return encode_rollout(
        arrays, model_version=7, env_id=3, rollout_id=123456789,
        length=16, total_reward=-2.5,
    )


class TestNativeCodec:
    def test_exact_parity_with_protobuf(self, native_lib):
        import jax

        r = sample_rollout()
        payload = r.SerializeToString()
        m_py, a_py = decode_rollout(r)
        m_nat, a_nat = decode_rollout_bytes(payload, native=True)
        assert m_py == m_nat
        jax.tree.map(
            lambda x, y: np.testing.assert_array_equal(
                np.asarray(x), np.asarray(y)
            ),
            a_py, a_nat,
        )

    def test_bfloat16_payload(self, native_lib):
        import ml_dtypes

        arrays = {"x": np.arange(8).astype(ml_dtypes.bfloat16)}
        r = encode_rollout(arrays, model_version=0, env_id=0, rollout_id=0,
                           length=1, total_reward=0.0)
        _, a = decode_rollout_bytes(r.SerializeToString())
        assert a["x"].dtype == ml_dtypes.bfloat16
        np.testing.assert_array_equal(np.asarray(a["x"], np.float32),
                                      np.arange(8, dtype=np.float32))

    def test_zero_copy_views(self, native_lib):
        payload = sample_rollout().SerializeToString()
        _, a = decode_rollout_bytes(payload, native=True)
        units = a["obs"]["units"]
        assert units.base is not None  # a view, not an owning copy
        assert not units.flags.writeable

    def test_malformed_input_falls_back_or_raises_cleanly(self, native_lib):
        with pytest.raises(Exception):
            decode_rollout_bytes(b"\xff\xff\xff\xff\x00garbage")

    def test_python_fallback_matches(self):
        r = sample_rollout(seed=3)
        payload = r.SerializeToString()
        m1, a1 = decode_rollout_bytes(payload, native=False)
        m2, a2 = decode_rollout(r)
        assert m1 == m2
        np.testing.assert_array_equal(a1["rewards"], a2["rewards"])


def sample_arrays_meta(seed=0):
    rng = np.random.default_rng(seed)
    arrays = {
        "obs": {
            "units": rng.normal(size=(17, 32, 22)).astype(np.float32),
            "unit_mask": rng.random((17, 32)) > 0.5,
            "hero_id": np.arange(17, dtype=np.int32),
        },
        "rewards": rng.normal(size=(16,)).astype(np.float32),
        "scalar": np.float32(2.5),
        "carry0": (
            rng.normal(size=(128,)).astype(np.float32),
            rng.normal(size=(128,)).astype(np.float32),
        ),
    }
    meta = dict(model_version=7, env_id=3, rollout_id=123456789,
                length=16, total_reward=-2.5)
    return arrays, meta


class TestNativeEncoder:
    def test_protobuf_parses_native_bytes_identically(self, native_lib):
        """python-protobuf must parse the C writer's output to the exact
        message the protobuf encoder would have produced."""
        arrays, meta = sample_arrays_meta()
        payload = encode_rollout_bytes(arrays, native=True, **meta)
        want = encode_rollout(arrays, **meta)
        got = pb.Rollout()
        got.ParseFromString(payload)
        assert got.model_version == want.model_version
        assert got.env_id == want.env_id
        assert got.rollout_id == want.rollout_id
        assert got.length == want.length
        assert got.total_reward == pytest.approx(want.total_reward)
        assert set(got.arrays) == set(want.arrays)
        for name in want.arrays:
            assert got.arrays[name] == want.arrays[name], name

    def test_roundtrip_through_native_decoder(self, native_lib):
        import jax

        arrays, meta = sample_arrays_meta(seed=5)
        payload = encode_rollout_bytes(arrays, native=True, **meta)
        m, a = decode_rollout_bytes(payload, native=True)
        assert m == {**meta, "total_reward": pytest.approx(-2.5)}
        flat_in = {
            k: np.asarray(v)
            for k, v in jax.tree_util.tree_flatten_with_path(arrays)[0]
        }
        flat_out = {
            k: np.asarray(v)
            for k, v in jax.tree_util.tree_flatten_with_path(a)[0]
        }
        assert set(map(str, flat_in)) == set(map(str, flat_out))
        for k, v in flat_in.items():
            np.testing.assert_array_equal(v, flat_out[k])

    def test_zero_header_and_empty_array(self, native_lib):
        arrays = {"empty": np.zeros((0, 4), np.float32),
                  "x": np.ones((3,), np.int32)}
        meta = dict(model_version=0, env_id=0, rollout_id=0, length=0,
                    total_reward=0.0)
        payload = encode_rollout_bytes(arrays, native=True, **meta)
        # zero-valued scalars are omitted on the wire (proto3), so the
        # protobuf encoding must be byte-identical modulo map order; with
        # sorted single-pass writes we just check the parse.
        m, a = decode_rollout_bytes(payload, native=True)
        assert m["model_version"] == 0 and m["total_reward"] == 0.0
        assert a["empty"].shape == (0, 4)
        np.testing.assert_array_equal(a["x"], np.ones((3,), np.int32))

    def test_bfloat16_roundtrip(self, native_lib):
        import ml_dtypes

        arrays = {"x": np.arange(8).astype(ml_dtypes.bfloat16)}
        payload = encode_rollout_bytes(
            arrays, model_version=1, env_id=0, rollout_id=0, length=1,
            total_reward=0.0, native=True,
        )
        _, a = decode_rollout_bytes(payload)
        assert a["x"].dtype == ml_dtypes.bfloat16
        np.testing.assert_array_equal(
            np.asarray(a["x"], np.float32), np.arange(8, dtype=np.float32)
        )

    def test_fallback_matches_native(self, native_lib):
        arrays, meta = sample_arrays_meta(seed=9)
        nat = encode_rollout_bytes(arrays, native=True, **meta)
        py = encode_rollout_bytes(arrays, native=False, **meta)
        a = pb.Rollout(); a.ParseFromString(nat)
        b = pb.Rollout(); b.ParseFromString(py)
        assert a == b
