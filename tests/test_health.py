"""Training health guardian tests (ISSUE 6): the detect → contain →
recover loop plus checkpoint integrity.

* detect: the in-graph ``health_ok`` probe and its scan AND-fold, the
  HealthMonitor's latch/EMA-band/generation semantics;
* contain: the snapshot engine's publish/checkpoint gates;
* recover: manifest round-trip, corrupt-leaf walk-back, the last_good
  slot surviving retention GC, divergence rollback in a real learner,
  and rollback exhaustion exiting loudly with the runbook pointer;
* admit: the buffer door's staleness counter and non-finite rejection.

The multi-process divergence scenario lives in scripts/chaos_run.py
(--scenario divergence) and its slow-marked wrapper in test_chaos.py.
"""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dotaclient_tpu.config import HealthConfig, RunConfig, default_config
from dotaclient_tpu.train.health import HealthMonitor
from dotaclient_tpu.utils import faults, telemetry


@pytest.fixture()
def registry():
    return telemetry.Registry()


@pytest.fixture(autouse=True)
def _no_faults():
    yield
    faults.configure(None)


def tiny_cfg(**kw):
    cfg = default_config()
    cfg = dataclasses.replace(
        cfg,
        model=dataclasses.replace(cfg.model, dtype="float32"),
        ppo=dataclasses.replace(cfg.ppo, rollout_len=4, batch_rollouts=8),
        env=dataclasses.replace(cfg.env, n_envs=8, max_dota_time=60.0),
        buffer=dataclasses.replace(
            cfg.buffer, capacity_rollouts=16, min_fill=8
        ),
        log_every=1,
    )
    return dataclasses.replace(cfg, **kw)


class TestMonitor:
    def test_healthy_folds_do_not_latch(self, registry):
        m = HealthMonitor(HealthConfig(), registry)
        for i in range(10):
            m.fold_host(i, i, {"loss": 0.1, "grad_norm": 0.5, "health_ok": 1.0})
        assert m.unhealthy is None
        assert registry.snapshot()["health/nonfinite_steps_total"] == 0

    def test_nonfinite_latches_and_counts(self, registry):
        m = HealthMonitor(HealthConfig(), registry)
        m.fold_host(1, 1, {"loss": 0.1, "grad_norm": 0.5, "health_ok": 1.0})
        m.fold_host(2, 2, {"loss": float("nan"), "grad_norm": 0.5, "health_ok": 0.0})
        ev = m.unhealthy
        assert ev is not None and ev.reason == "nonfinite" and ev.step == 2
        # the latch holds: later healthy folds do not clear it
        m.fold_host(3, 3, {"loss": 0.1, "grad_norm": 0.5, "health_ok": 1.0})
        assert m.unhealthy is ev
        assert registry.snapshot()["health/nonfinite_steps_total"] == 1

    def test_probe_flag_latches_even_with_finite_scalars(self, registry):
        """The device-side flag is authoritative: a scanned program whose
        LAST update looks finite still reports the AND-folded 0."""
        m = HealthMonitor(HealthConfig(), registry)
        m.fold_host(1, 1, {"loss": 0.1, "grad_norm": 0.5, "health_ok": 0.0})
        assert m.unhealthy is not None

    def test_ema_band_catches_explosion(self, registry):
        cfg = HealthConfig(warmup_steps=5, explosion_band=10.0, ema_alpha=0.5)
        m = HealthMonitor(cfg, registry)
        for i in range(6):
            m.fold_host(i, i, {"loss": 0.1, "grad_norm": 1.0, "health_ok": 1.0})
        assert m.unhealthy is None
        m.fold_host(7, 7, {"loss": 0.1, "grad_norm": 50.0, "health_ok": 1.0})
        ev = m.unhealthy
        assert ev is not None and ev.reason == "explosion"
        assert registry.snapshot()["health/ema_breaches_total"] == 1

    def test_ema_band_disarmed_during_warmup(self, registry):
        cfg = HealthConfig(warmup_steps=50, explosion_band=10.0)
        m = HealthMonitor(cfg, registry)
        m.fold_host(0, 0, {"loss": 0.1, "grad_norm": 1.0, "health_ok": 1.0})
        m.fold_host(1, 1, {"loss": 0.1, "grad_norm": 500.0, "health_ok": 1.0})
        assert m.unhealthy is None

    def test_clear_discards_stale_generation(self, registry):
        """Entries submitted before a rollback's clear() are the abandoned
        timeline's verdicts — folding them afterwards must be a no-op."""
        m = HealthMonitor(HealthConfig(), registry)
        m.submit(5, 5, {"loss": jnp.float32(float("nan")),
                        "grad_norm": jnp.float32(1.0),
                        "health_ok": jnp.float32(0.0)})
        stale = m.take_pending()
        m.clear()
        m.fold_batch([(g, s, v, jax.device_get(t)) for g, s, v, t in stale])
        assert m.unhealthy is None   # old-generation entries discarded

    def test_batched_submit_take_fold(self, registry):
        m = HealthMonitor(HealthConfig(), registry)
        for i in range(3):
            m.submit(i, i, {"loss": jnp.float32(0.1),
                            "grad_norm": jnp.float32(0.5),
                            "health_ok": jnp.float32(1.0)})
        pending = m.take_pending()
        assert len(pending) == 3 and not m.take_pending()
        m.fold_batch(jax.device_get(pending))
        assert m.unhealthy is None


class TestProbe:
    def test_fold_scan_metrics_and_folds_health(self):
        from dotaclient_tpu.train.ppo import fold_scan_metrics

        seq = {
            "loss": jnp.asarray([1.0, 2.0, 3.0]),
            "health_ok": jnp.asarray([1.0, 0.0, 1.0]),
        }
        out = fold_scan_metrics(seq)
        assert float(out["loss"]) == 3.0          # last, as ever
        assert float(out["health_ok"]) == 0.0     # min: one bad taints all

    @pytest.mark.slow   # compiles a full policy train step (~10s+)
    def test_train_step_probe_flags_nan_batch(self):
        from dotaclient_tpu.models import init_params, make_policy
        from dotaclient_tpu.train.ppo import (
            _train_step, example_batch, init_train_state,
        )

        cfg = tiny_cfg()
        policy = make_policy(cfg.model, cfg.obs, cfg.actions)
        params = init_params(policy, jax.random.PRNGKey(0))
        state = init_train_state(params, cfg.ppo)
        batch = example_batch(cfg, batch=cfg.ppo.batch_rollouts)
        step = jax.jit(lambda s, b: _train_step(policy, cfg.ppo, s, b))
        _, m = step(state, batch)
        assert float(m["health_ok"]) == 1.0
        bad = dict(batch)
        bad["rewards"] = jnp.asarray(batch["rewards"]).at[0, 0].set(jnp.nan)
        _, m = step(init_train_state(params, cfg.ppo), bad)
        assert float(m["health_ok"]) == 0.0

    @pytest.mark.slow   # compiles a full policy train step (~10s+)
    def test_probe_off_omits_the_metric(self):
        from dotaclient_tpu.models import init_params, make_policy
        from dotaclient_tpu.train.ppo import (
            _train_step, example_batch, init_train_state,
        )

        cfg = tiny_cfg()
        policy = make_policy(cfg.model, cfg.obs, cfg.actions)
        params = init_params(policy, jax.random.PRNGKey(0))
        state = init_train_state(params, cfg.ppo)
        batch = example_batch(cfg, batch=cfg.ppo.batch_rollouts)
        _, m = jax.jit(
            lambda s, b: _train_step(policy, cfg.ppo, s, b, probe=False)
        )(state, batch)
        assert "health_ok" not in m


class TestEngineGates:
    class _Transport:
        def __init__(self):
            self.published = []

        def publish_weights(self, msg):
            self.published.append(msg.version)

    def test_publish_blocked_while_latched_then_flows_after_clear(self, registry):
        from dotaclient_tpu.train.snapshot import SnapshotEngine

        monitor = HealthMonitor(HealthConfig(), registry)
        transport = self._Transport()
        engine = SnapshotEngine(
            transport=transport, registry=registry, health=monitor
        )
        try:
            params = {"w": np.ones((4,), np.float32)}
            monitor.fold_host(3, 3, {"loss": float("nan"), "grad_norm": 1.0})
            engine.submit_publish(params, 3)
            assert engine.drain(timeout=30.0)
            assert transport.published == []
            assert registry.snapshot()["health/publish_blocked_total"] == 1
            assert engine.last_published == -1
            monitor.clear()
            engine.submit_publish(params, 4)
            assert engine.drain(timeout=30.0)
            assert transport.published == [4]
        finally:
            engine.stop()

    def test_stats_fold_orders_before_publish(self, registry):
        """A verdict and a publish submitted in the same cycle: the fold
        runs first, so the poisoned version never reaches the wire even
        when both jobs are grabbed together."""
        from dotaclient_tpu.train.snapshot import SnapshotEngine

        monitor = HealthMonitor(HealthConfig(), registry)
        transport = self._Transport()
        engine = SnapshotEngine(
            transport=transport, registry=registry, health=monitor
        )
        try:
            monitor.submit(5, 5, {"loss": np.float32(np.nan),
                                  "grad_norm": np.float32(1.0),
                                  "health_ok": np.float32(0.0)})
            engine.submit_stats(monitor.take_pending(), monitor.fold_batch)
            engine.submit_publish({"w": np.ones((2,), np.float32)}, 5)
            assert engine.drain(timeout=30.0)
            assert transport.published == []
            assert monitor.unhealthy is not None
        finally:
            engine.stop()


def _fake_state(step: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    return {
        "step": np.asarray(step, np.int32),
        "version": np.asarray(step, np.int32),
        "params": {"w": rng.normal(size=(8, 8)).astype(np.float32)},
        "opt_state": {"m": np.zeros((8, 8), np.float32)},
    }


class TestCheckpointIntegrity:
    @pytest.mark.slow   # orbax save+restore disk round-trip (~10s)
    def test_manifest_roundtrip_verifies_clean(self, tmp_path, registry, monkeypatch):
        from dotaclient_tpu.utils.checkpoint import CheckpointManager

        monkeypatch.setattr(telemetry, "get_registry", lambda: registry)
        cfg = RunConfig()
        ckpt = CheckpointManager(str(tmp_path / "ck"))
        assert ckpt.save_host(_fake_state(1, seed=1), cfg)
        ckpt.wait()
        assert os.path.exists(ckpt._manifest_path(1))
        params, step = ckpt.restore_weights()
        assert step == 1
        np.testing.assert_array_equal(
            params["w"], _fake_state(1, seed=1)["params"]["w"]
        )
        assert registry.snapshot()["checkpoint/manifest_failures_total"] == 0
        ckpt.close()

    @pytest.mark.slow   # two saves + walk-back restore (~9s)
    def test_corrupt_leaf_walks_back_and_counts(self, tmp_path, registry, monkeypatch):
        """save → corrupt bytes on disk → restore lands on the previous
        manifest-valid save and counts the failure (ISSUE 6 acceptance)."""
        from dotaclient_tpu.utils.checkpoint import CheckpointManager

        monkeypatch.setattr(telemetry, "get_registry", lambda: registry)
        cfg = RunConfig()
        d = str(tmp_path / "ck")
        ckpt = CheckpointManager(d)
        assert ckpt.save_host(_fake_state(1, seed=1), cfg)
        assert ckpt.save_host(_fake_state(2, seed=2), cfg)
        ckpt.wait()
        # corrupt step 2 on disk: overwrite the head of every payload file
        # (the arrays are tiny, so a single targeted flip can miss them —
        # bit rot at THIS scale means any byte anywhere)
        step_dir = os.path.join(d, "2")
        corrupted = 0
        for root, _, files in os.walk(step_dir):
            for name in files:
                p = os.path.join(root, name)
                size = os.path.getsize(p)
                if size == 0:
                    continue
                with open(p, "r+b") as f:
                    f.write(b"\xff" * min(size, 256))
                corrupted += 1
        assert corrupted > 0
        params, step = ckpt.restore_weights()
        assert step == 1, "restore must walk back to the intact save"
        np.testing.assert_array_equal(
            params["w"], _fake_state(1, seed=1)["params"]["w"]
        )
        assert registry.snapshot()["checkpoint/manifest_failures_total"] >= 1
        ckpt.close()

    def test_corrupt_manifest_fault_site(self, tmp_path, registry, monkeypatch):
        from dotaclient_tpu.utils.checkpoint import CheckpointManager

        monkeypatch.setattr(telemetry, "get_registry", lambda: registry)
        faults.configure("checkpoint.corrupt_manifest@1")
        cfg = RunConfig()
        ckpt = CheckpointManager(str(tmp_path / "ck"))
        assert ckpt.save_host(_fake_state(1, seed=1), cfg)
        assert ckpt.save_host(_fake_state(2, seed=2), cfg)
        ckpt.wait()
        _, step = ckpt.restore_weights()
        # the injected verification failure hits the newest step first;
        # the walk-back lands on the previous one
        assert step == 1
        assert registry.snapshot()["checkpoint/manifest_failures_total"] >= 1
        ckpt.close()

    def test_all_steps_corrupt_raises(self, tmp_path, registry, monkeypatch):
        from dotaclient_tpu.utils.checkpoint import (
            CheckpointIntegrityError, CheckpointManager,
        )

        monkeypatch.setattr(telemetry, "get_registry", lambda: registry)
        faults.configure("checkpoint.corrupt_manifest@1+1")   # every restore
        cfg = RunConfig()
        ckpt = CheckpointManager(str(tmp_path / "ck"))
        assert ckpt.save_host(_fake_state(1), cfg)
        ckpt.wait()
        with pytest.raises(CheckpointIntegrityError):
            ckpt.restore_weights()
        ckpt.close()

    @pytest.mark.slow   # several orbax saves + GC (~5s)
    def test_last_good_slot_survives_retention_gc(self, tmp_path, registry, monkeypatch):
        """The rolling max_to_keep GC must never eat the health-verified
        save — the exact failure mode of the ISSUE 6 motivation."""
        from dotaclient_tpu.utils.checkpoint import CheckpointManager

        monkeypatch.setattr(telemetry, "get_registry", lambda: registry)
        cfg = RunConfig()
        ckpt = CheckpointManager(str(tmp_path / "ck"), max_to_keep=2)
        assert ckpt.save_host(_fake_state(1, seed=1), cfg, mark_good=True)
        for s in range(2, 6):
            assert ckpt.save_host(_fake_state(s, seed=s), cfg)
        ckpt.wait()
        assert 1 not in ckpt._mgr.all_steps()   # GC'd from the main ring
        assert ckpt.last_good_step() == 1       # but the slot still has it
        restored = ckpt.restore_last_good(cfg, _abstract_from(_fake_state(1)))
        assert restored is not None
        state, _ = restored
        np.testing.assert_array_equal(
            np.asarray(state.params["w"]),
            _fake_state(1, seed=1)["params"]["w"],
        )
        assert registry.snapshot()["health/last_good_step"] == 1.0
        ckpt.close()

    def test_same_step_resave_supersedes(self, tmp_path, registry, monkeypatch):
        """A rollback-then-retrain run re-reaches old step numbers; the
        fresh save must replace the stale one, not be declined."""
        from dotaclient_tpu.utils.checkpoint import CheckpointManager

        monkeypatch.setattr(telemetry, "get_registry", lambda: registry)
        cfg = RunConfig()
        ckpt = CheckpointManager(str(tmp_path / "ck"))
        assert ckpt.save_host(_fake_state(3, seed=1), cfg)
        assert ckpt.save_host(_fake_state(3, seed=9), cfg)
        ckpt.wait()
        params, _ = ckpt.restore_weights()
        np.testing.assert_array_equal(
            params["w"], _fake_state(3, seed=9)["params"]["w"]
        )
        ckpt.close()

    def test_discard_steps_above(self, tmp_path, registry, monkeypatch):
        from dotaclient_tpu.utils.checkpoint import CheckpointManager

        monkeypatch.setattr(telemetry, "get_registry", lambda: registry)
        cfg = RunConfig()
        ckpt = CheckpointManager(str(tmp_path / "ck"))
        for s in (1, 2, 3):
            assert ckpt.save_host(_fake_state(s), cfg)
        ckpt.wait()
        assert ckpt.discard_steps_above(1) == 2
        assert ckpt.latest_step() == 1
        assert not os.path.exists(ckpt._manifest_path(3))
        ckpt.close()


def _abstract_from(fake):
    """A TrainState-shaped template matching the _fake_state layout."""
    from dotaclient_tpu.train.ppo import TrainState

    return TrainState(
        step=jnp.asarray(fake["step"]),
        version=jnp.asarray(fake["version"]),
        params=jax.tree.map(jnp.asarray, fake["params"]),
        opt_state=jax.tree.map(jnp.asarray, fake["opt_state"]),
    )


class TestAdmissionControl:
    def _buffer(self, cfg):
        from dotaclient_tpu.buffer import TrajectoryBuffer
        from dotaclient_tpu.parallel import make_mesh

        return TrajectoryBuffer(cfg, make_mesh(cfg.mesh, devices=jax.devices()[:1]))

    def _rollout(self, cfg, version=0, poison=False):
        from dotaclient_tpu.train import example_batch

        row = jax.tree.map(
            lambda x: np.asarray(x[0]).copy(), example_batch(cfg, batch=1)
        )
        if poison:
            row["rewards"][0] = np.nan
        return ({"model_version": version}, row)

    def test_nonfinite_payload_rejected_and_counted(self, monkeypatch, registry):
        monkeypatch.setattr(telemetry, "get_registry", lambda: registry)
        cfg = tiny_cfg()
        buf = self._buffer(cfg)
        kept = buf.add(
            [self._rollout(cfg), self._rollout(cfg, poison=True)],
            current_version=0,
        )
        assert kept == 1
        assert buf.dropped_nonfinite == 1
        assert registry.snapshot()["buffer/nonfinite_rejected_total"] == 1

    def test_nonfinite_admitted_when_disabled(self, monkeypatch, registry):
        monkeypatch.setattr(telemetry, "get_registry", lambda: registry)
        cfg = tiny_cfg()
        cfg = dataclasses.replace(
            cfg, buffer=dataclasses.replace(cfg.buffer, reject_nonfinite=False)
        )
        buf = self._buffer(cfg)
        assert buf.add([self._rollout(cfg, poison=True)], current_version=0) == 1

    def test_stale_rejection_counted(self, monkeypatch, registry):
        monkeypatch.setattr(telemetry, "get_registry", lambda: registry)
        cfg = tiny_cfg()
        cfg = dataclasses.replace(
            cfg, buffer=dataclasses.replace(cfg.buffer, max_weight_staleness=2)
        )
        buf = self._buffer(cfg)
        assert buf.add([self._rollout(cfg, version=0)], current_version=10) == 0
        assert registry.snapshot()["buffer/stale_rejected_total"] == 1
        assert buf.add([self._rollout(cfg, version=9)], current_version=10) == 1

    def test_drop_newer_than_purges_poisoned_window(self, monkeypatch, registry):
        monkeypatch.setattr(telemetry, "get_registry", lambda: registry)
        cfg = tiny_cfg()
        buf = self._buffer(cfg)
        # versions all within the ingest staleness window of 6
        buf.add([self._rollout(cfg, version=v) for v in (2, 3, 5, 6)],
                current_version=6)
        assert buf.size == 4
        assert buf.drop_newer_than(3) == 2
        assert buf.size == 2
        assert registry.snapshot()["buffer/poison_dropped_total"] == 2

    @pytest.mark.slow   # vec pool rollout compile (~9s)
    def test_actor_nonfinite_fault_site_rejected_at_the_door(self, monkeypatch, registry):
        """The chaos path end to end in-process: a vec pool with the
        actor.nonfinite_payload fault ships one poisoned rollout; the
        buffer door rejects exactly it."""
        monkeypatch.setattr(telemetry, "get_registry", lambda: registry)
        from dotaclient_tpu.models import init_params, make_policy

        faults.configure("actor.nonfinite_payload@1")
        cfg = tiny_cfg()
        from dotaclient_tpu.actor import VecActorPool

        policy = make_policy(cfg.model, cfg.obs, cfg.actions)
        params = init_params(policy, jax.random.PRNGKey(0))
        shipped = []
        pool = VecActorPool(
            cfg, policy, params, seed=0, rollout_sink=shipped.extend
        )
        pool.run(cfg.ppo.rollout_len, refresh_every=0)
        assert shipped, "pool shipped nothing"
        buf = self._buffer(cfg)
        kept = buf.add(list(shipped), current_version=0)
        assert buf.dropped_nonfinite == 1
        assert kept == len(shipped) - 1


class TestSchemaTier:
    def test_health_keys_required_when_flagged(self):
        import importlib.util

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "check_telemetry_schema",
            os.path.join(root, "scripts", "check_telemetry_schema.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        line = (
            '{"ts": 1.0, "step": 1, "scalars": {'
            + ", ".join(f'"{k}": 0.0' for k in mod.REQUIRED_KEYS)
            + "}}"
        )
        errors = mod.validate_lines([line], extra_required=mod.HEALTH_KEYS)
        joined = "\n".join(errors)
        for key in mod.HEALTH_KEYS:
            assert key in joined
        # the clean line needs every timer's full leaf set too (the span
        # completeness rule), not just the /mean_s spot checks
        keys = set(mod.REQUIRED_KEYS) | set(mod.HEALTH_KEYS)
        for k in mod.REQUIRED_KEYS:
            if k.startswith("span/"):
                root = k.rsplit("/", 1)[0]
                keys.update(f"{root}/{leaf}" for leaf in mod.TIMER_LEAVES)
        ok_line = (
            '{"ts": 1.0, "step": 1, "scalars": {'
            + ", ".join(f'"{k}": 0.0' for k in sorted(keys))
            + "}}"
        )
        assert not mod.validate_lines([ok_line], extra_required=mod.HEALTH_KEYS)


class TestLearnerRollback:
    @pytest.mark.slow
    def test_divergence_rolls_back_and_completes(self, tmp_path):
        """In-process acceptance: injected NaN gradient → probe flags it,
        publishes/checkpoints block, rollback restores last_good, the run
        completes to its exact target step with finite loss and a
        monotone version counter."""
        from dotaclient_tpu.train.learner import Learner

        faults.configure("learner.nan_grad@5")
        try:
            learner = Learner(
                tiny_cfg(checkpoint_every=2), actor="device",
                checkpoint_dir=str(tmp_path / "ck"),
            )
            out = learner.train(10)
            snap = telemetry.get_registry().snapshot()
            assert snap["health/rollbacks_total"] >= 1
            assert snap["health/nonfinite_steps_total"] >= 1
            assert np.isfinite(out["loss"])
            assert learner._host_step == 10
            assert learner.ckpt.latest_step() == 10
            assert learner.ckpt.last_good_step() == 10
            # version counter stayed monotone across the rollback: the
            # poisoned version range is never reused on the wire
            assert learner._host_version > 10
            assert int(np.asarray(learner.state.version)) == learner._host_version
        finally:
            if learner._snap_engine is not None:
                learner._snap_engine.stop()
            learner.ckpt.wait()
            learner.ckpt.close()

    @pytest.mark.slow
    def test_rollback_exhaustion_exits_loudly_with_runbook(self, tmp_path):
        """A divergence that persists through every retry must raise (the
        CLI then exits non-zero) and point at the runbook."""
        from dotaclient_tpu.train.learner import Learner

        # every batch from the 5th on is poisoned: each rollback's retry
        # diverges again until max_rollbacks is exhausted
        faults.configure("learner.nan_grad@5+1")
        learner = Learner(
            tiny_cfg(
                checkpoint_every=2,
                health=HealthConfig(max_rollbacks=1),
            ),
            actor="device", checkpoint_dir=str(tmp_path / "ck"),
        )
        try:
            with pytest.raises(RuntimeError, match="OPERATIONS.md"):
                learner.train(12)
            assert (
                telemetry.get_registry().snapshot()["health/rollbacks_total"]
                >= 1
            )
        finally:
            if learner._snap_engine is not None:
                learner._snap_engine.stop()
            learner.ckpt.wait()
            learner.ckpt.close()

    @pytest.mark.slow
    def test_no_checkpoint_dir_degrades_to_containment(self):
        """Without a restore point the guardian must not crash the run:
        training continues (NaN and all), publishes stay blocked, the
        operator is warned."""
        from dotaclient_tpu.train.learner import Learner

        faults.configure("learner.nan_grad@3")
        # the registry is process-global: other rollback tests in the same
        # session may already have counted — assert the DELTA stays zero
        before = telemetry.get_registry().snapshot().get(
            "health/rollbacks_total", 0.0
        )
        learner = Learner(tiny_cfg(), actor="device")
        try:
            out = learner.train(6)
            assert out["optimizer_steps"] == 6.0
            assert learner._health.unhealthy is not None
            snap = telemetry.get_registry().snapshot()
            assert snap["health/rollbacks_total"] == before
        finally:
            if learner._snap_engine is not None:
                learner._snap_engine.stop()
