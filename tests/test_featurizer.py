"""Featurizer + action-codec tests (SURVEY.md §4: golden tests on canned
worldstates; Hypothesis property that illegal actions are never exposed)."""

import numpy as np
import pytest

try:
    from hypothesis import given, settings, strategies as st
except ImportError:
    # Slim images ship without hypothesis; an unconditional import would
    # error the whole module at collection and take the golden tests down
    # with it. Fall back to a minimal seeded-sweep shim: each @given test
    # runs 25 deterministic draws instead of a shrinking property search.
    class _Data:
        def __init__(self, rng):
            self._rng = rng

        def draw(self, strategy):
            return strategy(self._rng)

    class st:  # noqa: N801 - mirrors the hypothesis module name
        @staticmethod
        def integers(lo, hi):
            return lambda rng: int(rng.integers(lo, hi + 1))

        @staticmethod
        def sampled_from(seq):
            items = list(seq)
            return lambda rng: items[int(rng.integers(0, len(items)))]

        @staticmethod
        def data():
            return _Data

    def settings(**_kwargs):
        return lambda fn: fn

    def given(**strategies):
        def deco(fn):
            def wrapper(self):
                rng = np.random.default_rng(0)
                for _ in range(25):
                    kwargs = {
                        name: _Data(rng) if strat is _Data else strat(rng)
                        for name, strat in strategies.items()
                    }
                    fn(self, **kwargs)

            return wrapper

        return deco

from dotaclient_tpu.config import ActionSpec, ObsSpec
from dotaclient_tpu.envs.lane_sim import LaneSim, NUKE_RANGE, TEAM_DIRE, TEAM_RADIANT
from dotaclient_tpu.features import (
    UNIT_FEATURES,
    decode_action,
    featurize,
    shaped_reward,
    stack_observations,
)
from dotaclient_tpu.protos import dota_pb2 as pb

OBS = ObsSpec()
ACT = ActionSpec()


def make_sim(seed: int = 0, hard: bool = False) -> LaneSim:
    mode = pb.CONTROL_SCRIPTED_HARD if hard else pb.CONTROL_SCRIPTED_EASY
    cfg = pb.GameConfig(
        ticks_per_observation=6,
        seed=seed,
        hero_picks=[
            pb.HeroPick(team_id=TEAM_RADIANT, hero_id=1, control_mode=pb.CONTROL_AGENT),
            pb.HeroPick(team_id=TEAM_DIRE, hero_id=2, control_mode=mode),
        ],
    )
    return LaneSim(cfg)


class TestShapes:
    def test_fixed_shapes_regardless_of_unit_count(self):
        sim = make_sim()
        for _ in range(30):
            ws = sim.world_state(TEAM_RADIANT)
            obs = featurize(ws, player_id=0, obs_spec=OBS, action_spec=ACT)
            assert obs.units.shape == (OBS.max_units, OBS.unit_features)
            assert obs.unit_mask.shape == (OBS.max_units,)
            assert obs.globals.shape == (OBS.global_features,)
            assert obs.mask_action_type.shape == (ACT.n_action_types,)
            assert obs.mask_target_unit.shape == (ACT.max_units,)
            assert obs.units.dtype == np.float32
            sim.step({})

    def test_self_in_slot_zero(self):
        sim = make_sim()
        obs = featurize(sim.world_state(TEAM_RADIANT), 0, OBS, ACT)
        is_self_col = list(UNIT_FEATURES).index("is_self")
        assert obs.units[0, is_self_col] == 1.0
        assert obs.unit_mask[0]
        # self is never a legal target
        assert not obs.mask_target_unit[0]

    def test_stacking(self):
        sim = make_sim()
        obs = [
            featurize(sim.world_state(TEAM_RADIANT), 0, OBS, ACT)
            for _ in range(4)
        ]
        batch = stack_observations(obs)
        assert batch["units"].shape == (4, OBS.max_units, OBS.unit_features)
        assert batch["hero_id"].shape == (4,)

    def test_finite(self):
        sim = make_sim()
        for _ in range(50):
            obs = featurize(sim.world_state(TEAM_RADIANT), 0, OBS, ACT)
            assert np.isfinite(obs.units).all()
            assert np.isfinite(obs.globals).all()
            sim.step({})


class TestMasks:
    def test_noop_always_legal(self):
        sim = make_sim()
        obs = featurize(sim.world_state(TEAM_RADIANT), 0, OBS, ACT)
        assert obs.mask_action_type[pb.ACTION_NOOP]

    def test_targets_are_valid_units(self):
        sim = make_sim()
        for _ in range(40):
            ws = sim.world_state(TEAM_RADIANT)
            obs = featurize(ws, 0, OBS, ACT)
            alive = {u.handle for u in ws.units if u.is_alive}
            for slot in np.flatnonzero(obs.mask_target_unit):
                assert obs.unit_handles[slot] in alive
            sim.step({})

    def test_attack_mask_excludes_healthy_allied_creeps(self):
        sim = make_sim()
        ws = sim.world_state(TEAM_RADIANT)
        obs = featurize(ws, 0, OBS, ACT)
        by_handle = {u.handle: u for u in ws.units}
        for slot in np.flatnonzero(obs.mask_target_unit):
            u = by_handle[int(obs.unit_handles[slot])]
            if u.team_id == TEAM_RADIANT:  # allied target ⇒ must be a deny
                assert u.unit_type == pb.UNIT_LANE_CREEP
                assert u.health < 0.5 * u.health_max

    def test_cast_targets_are_in_range_enemies(self):
        """CAST legality is stricter than ATTACK: enemies inside nuke range."""
        sim = make_sim()
        for _ in range(40):
            ws = sim.world_state(TEAM_RADIANT)
            obs = featurize(ws, 0, OBS, ACT)
            by_handle = {u.handle: u for u in ws.units}
            me = sim.hero_for_player(0)
            for slot in np.flatnonzero(obs.mask_cast_target):
                u = by_handle[int(obs.unit_handles[slot])]
                assert u.team_id != TEAM_RADIANT
                assert np.hypot(u.location.x - me.x, u.location.y - me.y) <= NUKE_RANGE
            if obs.mask_action_type[pb.ACTION_CAST]:
                assert obs.mask_cast_target.any()
            sim.step({})

    def test_dead_hero_can_only_noop(self):
        sim = make_sim()
        hero = sim.hero_for_player(0)
        hero.alive = False
        obs = featurize(sim.world_state(TEAM_RADIANT), 0, OBS, ACT)
        assert obs.mask_action_type[pb.ACTION_NOOP]
        assert not obs.mask_action_type[pb.ACTION_MOVE]
        assert not obs.mask_action_type[pb.ACTION_ATTACK_UNIT]
        assert not obs.mask_action_type[pb.ACTION_CAST]


class TestCodec:
    @settings(max_examples=50, deadline=None)
    @given(seed=st.integers(0, 1000), steps=st.integers(0, 20), data=st.data())
    def test_any_legal_action_decodes_and_applies(self, seed, steps, data):
        """Property: any action drawn from the legal masks decodes into a
        proto the sim accepts without error."""
        sim = make_sim(seed=seed)
        for _ in range(steps):
            sim.step({})
        ws = sim.world_state(TEAM_RADIANT)
        obs = featurize(ws, 0, OBS, ACT)

        legal_types = list(np.flatnonzero(obs.mask_action_type))
        a_type = data.draw(st.sampled_from(legal_types))
        indices = {
            "action_type": int(a_type),
            "move_x": data.draw(st.integers(0, ACT.move_bins - 1)),
            "move_y": data.draw(st.integers(0, ACT.move_bins - 1)),
            "target_unit": 0,
            "ability": 0,
        }
        if a_type == pb.ACTION_ATTACK_UNIT:
            legal_targets = list(np.flatnonzero(obs.mask_target_unit))
            indices["target_unit"] = int(data.draw(st.sampled_from(legal_targets)))
        elif a_type == pb.ACTION_CAST:
            legal_targets = list(np.flatnonzero(obs.mask_cast_target))
            indices["target_unit"] = int(data.draw(st.sampled_from(legal_targets)))
        action = decode_action(indices, obs, player_id=0)
        assert action.player_id == 0
        if a_type in (pb.ACTION_ATTACK_UNIT, pb.ACTION_CAST):
            assert action.target_handle > 0
        sim.step({0: action})  # must not raise

    def test_move_roundtrip(self):
        sim = make_sim()
        obs = featurize(sim.world_state(TEAM_RADIANT), 0, OBS, ACT)
        action = decode_action(
            {"action_type": pb.ACTION_MOVE, "move_x": 8, "move_y": 0,
             "target_unit": 0, "ability": 0},
            obs, player_id=0,
        )
        assert action.type == pb.ACTION_MOVE
        assert (action.move_x, action.move_y) == (8, 0)


class TestReward:
    def test_zero_reward_on_identical_states(self):
        sim = make_sim()
        ws = sim.world_state(TEAM_RADIANT)
        r, comps = shaped_reward(ws, ws, player_id=0)
        assert r == pytest.approx(0.0)
        assert all(v == pytest.approx(0.0) for v in comps.values())

    def test_lasthit_gold_rewarded(self):
        sim = make_sim()
        prev = sim.world_state(TEAM_RADIANT)
        hero = sim.hero_for_player(0)
        hero.last_hits += 1
        hero.gold += 40.0
        cur = sim.world_state(TEAM_RADIANT)
        r, comps = shaped_reward(prev, cur, player_id=0)
        assert comps["last_hits"] > 0
        assert comps["gold"] > 0
        assert r > 0

    def test_configurable_weights_override_default_table(self):
        """RewardConfig weights flow into the shaping (the table is config,
        not a constant — per-run shaping experiments without code edits)."""
        import dataclasses

        from dotaclient_tpu.config import RewardConfig

        sim = make_sim()
        prev = sim.world_state(TEAM_RADIANT)
        hero = sim.hero_for_player(0)
        hero.last_hits += 1
        hero.gold += 40.0
        cur = sim.world_state(TEAM_RADIANT)
        r_default, _ = shaped_reward(prev, cur, player_id=0)
        boosted = dataclasses.replace(
            RewardConfig(), last_hits=RewardConfig().last_hits * 10
        )
        r_boosted, comps = shaped_reward(
            prev, cur, player_id=0, weights=dict(boosted.as_dict())
        )
        assert r_boosted > r_default
        assert comps["last_hits"] == pytest.approx(
            10 * RewardConfig().last_hits
        )

    def test_win_signal_symmetric(self):
        sim = make_sim()
        prev = sim.world_state(TEAM_RADIANT)
        sim.game_state = pb.GAME_STATE_POST_GAME
        sim.winning_team = TEAM_RADIANT
        cur_r = sim.world_state(TEAM_RADIANT)
        r_win, _ = shaped_reward(prev, cur_r, player_id=0)
        sim.winning_team = TEAM_DIRE
        cur_d = sim.world_state(TEAM_RADIANT)
        r_loss, _ = shaped_reward(prev, cur_d, player_id=0)
        assert r_win > 0 > r_loss

    def test_death_penalized(self):
        sim = make_sim()
        prev = sim.world_state(TEAM_RADIANT)
        hero = sim.hero_for_player(0)
        hero.alive = False
        hero.deaths += 1
        cur = sim.world_state(TEAM_RADIANT)
        r, comps = shaped_reward(prev, cur, player_id=0)
        assert comps["deaths"] < 0
        assert r < 0
