"""Transport codec + sharded trajectory-buffer tests (SURVEY.md §7 step 5)."""

import dataclasses

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dotaclient_tpu.config import RunConfig
from dotaclient_tpu.buffer import TrajectoryBuffer
from dotaclient_tpu.parallel import make_mesh
from dotaclient_tpu.train import example_batch
from dotaclient_tpu.transport import (
    InProcTransport,
    decode_rollout,
    decode_weights,
    encode_rollout,
    encode_weights,
    flatten_tree,
    unflatten_tree,
)

CFG = RunConfig()


def one_rollout(seed: int = 0):
    """A single-rollout pytree (a Batch row) filled with random values.

    ``valid``/``dones`` keep their semantics (1s and {0,1}); everything else
    is random noise — enough for roundtrip/ordering checks and a well-posed
    train step."""
    rng = np.random.default_rng(seed)
    row = jax.tree.map(
        lambda x: np.asarray(x[0]), example_batch(CFG, batch=1)
    )
    row = jax.tree.map(
        lambda x: rng.normal(size=x.shape).astype(x.dtype)
        if np.issubdtype(x.dtype, np.floating)
        else rng.integers(0, 2, size=x.shape).astype(x.dtype),
        row,
    )
    row["valid"] = np.ones_like(row["valid"])
    row["dones"] = (rng.random(row["dones"].shape) < 0.05).astype(row["dones"].dtype)
    row["behavior_logp"] = -np.abs(row["behavior_logp"])
    return row


class TestSerialize:
    def test_flatten_unflatten_roundtrip(self):
        tree = one_rollout()
        flat = flatten_tree(tree)
        assert "obs/units" in flat and "carry0/0" in flat
        rebuilt = unflatten_tree(flat)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(a, b), tree, rebuilt
        )
        # carry0 must come back as a tuple, not a dict
        assert isinstance(rebuilt["carry0"], tuple)

    def test_rollout_roundtrip(self):
        tree = one_rollout(1)
        msg = encode_rollout(
            tree, model_version=7, env_id=3, rollout_id=99,
            length=CFG.ppo.rollout_len, total_reward=1.5,
        )
        meta, back = decode_rollout(msg)
        assert meta["model_version"] == 7
        assert meta["rollout_id"] == 99
        assert meta["total_reward"] == pytest.approx(1.5)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(a, b), tree, back
        )

    def test_rollout_proto_is_wire_stable(self):
        tree = one_rollout(2)
        msg = encode_rollout(tree, 1, 0, 1, CFG.ppo.rollout_len, 0.0)
        wire = msg.SerializeToString()
        from dotaclient_tpu.protos import dota_pb2 as pb

        msg2 = pb.Rollout()
        msg2.ParseFromString(wire)
        _, back = decode_rollout(msg2)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(a, b), tree, back
        )

    def test_weights_roundtrip(self):
        params = {"dense": {"kernel": np.ones((4, 2), np.float32),
                            "bias": np.zeros((2,), np.float32)}}
        version, back = decode_weights(encode_weights(params, 11))
        assert version == 11
        np.testing.assert_array_equal(back["dense"]["kernel"], params["dense"]["kernel"])

    def test_bfloat16_roundtrip(self):
        import ml_dtypes

        arr = np.arange(8, dtype=np.float32).astype(ml_dtypes.bfloat16)
        from dotaclient_tpu.transport import proto_to_tensor, tensor_to_proto

        back = proto_to_tensor(tensor_to_proto(arr))
        assert back.dtype == np.dtype(ml_dtypes.bfloat16)
        np.testing.assert_array_equal(back, arr)

    def test_wire_dtype_bf16_halves_bytes_and_upcasts_losslessly(self):
        """ISSUE 3: transport.wire_dtype='bfloat16' casts f32 params at
        encode — wire bytes ≈ half — and decode upcasts to f32 values
        exactly equal to the published bf16 values (lossless: every bf16
        is exactly representable in f32)."""
        import ml_dtypes

        rng = np.random.default_rng(3)
        params = {
            "dense": {"kernel": rng.normal(size=(64, 32)).astype(np.float32),
                      "bias": rng.normal(size=(32,)).astype(np.float32)},
            "step": np.asarray(7, np.int64),   # non-float leaf: untouched
        }
        f32_wire = encode_weights(params, 9).SerializeToString()
        m = encode_weights(params, 9, wire_dtype="bfloat16")
        bf16_wire = m.SerializeToString()
        # tensor payload halves; proto framing/names add a fixed overhead
        assert len(bf16_wire) < 0.6 * len(f32_wire)
        version, back = decode_weights(m)
        assert version == 9
        assert back["dense"]["kernel"].dtype == np.float32
        assert back["step"].dtype == np.int64 and back["step"] == 7
        expect = params["dense"]["kernel"].astype(ml_dtypes.bfloat16)
        np.testing.assert_array_equal(
            back["dense"]["kernel"], expect.astype(np.float32)
        )
        # raw wire form is inspectable: upcast=False keeps bf16
        _, raw = decode_weights(m, upcast=False)
        assert raw["dense"]["kernel"].dtype == np.dtype(ml_dtypes.bfloat16)

    def test_wire_dtype_unknown_rejected(self):
        with pytest.raises(ValueError, match="wire_dtype"):
            encode_weights({"w": np.zeros(2, np.float32)}, 1,
                           wire_dtype="float16")

    def test_natively_bf16_params_never_widened(self):
        """The upcast applies ONLY to leaves the encoder narrowed: params
        that are bf16 in the model (param_dtype='bfloat16') keep their
        dtype through both wire modes — decode must not guess from dtype
        alone (review finding)."""
        import ml_dtypes

        bf16 = np.dtype(ml_dtypes.bfloat16)
        params = {
            "native_bf16": np.arange(6, dtype=np.float32).astype(bf16),
            "f32": np.linspace(0, 1, 6, dtype=np.float32),
        }
        # float32 wire: nothing cast, nothing upcast
        _, back = decode_weights(encode_weights(params, 1))
        assert back["native_bf16"].dtype == bf16
        assert back["f32"].dtype == np.float32
        # bf16 wire: only the f32 leaf was narrowed → only it upcasts
        _, back = decode_weights(
            encode_weights(params, 1, wire_dtype="bfloat16")
        )
        assert back["native_bf16"].dtype == bf16
        assert back["f32"].dtype == np.float32


class TestInProcTransport:
    def test_rollout_queue_fifo_and_exactly_once(self):
        t = InProcTransport()
        for i in range(5):
            t.publish_rollout(encode_rollout(one_rollout(i), i, 0, i, 4, 0.0))
        got = t.consume_rollouts(3)
        assert [g.model_version for g in got] == [0, 1, 2]
        got2 = t.consume_rollouts(10)
        assert [g.model_version for g in got2] == [3, 4]
        assert t.consume_rollouts(1, timeout=0.01) == []

    def test_drop_oldest_on_overflow(self):
        t = InProcTransport(max_rollouts=2)
        for i in range(4):
            t.publish_rollout(encode_rollout(one_rollout(), i, 0, i, 4, 0.0))
        got = t.consume_rollouts(10)
        assert [g.model_version for g in got] == [2, 3]
        assert t.dropped == 2

    def test_weights_latest_wins(self):
        t = InProcTransport()
        assert t.latest_weights() is None
        for v in range(3):
            t.publish_weights(encode_weights({"w": np.zeros(1, np.float32)}, v))
        assert t.latest_weights().version == 2


class TestTrajectoryBuffer:
    def make(self, capacity=16, batch_rollouts=8, min_fill=8):
        cfg = dataclasses.replace(
            CFG,
            buffer=dataclasses.replace(CFG.buffer, capacity_rollouts=capacity,
                                       min_fill=min_fill),
            ppo=dataclasses.replace(CFG.ppo, batch_rollouts=batch_rollouts),
        )
        mesh = make_mesh(cfg.mesh)
        return TrajectoryBuffer(cfg, mesh), cfg

    def decoded(self, seed, version=0):
        return ({"model_version": version, "env_id": 0, "rollout_id": seed,
                 "length": CFG.ppo.rollout_len, "total_reward": 0.0},
                one_rollout(seed))

    def test_fifo_roundtrip_values(self):
        buf, cfg = self.make()
        rolls = [self.decoded(i) for i in range(12)]
        assert buf.add(rolls, current_version=0) == 12
        assert buf.size == 12
        batch = buf.take(8)
        assert batch is not None
        assert buf.size == 4
        # oldest eight, stacked in order, bit-identical
        for k in ("rewards", "behavior_logp"):
            expect = np.stack([np.asarray(r[1][k]) for r in rolls[:8]])
            np.testing.assert_array_equal(np.asarray(batch[k]), expect)
        obs_units = np.stack([np.asarray(r[1]["obs"]["units"]) for r in rolls[:8]])
        np.testing.assert_array_equal(np.asarray(batch["obs"]["units"]), obs_units)

    def test_batch_is_data_sharded(self):
        buf, cfg = self.make(capacity=16, batch_rollouts=8, min_fill=8)
        buf.add([self.decoded(i) for i in range(8)], 0)
        batch = buf.take(8)
        shard_devs = {d for d in batch["rewards"].sharding.device_set}
        assert len(shard_devs) == 8  # spread over the 8 forced host devices

    def test_underfill_returns_none(self):
        buf, _ = self.make()
        buf.add([self.decoded(0)], 0)
        assert buf.take(8) is None

    def test_staleness_filter(self):
        buf, cfg = self.make()
        kept = buf.add(
            [self.decoded(0, version=0), self.decoded(1, version=6)],
            current_version=6 + cfg.ppo.max_staleness,
        )
        assert kept == 1
        assert buf.dropped_stale == 1

    def test_config_skewed_rollout_dropped_not_fatal(self):
        """A rollout with mismatched shapes (actor running a different
        rollout_len or model config) must be dropped at the ingest door —
        the disposable-actor failure model (SURVEY.md §5.3), not a learner
        crash."""
        buf, cfg = self.make()
        good = self.decoded(0)
        meta, row = self.decoded(1)
        skewed = jax.tree.map(
            lambda x: np.repeat(x, 2, axis=0) if x.ndim else x, row
        )  # doubled leading (time) dims everywhere
        wrong_struct = ({"model_version": 0, "env_id": 0, "rollout_id": 9,
                         "length": 4, "total_reward": 0.0},
                        {"not_a_batch": np.zeros((3,), np.float32)})
        kept = buf.add([good, (meta, skewed), wrong_struct], current_version=0)
        assert kept == 1
        assert buf.dropped_skew == 2
        assert buf.size == 1
        assert buf.metrics()["buffer_dropped_skew"] == 2.0

    def test_ring_wraparound_overwrites_oldest(self):
        buf, cfg = self.make(capacity=16, batch_rollouts=8)
        buf.add([self.decoded(i) for i in range(16)], 0)
        buf.add([self.decoded(100 + i) for i in range(2)], 0)  # wraps to 0,1
        assert buf.size == 16
        batch = buf.take(8)
        # slots 0,1 were overwritten; oldest remaining are 2..9
        expect = np.stack([np.asarray(one_rollout(i)["rewards"]) for i in range(2, 10)])
        np.testing.assert_array_equal(np.asarray(batch["rewards"]), expect)

    def test_staging_lane_reuse_is_bitexact(self):
        """Back-to-back ingests rotate through the REUSED staging lanes
        (BufferConfig.staging_slots): later assemblies must never corrupt
        rows an earlier scatter staged from the same memory."""
        buf, cfg = self.make(capacity=16, batch_rollouts=8, min_fill=8)
        first = [self.decoded(i) for i in range(8)]
        buf.add(first, 0)
        # cycles through every lane at least twice at staging_slots=2
        for wave in range(1, 4):
            buf.add([self.decoded(100 * wave + i) for i in range(2)], 0)
        batch = buf.take(8)
        expect = np.stack([np.asarray(r[1]["rewards"]) for r in first])
        np.testing.assert_array_equal(np.asarray(batch["rewards"]), expect)

    def test_hold_release_and_requeue(self):
        """The prefetch lane's contract: held slots are out of circulation
        until released; a requeued batch returns to the FRONT of the order
        and re-gathers the same rows."""
        buf, cfg = self.make(capacity=16, batch_rollouts=8, min_fill=8)
        rolls = [self.decoded(i) for i in range(12)]
        buf.add(rolls, 0)
        held = buf.take(8, hold=True)
        assert held is not None
        batch, ticket = held
        assert buf.size == 4                     # held slots left the order
        buf.requeue(ticket)
        assert buf.size == 12                    # ... and came back in front
        batch2 = buf.take(8)
        np.testing.assert_array_equal(
            np.asarray(batch["rewards"]), np.asarray(batch2["rewards"])
        )
        # a released batch's slots become reusable: ring refills to capacity
        buf.add([self.decoded(50 + i) for i in range(12)], 0)
        assert buf.size == 16

    def test_eviction_during_inflight_hold_spares_held_slots(self):
        """An ingest racing an in-flight (held) batch may evict unconsumed
        slots but must never overwrite the held ones — re-gathering after a
        requeue returns bit-identical rows."""
        buf, cfg = self.make(capacity=16, batch_rollouts=8, min_fill=8)
        buf.add([self.decoded(i) for i in range(16)], 0)          # full ring
        batch, ticket = buf.take(8, hold=True)
        # 10 new rollouts: 8 unconsumed slots evicted... but only 8 exist —
        # the surplus 2 must be dropped, not scribbled over held slots
        kept = buf.add([self.decoded(100 + i) for i in range(10)], 0)
        assert kept == 8
        assert buf.dropped_overflow >= 2
        buf.requeue(ticket)
        again = buf.take(8)
        np.testing.assert_array_equal(
            np.asarray(batch["rewards"]), np.asarray(again["rewards"])
        )

    def test_add_device_drops_when_all_slots_held(self):
        """Degenerate capacity == batch with a batch in flight: the device
        ingest drops (counted) instead of corrupting or crashing."""
        buf, cfg = self.make(capacity=8, batch_rollouts=8, min_fill=8)
        buf.add([self.decoded(i) for i in range(8)], 0)
        _, ticket = buf.take(8, hold=True)
        chunk = jax.tree.map(
            lambda *xs: np.stack(xs), *[self.decoded(50 + i)[1] for i in range(4)]
        )
        assert buf.add_device(chunk, 0) == 0
        assert buf.dropped_overflow >= 4
        buf.release(ticket)
        assert buf.add_device(chunk, 0) == 4     # slots reusable again

    def test_take_staleness_reenforced_interleaved_with_add(self):
        """Pipelined ingest interleaves add and take: rollouts fresh at the
        ingest door must STILL be dropped at consume time once the version
        has moved past the staleness window while they sat in the ring."""
        buf, cfg = self.make(capacity=32, batch_rollouts=8, min_fill=8)
        limit = cfg.ppo.max_staleness * cfg.ppo.steps_per_batch
        buf.add([self.decoded(i, version=0) for i in range(8)], 0)
        # interleaved newer ingest, then the version advances past the
        # window for the first wave only
        buf.add([self.decoded(10 + i, version=limit + 1) for i in range(8)],
                limit + 1)
        batch = buf.take(8, current_version=limit + 1)
        assert buf.dropped_stale == 8
        expect = np.stack(
            [np.asarray(one_rollout(10 + i)["rewards"]) for i in range(8)]
        )
        np.testing.assert_array_equal(np.asarray(batch["rewards"]), expect)

    def test_skew_drop_routes_through_logging_and_counter(self, caplog):
        """The shape-skew warning goes through logging + a telemetry
        counter — never a bare print (satellite)."""
        import logging

        from dotaclient_tpu.utils import telemetry as tel

        reg = tel.Registry()
        cfg = dataclasses.replace(
            CFG,
            buffer=dataclasses.replace(
                CFG.buffer, capacity_rollouts=16, min_fill=8
            ),
            ppo=dataclasses.replace(CFG.ppo, batch_rollouts=8),
        )
        buf = TrajectoryBuffer(cfg, make_mesh(cfg.mesh), registry=reg)
        bad = ({"model_version": 0, "env_id": 0, "rollout_id": 1,
                "length": 4, "total_reward": 0.0},
               {"not_a_batch": np.zeros((3,), np.float32)})
        with caplog.at_level(
            logging.WARNING, logger="dotaclient_tpu.buffer.trajectory_buffer"
        ):
            buf.add([bad], current_version=0)
        assert any("shapes" in r.getMessage() for r in caplog.records)
        assert reg.snapshot()["buffer/skew_drops_total"] == 1.0

    def test_ingest_scatter_trace_count_bounded(self):
        """ADVICE round 1 retrace fix: host ingest pads each group to a
        power-of-two bucket and scatters ONCE, so arbitrary fresh-row
        counts compile at most log2(capacity)+1 scatter programs (and one
        dispatch per ingest, not one per pow2 term)."""
        buf, cfg = self.make(capacity=8, batch_rollouts=8, min_fill=8)
        assert buf.scatter_traces == 0
        distinct_counts = [3, 4, 2]
        rid = 0
        for n in distinct_counts:
            buf.add([self.decoded(rid + k) for k in range(n)], 0)
            rid += n
        # 3 distinct ingest sizes → at most log2(8)+1 = 4 programs, and
        # strictly fewer programs than distinct sizes (3 pads into 4's
        # bucket) — the padding collapses arbitrary counts onto pow2s
        assert buf.scatter_traces <= 4
        assert buf.scatter_traces < len(set(distinct_counts))

    def test_ingest_pad_rows_do_not_corrupt(self):
        """Pow2 padding must be invisible: odd-count ingests followed by a
        take return exactly the ingested rows, bit-identical, and the pad
        never claims a slot."""
        buf, cfg = self.make(capacity=16, batch_rollouts=8, min_fill=8)
        rolls = [self.decoded(i) for i in range(3)]      # pads 3 → 4
        buf.add(rolls, 0)
        assert buf.size == 3                             # pad not booked
        more = [self.decoded(10 + i) for i in range(5)]  # pads 5 → 8
        buf.add(more, 0)
        assert buf.size == 8
        batch = buf.take(8)
        expect = np.stack(
            [np.asarray(r[1]["rewards"]) for r in rolls + more]
        )
        np.testing.assert_array_equal(np.asarray(batch["rewards"]), expect)

    def test_feeds_train_step(self):
        """Buffer output is a valid train batch end-to-end."""
        from dotaclient_tpu.models import init_params, make_policy
        from dotaclient_tpu.train import init_train_state, make_train_step

        buf, cfg = self.make(capacity=16, batch_rollouts=8, min_fill=8)
        policy = make_policy(cfg.model, cfg.obs, cfg.actions)
        params = init_params(policy, jax.random.PRNGKey(0))
        state = init_train_state(params, cfg.ppo)
        step = make_train_step(policy, cfg, make_mesh(cfg.mesh))
        buf.add([self.decoded(i) for i in range(8)], 0)
        batch = buf.take(8)
        # behavior_logp must be ≤ 0 for a sane ratio; fake it
        batch = dict(batch)
        batch["behavior_logp"] = jnp.zeros_like(batch["behavior_logp"]) - 1.0
        state2, metrics = step(state, batch)
        assert np.isfinite(float(metrics["loss"]))
