"""Mixture-of-experts / expert-parallelism tests.

SURVEY.md §2.3 row 6 lists EP as absent in the reference; the rebuild ships
it first-class. Pins:

* the explicit shard_map + all_to_all dispatch (``parallel.expert``) equals
  a per-token dense oracle on the 8-device host mesh (no drops at ample
  capacity) — the EP analogue of the ring-attention-vs-reference test;
* the GSPMD einsum form (``models.moe.MoEMLP``) equals the same oracle;
* a transformer+MoE policy trains end-to-end on a data×model mesh with the
  expert tensors actually sharded over the model axis.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from dotaclient_tpu.config import MeshConfig, default_config
from dotaclient_tpu.parallel import make_mesh
from dotaclient_tpu.parallel.expert import make_expert_dispatch, route_top1


def _ffn_oracle(x, gate_w, w1, b1, w2, b2):
    """Per-token dense reference: route to top-1 expert, full FFN, × prob."""
    probs = jax.nn.softmax(x @ gate_w, axis=-1)
    expert = jnp.argmax(probs, axis=-1)
    prob = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]
    h = jax.nn.gelu(jnp.einsum("bd,bdf->bf", x, w1[expert]) + b1[expert])
    out = jnp.einsum("bf,bfd->bd", h, w2[expert]) + b2[expert]
    return out * prob[:, None]


def _make_weights(key, E, D, F):
    ks = jax.random.split(key, 5)
    gate_w = jax.random.normal(ks[0], (D, E), jnp.float32) * 0.5
    w1 = jax.random.normal(ks[1], (E, D, F), jnp.float32) / np.sqrt(D)
    b1 = jax.random.normal(ks[2], (E, F), jnp.float32) * 0.1
    w2 = jax.random.normal(ks[3], (E, F, D), jnp.float32) / np.sqrt(F)
    b2 = jax.random.normal(ks[4], (E, D), jnp.float32) * 0.1
    return gate_w, w1, b1, w2, b2


class TestExpertDispatch:
    def test_matches_oracle_on_8dev_mesh(self):
        E, D, F, B = 8, 16, 32, 64
        mesh = make_mesh(MeshConfig(), devices=jax.devices()[:8])
        fn = make_expert_dispatch(mesh, axis="data", capacity_factor=float(E))
        gate_w, w1, b1, w2, b2 = _make_weights(jax.random.PRNGKey(0), E, D, F)
        x = jax.random.normal(jax.random.PRNGKey(1), (B, D), jnp.float32)
        got = fn(x, gate_w, w1, b1, w2, b2)
        want = _ffn_oracle(x, gate_w, w1, b1, w2, b2)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_capacity_drop_zeroes_overflow_tokens(self):
        """At capacity 1 per (device, expert), overflow tokens contribute a
        zero FFN delta — never garbage."""
        E, D, F, B = 8, 8, 16, 32
        mesh = make_mesh(MeshConfig(), devices=jax.devices()[:8])
        # Bl = B/8 = 4 tokens/device; capacity = max(1, int(4/8·2)) = 1
        fn = make_expert_dispatch(mesh, axis="data", capacity_factor=2.0)
        gate_w, w1, b1, w2, b2 = _make_weights(jax.random.PRNGKey(2), E, D, F)
        # bias every token onto expert 0 → 4 contenders for 1 slot per device
        # (positive tokens × {+1 col 0, −1 elsewhere} ⇒ argmax is always 0)
        gate_w = jnp.where(
            jnp.arange(E)[None, :] == 0, 1.0, -1.0
        ).astype(jnp.float32) * jnp.ones((D, 1), jnp.float32)
        x = (
            jnp.abs(jax.random.normal(jax.random.PRNGKey(3), (B, D))) + 0.1
        ).astype(jnp.float32)
        got = np.asarray(fn(x, gate_w, w1, b1, w2, b2))
        want = np.asarray(_ffn_oracle(x, gate_w, w1, b1, w2, b2))
        assert np.all(np.isfinite(got))
        # every row is either the oracle value (kept) or exactly zero (dropped)
        kept = np.isclose(got, want, rtol=2e-5, atol=2e-5).all(axis=1)
        dropped = (got == 0.0).all(axis=1)
        assert np.all(kept | dropped)
        assert dropped.any(), "capacity 1 with 4 tokens/device must drop"

    def test_routing_is_deterministic_per_token(self):
        """route_top1 keeps at most `capacity` tokens per expert and routes
        every kept token to its argmax expert."""
        E, D, B, C = 4, 8, 32, 3
        key = jax.random.PRNGKey(4)
        x = jax.random.normal(key, (B, D), jnp.float32)
        gate_w = jax.random.normal(jax.random.PRNGKey(5), (D, E), jnp.float32)
        dispatch, combine, probs = route_top1(x, gate_w, E, C)
        assert probs.shape == (B, E)
        d = np.asarray(dispatch)
        assert d.sum(axis=(1, 2)).max() <= 1.0          # ≤1 slot per token
        assert d.sum(axis=(0, 2)).max() <= C            # ≤C tokens per expert
        expert = np.asarray(jnp.argmax(x @ gate_w, axis=-1))
        for b in range(B):
            if d[b].sum() > 0:
                assert d[b, expert[b]].sum() == 1.0


class TestMoEMLP:
    def _cfg(self, E=4):
        cfg = default_config()
        return dataclasses.replace(
            cfg.model, core="transformer", moe_experts=E,
            moe_capacity_factor=float(E), dtype="float32",
        )

    def test_matches_oracle(self):
        from dotaclient_tpu.models.moe import MoEMLP

        mcfg = self._cfg(E=4)
        B, D = 32, mcfg.hidden_dim
        layer = MoEMLP(mcfg)
        x = jax.random.normal(jax.random.PRNGKey(6), (B, D), jnp.float32)
        params = layer.init(jax.random.PRNGKey(7), x)
        got = layer.apply(params, x)
        p = params["params"]
        want = _ffn_oracle(
            x, p["gate"], p["expert_w1"], p["expert_b1"],
            p["expert_w2"], p["expert_b2"],
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.slow   # tier-1 duration audit (ISSUE 6): ~36s on the reference container
    def test_moe_transformer_trains_on_data_model_mesh(self):
        from dotaclient_tpu.models import init_params, make_policy
        from dotaclient_tpu.train.ppo import (
            example_batch,
            init_train_state,
            make_train_step,
        )

        cfg = default_config()
        cfg = dataclasses.replace(
            cfg,
            model=dataclasses.replace(
                cfg.model, core="transformer", n_layers=1, moe_experts=4,
                context_window=4, dtype="float32",
            ),
            ppo=dataclasses.replace(cfg.ppo, rollout_len=4, batch_rollouts=4),
            mesh=MeshConfig(model_parallel=2, data_parallel=-1),
        )
        mesh = make_mesh(cfg.mesh, devices=jax.devices()[:8])
        policy = make_policy(cfg.model, cfg.obs, cfg.actions)
        params = init_params(policy, jax.random.PRNGKey(0))
        state = init_train_state(params, cfg.ppo)
        step = make_train_step(policy, cfg, mesh)
        batch = example_batch(cfg, batch=cfg.ppo.batch_rollouts)
        state, metrics = step(state, batch)
        assert np.isfinite(float(np.asarray(metrics["loss"])))
        w1 = state.params["params"]["core"]["block_0"]["moe"]["expert_w1"]
        assert w1.sharding.spec == P("model", None, None)
        # the Switch load-balancing aux loss flows into the objective:
        # ≥ 1 by Cauchy-Schwarz for top-1 routing (== 1 iff perfectly
        # balanced), and 0 only for dense cores
        aux = float(np.asarray(metrics["moe_aux"]))
        assert aux >= 0.99, aux

    def test_dense_core_has_zero_aux(self):
        from dotaclient_tpu.models import init_params, make_policy
        from dotaclient_tpu.train.ppo import ppo_loss, example_batch

        cfg = default_config()
        cfg = dataclasses.replace(
            cfg, ppo=dataclasses.replace(cfg.ppo, rollout_len=4)
        )
        policy = make_policy(cfg.model, cfg.obs, cfg.actions)
        params = init_params(policy, jax.random.PRNGKey(0))
        _, metrics = ppo_loss(
            policy, params, example_batch(cfg, batch=2), cfg.ppo
        )
        assert float(np.asarray(metrics["moe_aux"])) == 0.0
