"""Actor-runtime tests: chunk contract, episode boundaries, weight refresh,
and the full actor→transport→buffer→learner loop (SURVEY.md §7 step 6)."""

import dataclasses

import numpy as np
import jax
import pytest

from dotaclient_tpu.actor import ActorPool, build_game_config
from dotaclient_tpu.buffer import TrajectoryBuffer
from dotaclient_tpu.config import RunConfig
from dotaclient_tpu.models import init_params, make_policy
from dotaclient_tpu.parallel import make_mesh
from dotaclient_tpu.protos import dota_pb2 as pb
from dotaclient_tpu.train import init_train_state, make_train_step
from dotaclient_tpu.transport import (
    InProcTransport,
    decode_rollout,
    encode_weights,
)


def small_config(**env_kw) -> RunConfig:
    cfg = RunConfig()
    env_kw = {"n_envs": 2, "max_dota_time": 30.0, **env_kw}
    return dataclasses.replace(
        cfg,
        model=dataclasses.replace(cfg.model, dtype="float32"),
        env=dataclasses.replace(cfg.env, **env_kw),
        ppo=dataclasses.replace(cfg.ppo, rollout_len=8, batch_rollouts=8),
        buffer=dataclasses.replace(cfg.buffer, capacity_rollouts=64, min_fill=8),
    )


@pytest.fixture(scope="module")
def policy_params():
    cfg = small_config()
    policy = make_policy(cfg.model, cfg.obs, cfg.actions)
    params = init_params(policy, jax.random.PRNGKey(0))
    return policy, params


def make_pool(cfg, policy, params, **kw):
    return ActorPool(cfg, policy, params, **kw)


class TestGameConfig:
    def test_1v1_scripted(self):
        cfg = small_config()
        gc = build_game_config(cfg, seed=0)
        assert len(gc.hero_picks) == 2
        assert gc.hero_picks[0].control_mode == pb.CONTROL_AGENT
        assert gc.hero_picks[1].control_mode == pb.CONTROL_SCRIPTED_EASY

    def test_selfplay_5v5(self):
        cfg = small_config(team_size=5, opponent="selfplay")
        gc = build_game_config(cfg, seed=0)
        assert len(gc.hero_picks) == 10
        assert all(p.control_mode == pb.CONTROL_AGENT for p in gc.hero_picks)

    def test_hero_pool_sampling(self):
        cfg = small_config(hero_pool=(1, 2, 3))
        ids = {
            build_game_config(cfg, seed=s).hero_picks[0].hero_id
            for s in range(20)
        }
        assert ids == {1, 2, 3}


class TestRolloutContract:
    def test_chunk_shapes_and_versions(self, policy_params):
        policy, params = policy_params
        cfg = small_config()
        shipped = []
        pool = make_pool(cfg, policy, params, rollout_sink=shipped.append,
                         version=3)
        pool.run(cfg.ppo.rollout_len, refresh_every=0)
        assert len(shipped) == 2  # one per lane, chunks full at T
        T = cfg.ppo.rollout_len
        for r in shipped:
            assert r.model_version == 3
            assert r.length == T
            meta, arrays = decode_rollout(r)
            assert arrays["obs"]["units"].shape[0] == T + 1
            assert arrays["rewards"].shape == (T,)
            assert arrays["valid"].sum() == T
            assert arrays["carry0"][0].shape == (cfg.model.hidden_dim,)
            # first chunk of an episode starts from zero state
            np.testing.assert_array_equal(arrays["carry0"][0], 0.0)

    def test_second_chunk_carries_state(self, policy_params):
        policy, params = policy_params
        cfg = small_config()
        shipped = []
        pool = make_pool(cfg, policy, params, rollout_sink=shipped.append)
        pool.run(2 * cfg.ppo.rollout_len, refresh_every=0)
        by_env = {}
        for r in shipped:
            by_env.setdefault(r.env_id, []).append(r)
        for env_id, rolls in by_env.items():
            assert len(rolls) == 2
            _, arrays = decode_rollout(rolls[1])
            # second chunk of a live episode must carry nonzero LSTM state
            assert np.abs(arrays["carry0"][0]).sum() > 0

    def test_episode_end_ships_padded_chunk(self, policy_params):
        policy, params = policy_params
        cfg = small_config(max_dota_time=5.0)  # 25 steps @0.2s > chunk of 8
        shipped = []
        pool = make_pool(cfg, policy, params, rollout_sink=shipped.append)
        pool.run(30, refresh_every=0)
        assert pool.episodes_done >= 2
        # some chunk must be padded (episode length 25 = 8+8+8+1)
        padded = []
        for r in shipped:
            _, arrays = decode_rollout(r)
            if arrays["valid"].sum() < cfg.ppo.rollout_len:
                padded.append(arrays)
        assert padded, "expected at least one early-shipped padded chunk"
        for arrays in padded:
            n = int(arrays["valid"].sum())
            # done flag set at the last valid step; padding is marked done
            assert arrays["dones"][n - 1] == 1.0
            # after an episode a fresh chunk starts from zero carry
        # every post-reset chunk must restart from zeros
        first_chunks = [
            decode_rollout(r)[1] for r in shipped
            if decode_rollout(r)[1]["valid"].sum() == cfg.ppo.rollout_len
        ]
        assert first_chunks

    def test_behavior_logp_is_negative_on_valid_steps(self, policy_params):
        policy, params = policy_params
        cfg = small_config()
        shipped = []
        pool = make_pool(cfg, policy, params, rollout_sink=shipped.append)
        pool.run(cfg.ppo.rollout_len, refresh_every=0)
        for r in shipped:
            _, arrays = decode_rollout(r)
            valid = arrays["valid"].astype(bool)
            assert (arrays["behavior_logp"][valid] <= 0).all()


class TestWeightRefresh:
    def test_refresh_from_transport(self, policy_params):
        policy, params = policy_params
        cfg = small_config()
        transport = InProcTransport()
        pool = make_pool(cfg, policy, params, transport=transport, version=0)
        new_params = jax.tree.map(lambda x: x + 1.0, params)
        transport.publish_weights(
            encode_weights(jax.tree.map(np.asarray, new_params), version=5)
        )
        assert pool.refresh_weights()
        assert pool.version == 5
        leaf_old = jax.tree.leaves(params)[0]
        leaf_new = jax.tree.leaves(pool.params)[0]
        np.testing.assert_allclose(
            np.asarray(leaf_new), np.asarray(leaf_old) + 1.0, rtol=1e-6
        )

    def test_noop_without_new_weights(self, policy_params):
        policy, params = policy_params
        cfg = small_config()
        pool = make_pool(cfg, policy, params, transport=InProcTransport())
        assert not pool.refresh_weights()


class TestSelfplay:
    def test_selfplay_lanes_and_rollouts(self, policy_params):
        policy, params = policy_params
        cfg = small_config(opponent="selfplay")
        shipped = []
        pool = make_pool(cfg, policy, params, rollout_sink=shipped.append)
        assert len(pool.lanes) == 4  # 2 envs x 2 teams
        pool.run(cfg.ppo.rollout_len, refresh_every=0)
        assert len(shipped) == 4
        teams = {decode_rollout(r)[0]["env_id"] for r in shipped}
        assert teams == {0, 1}


class TestEndToEnd:
    def test_actor_to_learner_loop_runs(self, policy_params):
        """Full slice: pool → transport → buffer → train step → weight
        refresh → more rollouts (SURVEY.md §7 'minimum end-to-end slice')."""
        policy, params = policy_params
        cfg = small_config()
        mesh = make_mesh(cfg.mesh)
        transport = InProcTransport()
        pool = make_pool(cfg, policy, params, transport=transport)
        buf = TrajectoryBuffer(cfg, mesh)
        state = init_train_state(params, cfg.ppo)
        step = make_train_step(policy, cfg, mesh)

        n_train_steps = 0
        for _ in range(8):
            pool.run(cfg.ppo.rollout_len, refresh_every=0)
            protos = transport.consume_rollouts(64, timeout=0.01)
            buf.add([decode_rollout(p) for p in protos], int(state.version))
            while (batch := buf.take()) is not None:
                state, metrics = step(state, batch)
                n_train_steps += 1
                assert np.isfinite(float(metrics["loss"]))
            pool.set_params(state.params, int(state.version))
        assert n_train_steps >= 2
        assert pool.version == int(state.version)


class TestWindowedStats:
    def test_mixin_window_deltas(self):
        """Host-pool windowed stats (the best-checkpoint signal) are deltas
        between drains, mirroring DeviceActor's device-side window."""
        from dotaclient_tpu.actor.window_stats import WindowedStatsMixin

        class P(WindowedStatsMixin):
            def __init__(self):
                self.episodes_done = 0
                self.wins = 0
                self.episode_rewards = []

            def stats(self):
                return {
                    "episodes_done": float(self.episodes_done),
                    **self.windowed_entries(),
                }

        p = P()
        assert p.stats()["episodes_recent"] == 0.0
        p.episodes_done, p.wins = 4, 3
        p.episode_rewards = [1.0, 1.0, 2.0, 4.0]
        s = p.drain_stats()
        assert s["episodes_recent"] == 4.0
        assert s["win_rate_recent"] == 0.75
        assert s["ep_reward_recent"] == 2.0
        p.episodes_done, p.wins = 6, 3
        p.episode_rewards += [0.0, 0.0]
        s = p.drain_stats()
        assert s["episodes_recent"] == 2.0
        assert s["win_rate_recent"] == 0.0
        assert s["ep_reward_recent"] == 0.0


class TestConnectBackoff:
    """Actor-process robustness (ISSUE 3 satellite): bounded exponential
    backoff + jitter around transport (re)connects, counted in
    transport/reconnects_total."""

    def test_retries_then_succeeds(self):
        import random

        from dotaclient_tpu.actor.__main__ import connect_with_backoff
        from dotaclient_tpu.utils import telemetry

        reg = telemetry.get_registry()
        before = reg.counter("transport/reconnects_total").value
        sleeps = []
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise ConnectionError("learner not up yet")
            return "transport"

        out = connect_with_backoff(
            flaky, max_attempts=5, base_delay=0.5,
            sleep=sleeps.append, rng=random.Random(0),
        )
        assert out == "transport"
        assert calls["n"] == 3
        # one counted retry per attempt beyond the first
        assert reg.counter("transport/reconnects_total").value - before == 2
        # exponential envelope with full jitter: delay k bounded by
        # base * 2^(k-1), and never negative
        assert len(sleeps) == 2
        assert 0.0 <= sleeps[0] <= 0.5
        assert 0.0 <= sleeps[1] <= 1.0

    def test_bounded_attempts_reraise(self):
        import random

        from dotaclient_tpu.actor.__main__ import connect_with_backoff

        calls = {"n": 0}

        def dead():
            calls["n"] += 1
            raise ConnectionError("gone")

        with pytest.raises(ConnectionError, match="after 3 attempts"):
            connect_with_backoff(
                dead, max_attempts=3, sleep=lambda s: None,
                rng=random.Random(0),
            )
        assert calls["n"] == 3

    def test_abort_mid_backoff_raises_promptly(self):
        """A graceful stop requested during the schedule abandons the
        remaining attempts within one segment — a chaos-scale reconnect
        budget must not outlive the supervisor's SIGTERM grace window
        (ISSUE 6 divergence scenario: the drain's ACTOR_VERSIONS_SEEN
        audit line depends on the actor reaching its clean exit)."""
        import random

        from dotaclient_tpu.actor.__main__ import connect_with_backoff

        calls = {"n": 0}
        flag = {"stop": False}

        def dead():
            calls["n"] += 1
            flag["stop"] = True   # stop lands while we'd be backing off
            raise ConnectionError("gone")

        with pytest.raises(ConnectionError, match="stop requested"):
            connect_with_backoff(
                dead, max_attempts=10, sleep=lambda s: None,
                rng=random.Random(0),
                should_abort=lambda: flag["stop"],
            )
        assert calls["n"] == 1

    def test_jitter_desynchronizes_replicas(self):
        """Two replicas with different seeds must not sleep in lockstep
        (thundering-herd guard)."""
        import random

        from dotaclient_tpu.actor.__main__ import connect_with_backoff

        def sleeps_for(seed):
            sleeps = []

            def dead():
                raise ConnectionError("gone")

            with pytest.raises(ConnectionError):
                connect_with_backoff(
                    dead, max_attempts=4, sleep=sleeps.append,
                    rng=random.Random(seed),
                )
            return sleeps

        assert sleeps_for(1) != sleeps_for(2)
