"""Tracing utility tests (SURVEY.md §5.1 — the reference had only wall-clock
prints; the rebuild's device tracing must actually produce a trace)."""

import os

import jax
import jax.numpy as jnp
import pytest

from dotaclient_tpu.utils.profiling import trace


class TestTrace:
    def test_noop_without_logdir(self):
        with trace(None):
            x = jax.jit(lambda a: a * 2)(jnp.ones((4,)))
        assert float(x.sum()) == 8.0

    @pytest.mark.slow   # tier-1 duration audit (ISSUE 6): ~59s on the reference container
    def test_writes_profile_artifacts(self, tmp_path):
        logdir = str(tmp_path / "prof")
        with trace(logdir):
            jax.block_until_ready(jax.jit(lambda a: a @ a)(jnp.ones((8, 8))))
        found = [
            os.path.join(root, f)
            for root, _dirs, files in os.walk(logdir)
            for f in files
        ]
        # the TensorBoard profile plugin layout: plugins/profile/<run>/...
        assert found, "trace() produced no files"
        assert any("plugins" in p and "profile" in p for p in found)

    def test_trace_closes_on_exception(self, tmp_path):
        logdir = str(tmp_path / "prof2")
        try:
            with trace(logdir):
                raise RuntimeError("boom")
        except RuntimeError:
            pass
        # a second trace must start cleanly (stop_trace ran in finally)
        with trace(str(tmp_path / "prof3")):
            jax.block_until_ready(jnp.ones((2,)) + 1)
