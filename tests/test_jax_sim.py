"""JAX sim / featurizer / on-device rollout tests.

The numpy ``vec_lane_sim`` is the semantic oracle: the JAX sim is a
phase-for-phase port, so over wave-free horizons (no RNG involved) the two
must agree EXACTLY, scripted bots included. The device rollout path is tested
against the training contract (chunk shapes, train-step consumption, the
mid-chunk done/carry-reset semantics of ``Policy.sequence``).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dotaclient_tpu.config import default_config
from dotaclient_tpu.envs import jax_lane_sim as J
from dotaclient_tpu.envs import lane_sim
from dotaclient_tpu.envs.vec_lane_sim import VecLaneSim, VecSimSpec
from dotaclient_tpu.protos import dota_pb2 as pb


def make_pair(n=4, team_size=1, p0=pb.CONTROL_SCRIPTED_EASY,
              p1=pb.CONTROL_SCRIPTED_HARD, seed=0, **kw):
    """A numpy vec sim and a JAX state initialized to the SAME world."""
    spec = VecSimSpec(n_games=n, team_size=team_size, max_units=32, **kw)
    P = spec.n_players
    hero = np.ones((n, P), np.int32)
    ctrl = np.full((n, P), pb.CONTROL_AGENT, np.int32)
    ctrl[:, 0] = p0
    ctrl[:, team_size] = p1
    vsim = VecLaneSim(spec, hero, ctrl, seed=seed)
    jstate = state_from_vec(vsim)
    return spec, vsim, jstate


def state_from_vec(vsim: VecLaneSim) -> J.SimState:
    # jnp.array COPIES — jnp.asarray can zero-copy-alias the numpy buffers
    # on CPU, which the vec sim then mutates in place (async-read corruption)
    return J.SimState(
        key=jax.random.PRNGKey(0),
        **{
            k: jnp.array(getattr(vsim, "_next_wave_at" if k == "next_wave_at" else k))
            for k in J.SimState._fields
            if k not in ("key", "tick")
        },
        tick=jnp.array(vsim.tick.astype(np.int32)),
    )


def noop(n, P):
    a = {
        k: np.zeros((n, P), np.int32)
        for k in ("type", "move_x", "move_y", "target_slot", "ability")
    }
    a["type"][:] = -1
    return a


STATE_FIELDS = (
    "x", "y", "health", "health_max", "mana", "gold", "xp", "level",
    "alive", "kills", "deaths", "last_hits", "denies", "attack_cd",
    "ability_cd", "done", "winning_team",
)


def assert_states_equal(vsim, jstate, context=""):
    for name in STATE_FIELDS:
        a = np.asarray(getattr(vsim, name), np.float64)
        b = np.asarray(getattr(jstate, name), np.float64)
        np.testing.assert_allclose(
            a, b, rtol=1e-4, atol=1e-3, err_msg=f"{context}: field {name}"
        )


class TestJaxSimParity:
    def test_exact_parity_scripted_wave_free(self):
        """140 steps (28 s < first wave respawn at 30 s): zero randomness, so
        the JAX port must track the numpy sim exactly — scripted bots, combat,
        last-hits, XP, deaths, towers, the lot."""
        spec, vsim, jstate = make_pair(n=4)
        step = jax.jit(lambda s, a: J.step(spec, s, a))
        acts = noop(4, 2)
        jacts = {k: jnp.asarray(v) for k, v in acts.items()}
        for t in range(140):
            vsim.step(acts)
            jstate = step(jstate, jacts)
        assert_states_equal(vsim, jstate, "t=140")

    def test_exact_parity_agent_actions(self):
        """Driven hero actions (attack / cast / move) resolve identically."""
        spec, vsim, jstate = make_pair(n=2, p0=pb.CONTROL_AGENT)
        step = jax.jit(lambda s, a: J.step(spec, s, a))
        rng = np.random.default_rng(0)
        for t in range(60):
            acts = noop(2, 2)
            # random-ish but legal-ish agent actions for player 0
            acts["type"][:, 0] = rng.integers(0, 4, size=2)
            acts["move_x"][:, 0] = rng.integers(0, 9, size=2)
            acts["move_y"][:, 0] = rng.integers(0, 9, size=2)
            acts["target_slot"][:, 0] = rng.integers(0, 32, size=2)
            acts["ability"][:, 0] = 0
            vsim.step(acts)
            jstate = step(jstate, {k: jnp.asarray(v) for k, v in acts.items()})
        assert_states_equal(vsim, jstate, "agent-driven t=60")

    def test_full_episode_statistics(self):
        """Across full episodes (waves spawn → RNG differs) the port must
        still produce the same game: hard beats easy, games end."""
        spec = VecSimSpec(n_games=16, team_size=1, max_units=32, max_dota_time=300.0)
        hero = np.ones((16, 2), np.int32)
        ctrl = np.stack(
            [np.full(16, pb.CONTROL_SCRIPTED_EASY),
             np.full(16, pb.CONTROL_SCRIPTED_HARD)], 1
        )
        state = J.init_state(spec, jnp.asarray(hero), jnp.asarray(ctrl),
                             jax.random.PRNGKey(0))
        step = jax.jit(lambda s, a: J.step(spec, s, a))
        a = {k: jnp.asarray(v) for k, v in noop(16, 2).items()}
        for _ in range(1600):
            state = step(state, a)
            if bool(state.done.all()):
                break
        assert bool(state.done.all())
        # timeout wins are tower-HP noisy (hard retreats, easy pushes);
        # kills are the robust dominance signal
        hard_wins = int((state.winning_team == lane_sim.TEAM_DIRE).sum())
        assert hard_wins >= 7
        assert int(state.kills[:, 1].sum()) > 5 * int(state.kills[:, 0].sum())

    def test_deterministic_across_runs(self):
        """Regression: damage/credit accumulation must use fixed-order
        reductions — XLA scatter-add combines duplicate indices in
        unspecified order, which made full-battle outcomes flip run to run."""
        results = []
        for _ in range(2):
            spec = VecSimSpec(n_games=8, team_size=1, max_units=32,
                              max_dota_time=120.0)
            hero = np.ones((8, 2), np.int32)
            ctrl = np.stack(
                [np.full(8, pb.CONTROL_SCRIPTED_EASY),
                 np.full(8, pb.CONTROL_SCRIPTED_HARD)], 1
            )
            state = J.init_state(spec, jnp.asarray(hero), jnp.asarray(ctrl),
                                 jax.random.PRNGKey(3))
            step = jax.jit(lambda s, a: J.step(spec, s, a))
            a = {k: jnp.asarray(v) for k, v in noop(8, 2).items()}
            for _ in range(400):
                state = step(state, a)
            results.append(jax.device_get(state))
        for f in J.SimState._fields:
            if f == "key":
                continue
            np.testing.assert_array_equal(
                getattr(results[0], f), getattr(results[1], f),
                err_msg=f"nondeterministic field {f}",
            )

    def test_reset_where(self):
        spec, vsim, jstate = make_pair(n=3)
        step = jax.jit(lambda s, a: J.step(spec, s, a))
        a = {k: jnp.asarray(v) for k, v in noop(3, 2).items()}
        for _ in range(50):
            jstate = step(jstate, a)
        mask = jnp.asarray([False, True, False])
        jstate2 = jax.jit(lambda s, m: J.reset_where(spec, s, m))(jstate, mask)
        assert float(jstate2.dota_time[1]) == 0.0
        assert float(jstate2.dota_time[0]) > 0.0
        assert bool(jstate2.alive[1, :2].all())
        assert float(jstate2.gold[1, :2].sum()) == 0.0


class TestJaxFeaturizerParity:
    def test_matches_numpy_featurizer(self):
        from dotaclient_tpu.features.jax_featurizer import JaxFeaturizer
        from dotaclient_tpu.features.vec_featurizer import VecFeaturizer

        cfg = default_config()
        spec, vsim, jstate = make_pair(n=3)
        step = jax.jit(lambda s, a: J.step(spec, s, a))
        acts = noop(3, 2)
        for _ in range(40):
            vsim.step(acts)
            jstate = step(jstate, {k: jnp.asarray(v) for k, v in acts.items()})
        vf = VecFeaturizer(vsim, cfg.obs, cfg.actions, [0])
        jf = JaxFeaturizer(spec, cfg.obs, cfg.actions, [0])
        a = vf.featurize_all()
        b = jax.device_get(jf.featurize(jstate))
        assert set(a) == set(b)
        for k in a:
            np.testing.assert_allclose(
                np.asarray(a[k], np.float64), np.asarray(b[k], np.float64),
                rtol=1e-4, atol=1e-5, err_msg=f"obs field {k}",
            )

    def test_rewards_match_numpy(self):
        from dotaclient_tpu.features.jax_featurizer import shaped_rewards
        from dotaclient_tpu.features.vec_featurizer import VecRewards

        spec, vsim, jstate = make_pair(n=3)
        step = jax.jit(lambda s, a: J.step(spec, s, a))
        acts = noop(3, 2)
        jacts = {k: jnp.asarray(v) for k, v in acts.items()}
        for _ in range(20):
            vsim.step(acts)
            jstate = step(jstate, jacts)
        vr = VecRewards(vsim, [0])
        j_prev = jstate
        for _ in range(10):
            vsim.step(acts)
            jstate = step(jstate, jacts)
        r_np = vr.compute()
        r_j = np.asarray(
            shaped_rewards(spec, [0], j_prev, jstate)
        )
        np.testing.assert_allclose(r_np, r_j, rtol=1e-4, atol=1e-5)


class TestSequenceDoneReset:
    def test_sequence_resets_match_stepwise(self):
        """sequence(obs, carry0, dones) == per-step stepping with carry
        zeroed after each done — the contract device chunks rely on."""
        from dotaclient_tpu.models import init_params, make_policy
        from dotaclient_tpu.models.policy import dummy_obs_batch

        cfg = default_config()
        policy = make_policy(
            dataclasses.replace(cfg.model, dtype="float32"), cfg.obs, cfg.actions
        )
        params = init_params(policy, jax.random.PRNGKey(0))
        B, T = 2, 6
        rng = np.random.default_rng(0)
        obs = dummy_obs_batch(B, cfg.obs, cfg.actions, time=T)
        obs = dict(obs)
        obs["units"] = jnp.asarray(
            rng.normal(size=obs["units"].shape).astype(np.float32)
        )
        dones = jnp.asarray(
            [[0, 0, 1, 0, 0, 0], [0, 1, 0, 0, 1, 0]], jnp.float32
        )
        carry0 = policy.initial_state(B)
        logits_seq, values_seq, _ = policy.apply(
            params, obs, carry0, dones, method="sequence"
        )

        carry = carry0
        step_values = []
        step_logits = []
        for t in range(T):
            obs_t = {k: v[:, t] for k, v in obs.items()}
            lg, vv, carry = policy.apply(params, obs_t, carry, method="step")
            step_values.append(vv)
            step_logits.append(lg["action_type"])
            keep = (1.0 - dones[:, t])[:, None]
            carry = (carry[0] * keep, carry[1] * keep)
        np.testing.assert_allclose(
            np.asarray(values_seq), np.stack([np.asarray(v) for v in step_values], 1),
            rtol=1e-5, atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(logits_seq["action_type"]),
            np.stack([np.asarray(l) for l in step_logits], 1),
            rtol=1e-5, atol=1e-5,
        )


class TestDeviceRollout:
    def _actor(self, n_envs=4, opponent="scripted_easy", team_size=1, **env_kw):
        from dotaclient_tpu.actor.device_rollout import DeviceActor
        from dotaclient_tpu.models import init_params, make_policy

        cfg = default_config()
        cfg = dataclasses.replace(
            cfg,
            env=dataclasses.replace(
                cfg.env, n_envs=n_envs, opponent=opponent,
                team_size=team_size, max_dota_time=30.0, **env_kw,
            ),
            ppo=dataclasses.replace(cfg.ppo, rollout_len=8),
        )
        policy = make_policy(cfg.model, cfg.obs, cfg.actions)
        params = init_params(policy, jax.random.PRNGKey(0))
        return cfg, DeviceActor(cfg, policy, seed=0), params

    def test_chunk_contract(self):
        cfg, da, params = self._actor()
        chunk, stats = da.collect(params)
        T = cfg.ppo.rollout_len
        L = da.n_lanes
        assert chunk["obs"]["units"].shape == (
            L, T + 1, cfg.obs.max_units, cfg.obs.unit_features
        )
        assert chunk["rewards"].shape == (L, T)
        assert chunk["valid"].shape == (L, T)
        assert (np.asarray(chunk["valid"]) == 1.0).all()
        assert chunk["carry0"][0].shape == (L, cfg.model.hidden_dim)
        assert set(chunk["actions"]) == set(cfg.actions.head_sizes)

    @pytest.mark.slow   # tier-1 duration audit (ISSUE 6): ~38s on the reference container
    def test_feeds_train_step_and_buffer(self):
        from dotaclient_tpu.buffer import TrajectoryBuffer
        from dotaclient_tpu.parallel import make_mesh
        from dotaclient_tpu.train.ppo import init_train_state, make_train_step

        cfg, da, params = self._actor(n_envs=8)
        cfg = dataclasses.replace(
            cfg,
            ppo=dataclasses.replace(cfg.ppo, batch_rollouts=8),
            buffer=dataclasses.replace(cfg.buffer, capacity_rollouts=32, min_fill=8),
        )
        mesh = make_mesh(cfg.mesh)
        buffer = TrajectoryBuffer(cfg, mesh)
        state = init_train_state(params, cfg.ppo)
        step = make_train_step(da.policy, cfg, mesh)
        chunk, _ = da.collect(params)
        assert buffer.add_device(chunk, version=0) == 8
        batch = buffer.take(current_version=0)
        assert batch is not None
        state, metrics = step(state, batch)
        assert np.isfinite(float(metrics["loss"]))

    def test_episodes_complete_and_stats(self):
        cfg, da, params = self._actor()
        # 30s timeout / (8 steps * 0.2s) ≈ 19 collects per episode
        for _ in range(25):
            da.collect(params)
        s = da.drain_stats()
        assert s["episodes_done"] >= 4
        assert s["episode_reward_mean"] != 0.0

    def test_selfplay_lanes(self):
        cfg, da, params = self._actor(opponent="selfplay")
        assert da.n_lanes == cfg.env.n_envs * 2
        chunk, _ = da.collect(params)
        assert chunk["rewards"].shape[0] == da.n_lanes

    def test_league_opponent_params_used(self):
        """League mode: opponent lanes run on separate (frozen) params and
        ship nothing; different opponent params must change the game flow."""
        cfg, da, params = self._actor(opponent="league")
        assert da.n_lanes == cfg.env.n_envs  # only Radiant ships
        chunk, _ = da.collect(params, opp_params=params)
        assert chunk["rewards"].shape[0] == cfg.env.n_envs

    @pytest.mark.slow   # tier-1 duration audit (ISSUE 6): ~102s on the reference container
    def test_learner_device_mode(self):
        from dotaclient_tpu.train.learner import Learner

        cfg = default_config()
        cfg = dataclasses.replace(
            cfg,
            env=dataclasses.replace(
                cfg.env, n_envs=8, opponent="scripted_easy", max_dota_time=30.0
            ),
            ppo=dataclasses.replace(cfg.ppo, rollout_len=8, batch_rollouts=8),
            buffer=dataclasses.replace(cfg.buffer, capacity_rollouts=32, min_fill=8),
            log_every=100,
        )
        lrn = Learner(cfg, actor="device")
        stats = lrn.train(6)
        assert stats["optimizer_steps"] >= 6
        assert stats["actor_rollouts_shipped"] > 0
