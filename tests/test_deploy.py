"""Deployment-manifest drift tests (SURVEY.md §1 row 7).

The k8s manifests embed CLI invocations of the learner and actor
entrypoints. Nothing else executes them in CI, so a renamed/removed flag
would ship a manifest that crash-loops at deploy time. These tests pin:
every ``--flag`` a manifest passes exists in the target module's argparse
surface, the ``-m`` module paths are importable, and the service/selector
plumbing that the actor fleet depends on stays consistent.
"""

import importlib.util
import os
import re

import pytest

yaml = pytest.importorskip("yaml")   # PyYAML: baked into this image, but
                                     # the suite must not die without it

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
K8S = os.path.join(ROOT, "deploy", "k8s")


def load_docs(name):
    with open(os.path.join(K8S, name)) as f:
        return [d for d in yaml.safe_load_all(f) if d is not None]


def container_specs(doc):
    if doc.get("kind") != "Deployment":
        return []
    return doc["spec"]["template"]["spec"]["containers"]


def split_module_and_flags(args):
    """Parse a ``[-m, module, --flag=value, ...]`` container args list."""
    assert args[0] == "-m", args
    module = args[1]
    flags = [a.split("=", 1)[0] for a in args[2:] if a.startswith("--")]
    return module, flags


def argparse_flags_of(module_rel_path):
    src = open(os.path.join(ROOT, module_rel_path)).read()
    return set(re.findall(r'"(--[a-z0-9-]+)"', src))


CLI_SOURCES = {
    "dotaclient_tpu.train.learner": "dotaclient_tpu/train/learner.py",
    "dotaclient_tpu.actor": "dotaclient_tpu/actor/__main__.py",
}


class TestManifests:
    def test_yaml_parses(self):
        for name in os.listdir(K8S):
            assert load_docs(name), name

    def test_manifest_flags_exist_in_cli(self):
        checked = 0
        for name in os.listdir(K8S):
            for doc in load_docs(name):
                for c in container_specs(doc):
                    if "args" not in c:
                        continue
                    module, flags = split_module_and_flags(c["args"])
                    assert module in CLI_SOURCES, (
                        f"{name}: unknown entry module {module}"
                    )
                    known = argparse_flags_of(CLI_SOURCES[module])
                    for fl in flags:
                        assert fl in known, (
                            f"{name}: {module} does not accept {fl}"
                        )
                        checked += 1
        assert checked >= 8  # both deployments actually carry flags

    def test_entry_modules_importable(self):
        for module in CLI_SOURCES:
            spec = importlib.util.find_spec(module)
            assert spec is not None, module

    def test_actor_connects_to_learner_service(self):
        """The actor fleet's --connect target must match the learner
        Service name and port."""
        services = {
            d["metadata"]["name"]: d
            for d in load_docs("learner.yaml")
            if d.get("kind") == "Service"
        }
        (actor,) = [
            c
            for d in load_docs("actors.yaml")
            for c in container_specs(d)
        ]
        connect = [a for a in actor["args"] if a.startswith("--connect=")]
        assert connect, "actor manifest must pass --connect"
        host, port = connect[0].split("=", 1)[1].rsplit(":", 1)
        assert host in services, f"no Service named {host}"
        ports = [p["port"] for p in services[host]["spec"]["ports"]]
        assert int(port) in ports, (host, port, ports)

    def test_actor_pods_get_unique_seed_source(self):
        """Replicated actors derive their rollout seed from POD_NAME — the
        manifest must inject it or every replica streams identical
        experience (actor/__main__.py seed derivation)."""
        (actor,) = [
            c
            for d in load_docs("actors.yaml")
            for c in container_specs(d)
        ]
        env_names = {e["name"] for e in actor.get("env", [])}
        assert "POD_NAME" in env_names
