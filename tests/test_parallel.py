"""Parallelism-library tests: TP sharding rules on the forced host mesh.

SURVEY.md §2.3 row 3 / VERDICT round 1 item 6: the ``model`` mesh axis must
do real work. The pin here is GSPMD's semantic transparency: a widened core
trained on a (1, 2) data×model mesh must produce the same numbers as the
single-device run.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from dotaclient_tpu.config import MeshConfig, default_config
from dotaclient_tpu.models import init_params, make_policy
from dotaclient_tpu.parallel import make_mesh, param_spec, state_shardings
from dotaclient_tpu.train.ppo import init_train_state, make_train_step


def wide_config():
    cfg = default_config()
    return dataclasses.replace(
        cfg,
        model=dataclasses.replace(cfg.model, hidden_dim=512, dtype="float32"),
        ppo=dataclasses.replace(cfg.ppo, rollout_len=4, batch_rollouts=4),
    )


def wide_batch(cfg, policy, params, batch=4, seed=0):
    """Self-consistent batch at the widened-core shapes."""
    from dotaclient_tpu.models import distributions as D
    from dotaclient_tpu.train.ppo import example_batch

    rng = np.random.default_rng(seed)
    T = cfg.ppo.rollout_len
    b = example_batch(cfg, batch=batch)
    obs = dict(b["obs"])
    obs["units"] = jnp.asarray(rng.normal(size=obs["units"].shape).astype(np.float32))
    obs["globals"] = jnp.asarray(rng.normal(size=obs["globals"].shape).astype(np.float32))
    b["obs"] = obs
    b["dones"] = jnp.asarray((rng.random((batch, T)) < 0.1).astype(np.float32))
    logits, _, _ = policy.apply(params, obs, b["carry0"], b["dones"], method="sequence")
    logits_t = {k: v[:, :T] for k, v in logits.items()}
    obs_t = {k: v[:, :T] for k, v in obs.items()}
    actions, logp = D.sample(jax.random.PRNGKey(seed), logits_t, obs_t)
    b["actions"] = actions
    b["behavior_logp"] = logp
    b["rewards"] = jnp.asarray(rng.normal(size=(batch, T)).astype(np.float32))
    return b


class TestParamSpec:
    def test_rules(self):
        cfg = MeshConfig(model_parallel=2, data_parallel=1)
        mesh = make_mesh(cfg, devices=jax.devices()[:2])
        # divisible last axis -> sharded on model
        assert param_spec((128, 512), mesh, cfg) == P(None, "model")
        assert param_spec((512,), mesh, cfg) == P("model")
        # indivisible (tiny head) -> replicated
        assert param_spec((128, 9), mesh, cfg) == P()
        assert param_spec((1,), mesh, cfg) == P()
        # scalars -> replicated
        assert param_spec((), mesh, cfg) == P()

    def test_model_parallel_1_replicates_everything(self):
        cfg = MeshConfig(model_parallel=1, data_parallel=1)
        mesh = make_mesh(cfg, devices=jax.devices()[:1])
        assert param_spec((128, 512), mesh, cfg) == P()
        assert param_spec((512,), mesh, cfg) == P()


class TestSequenceParallel:
    """Ring / Ulysses attention vs the dense oracle, 8-device sequence
    sharding (SURVEY.md §2.3 row 5, §7 step 8)."""

    def _qkv(self, B=2, T=32, h=8, d=16, seed=0):
        rng = np.random.default_rng(seed)
        mk = lambda: jnp.asarray(rng.normal(size=(B, T, h, d)).astype(np.float32))
        return mk(), mk(), mk()

    @pytest.mark.parametrize("causal", [False, True])
    def test_ring_attention_matches_reference(self, causal):
        from dotaclient_tpu.parallel.sequence import (
            make_ring_attention,
            reference_attention,
        )

        mesh = make_mesh(MeshConfig(data_parallel=8, model_parallel=1))
        q, k, v = self._qkv()
        ring = make_ring_attention(mesh, axis="data", causal=causal)
        out = jax.device_get(ring(q, k, v))
        ref = jax.device_get(reference_attention(q, k, v, causal=causal))
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_ulysses_attention_matches_reference(self, causal):
        from dotaclient_tpu.parallel.sequence import (
            make_ulysses_attention,
            reference_attention,
        )

        mesh = make_mesh(MeshConfig(data_parallel=8, model_parallel=1))
        q, k, v = self._qkv(seed=3)
        uly = make_ulysses_attention(mesh, axis="data", causal=causal)
        out = jax.device_get(uly(q, k, v))
        ref = jax.device_get(reference_attention(q, k, v, causal=causal))
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_ring_memory_is_sharded(self):
        """Each device's shard of the output is T/8 of the sequence."""
        from dotaclient_tpu.parallel.sequence import make_ring_attention

        mesh = make_mesh(MeshConfig(data_parallel=8, model_parallel=1))
        q, k, v = self._qkv()
        out = make_ring_attention(mesh, axis="data")(q, k, v)
        shapes = {s.data.shape for s in out.addressable_shards}
        assert shapes == {(2, 4, 8, 16)}


class TestPipelineParallel:
    """GPipe-style stage pipeline vs sequential application (SURVEY.md §2.3
    row 4, §7 step 8)."""

    def test_pipeline_matches_sequential(self):
        import flax.linen as nn
        from dotaclient_tpu.parallel.pipeline import (
            make_pipeline,
            stack_stage_params,
        )

        S, M, B, D = 4, 8, 32, 64
        mesh = make_mesh(
            MeshConfig(data_parallel=1, model_parallel=S,
                       model_axis="stage", data_axis="data"),
            devices=jax.devices()[:S],
        )

        class Block(nn.Module):
            @nn.compact
            def __call__(self, x):
                return x + nn.Dense(D)(nn.tanh(x))

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(B, D)).astype(np.float32))
        block = Block()
        params_list = [
            block.init(jax.random.PRNGKey(s), x) for s in range(S)
        ]
        stacked = stack_stage_params(params_list)

        pipe = make_pipeline(
            lambda p, a: block.apply(p, a), mesh, axis="stage",
            n_microbatches=M,
        )
        out = pipe(stacked, x)

        ref = x
        for p in params_list:
            ref = block.apply(p, ref)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )


class TestTensorParallelEquivalence:
    @pytest.mark.slow   # tier-1 duration audit (ISSUE 6): ~58s on the reference container
    def test_wide_core_tp2_matches_single_device(self):
        """hidden=512 policy, one train step: (1 data, 2 model) mesh output
        must match the 1-device run (same math, different layout)."""
        base = wide_config()
        policy = make_policy(base.model, base.obs, base.actions)
        params = init_params(policy, jax.random.PRNGKey(0))
        batch = wide_batch(base, policy, params, batch=4, seed=3)

        results = {}
        for name, mesh_cfg, devs in (
            ("single", MeshConfig(data_parallel=1, model_parallel=1), 1),
            ("tp2", MeshConfig(data_parallel=1, model_parallel=2), 2),
        ):
            cfg = dataclasses.replace(base, mesh=mesh_cfg)
            mesh = make_mesh(cfg.mesh, devices=jax.devices()[:devs])
            state = init_train_state(params, cfg.ppo)
            step = make_train_step(policy, cfg, mesh)
            state, metrics = step(state, batch)
            state, metrics = step(state, batch)
            results[name] = (
                jax.device_get(metrics),
                jax.device_get(state.params),
            )

        m1, p1 = results["single"]
        m2, p2 = results["tp2"]
        for k in m1:
            np.testing.assert_allclose(
                m1[k], m2[k], rtol=2e-4, atol=2e-5, err_msg=f"metric {k}"
            )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5),
            p1, p2,
        )

    def test_tp2_state_actually_sharded(self):
        """The TP path must actually shard parameter leaves over the model
        axis (not silently replicate)."""
        base = wide_config()
        cfg = dataclasses.replace(
            base, mesh=MeshConfig(data_parallel=1, model_parallel=2)
        )
        mesh = make_mesh(cfg.mesh, devices=jax.devices()[:2])
        policy = make_policy(cfg.model, cfg.obs, cfg.actions)
        params = init_params(policy, jax.random.PRNGKey(0))
        state = init_train_state(params, cfg.ppo)
        step = make_train_step(policy, cfg, mesh)
        batch = wide_batch(cfg, policy, params, batch=4, seed=0)
        state, _ = step(state, batch)
        kernel = state.params["params"]["trunk_proj"]["kernel"]
        spec = kernel.sharding.spec
        assert spec == P(None, "model"), f"trunk kernel not TP-sharded: {spec}"
        # each device holds half the columns
        shard_shapes = {s.data.shape for s in kernel.addressable_shards}
        assert shard_shapes == {(kernel.shape[0], kernel.shape[1] // 2)}
