"""End-to-end chaos test (ISSUE 4 acceptance): the real multi-process
topology under the seeded fault plan of ``scripts/chaos_run.py`` — one
actor SIGKILLed (and supervisor-restarted), one actor corrupting frames on
the wire, the learner SIGTERM'd mid-run and relaunched with ``--restore``.

PASS means: no process died of an unhandled exception, the drained learner
exited 0 with a full-pipeline checkpoint, the restarted learner resumed at
EXACTLY the saved optimizer step (final checkpoint = saved + resume
steps), and the corrupt frames were observed (counted) by the learner.

Multi-process with two learner boot cycles → several minutes on this
container; marked slow (excluded from tier-1 — the in-process chaos smoke
in tests/test_faults.py covers the layer there).
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_chaos_run_end_to_end(tmp_path):
    env = dict(os.environ)
    env.pop("DOTA_FAULTS", None)   # the supervisor sets per-child specs
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "scripts", "chaos_run.py"),
            "--workdir", str(tmp_path / "chaos"),
            "--seed", "0",
            "--timeout", "900",
        ],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=960,
    )
    summary_lines = [
        line for line in proc.stdout.splitlines()
        if line.startswith("CHAOS_SUMMARY ")
    ]
    assert summary_lines, (
        f"no CHAOS_SUMMARY emitted\nstdout:\n{proc.stdout[-3000:]}\n"
        f"stderr:\n{proc.stderr[-3000:]}"
    )
    summary = json.loads(summary_lines[-1][len("CHAOS_SUMMARY "):])
    assert proc.returncode == 0 and summary.get("ok"), summary
    # the individual clauses, spelled out for a readable failure
    assert summary["learner1_exit"] == 0      # SIGTERM → clean drain
    assert summary["learner2_exit"] == 0      # restored run completed
    assert summary["actor_kills"] >= 1        # schedule really killed one
    assert summary["frames_corrupt_total"] >= 1
    assert summary["saved_step"] >= 1
    # exact resume: restored learner continued from the saved step
    assert summary["final_step"] == summary["saved_step"] + 10


@pytest.mark.slow
def test_chaos_divergence_scenario(tmp_path):
    """ISSUE 6 acceptance: an injected NaN gradient in the real
    multi-process topology triggers automatic last-good rollback, the run
    completes to its exact target step with exit 0, and no actor ever
    applied a version from the poisoned range."""
    env = dict(os.environ)
    env.pop("DOTA_FAULTS", None)   # the supervisor sets per-child specs
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "scripts", "chaos_run.py"),
            "--scenario", "divergence",
            "--workdir", str(tmp_path / "chaos"),
            "--seed", "0",
            "--timeout", "900",
        ],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=960,
    )
    summary_lines = [
        line for line in proc.stdout.splitlines()
        if line.startswith("CHAOS_SUMMARY ")
    ]
    assert summary_lines, (
        f"no CHAOS_SUMMARY emitted\nstdout:\n{proc.stdout[-3000:]}\n"
        f"stderr:\n{proc.stderr[-3000:]}"
    )
    summary = json.loads(summary_lines[-1][len("CHAOS_SUMMARY "):])
    assert proc.returncode == 0 and summary.get("ok"), summary
    assert summary["learner_exit"] == 0
    assert summary["rollbacks_total"] >= 1
    assert summary["nonfinite_steps_total"] >= 1
    assert summary["final_step"] == 24            # target reached exactly
    assert summary["leaked_versions"] == []       # poison never published
    assert any(summary["actor_versions_seen"])    # fanout really happened


@pytest.mark.slow
def test_chaos_alerts_scenario(tmp_path):
    """ISSUE 13 acceptance: the alert engine's test-in-anger. A killed
    actor's SILENCE fires the ``fleet_peer_stale`` alert with its
    runbook anchor, the restarted incarnation RESOLVES it, the injected
    corrupt frames fire the integrity alert, and the learner still
    drains cleanly with ``alerts/fired_total`` >= 2 on record."""
    env = dict(os.environ)
    env.pop("DOTA_FAULTS", None)   # the supervisor sets per-child specs
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "scripts", "chaos_run.py"),
            "--scenario", "alerts",
            "--workdir", str(tmp_path / "chaos"),
            "--seed", "0",
            "--timeout", "900",
        ],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=960,
    )
    summary_lines = [
        line for line in proc.stdout.splitlines()
        if line.startswith("CHAOS_SUMMARY ")
    ]
    assert summary_lines, (
        f"no CHAOS_SUMMARY emitted\nstdout:\n{proc.stdout[-3000:]}\n"
        f"stderr:\n{proc.stderr[-3000:]}"
    )
    summary = json.loads(summary_lines[-1][len("CHAOS_SUMMARY "):])
    assert proc.returncode == 0 and summary.get("ok"), summary
    assert summary["learner_exit"] == 0
    assert summary["stale_alert_fired"]["runbook"] == "rb:fleet-peer-stale"
    assert summary["stale_alert_fired"]["severity"] == "page"
    assert summary["stale_alert_resolved_after_s"] > 0
    assert summary["corrupt_alert_fired"]["runbook"] == "rb:corrupt-frames"
    assert summary["alerts_fired_total"] >= 2
    assert summary["fleet_peers_seen"] >= 2


@pytest.mark.slow
def test_chaos_outcome_scenario(tmp_path):
    """ISSUE 15 acceptance: episode outcomes reach the learner through
    the fleet snapshot lane, the whole fleet killed-and-held fires
    ``outcome_stream_stale`` with its runbook anchor (the fleet tick
    evaluates on wall clock while training stalls), the restarted
    fleet's fresh episodes RESOLVE it, and ``outcome_report`` finds
    usable curves in the drained learner's JSONL."""
    env = dict(os.environ)
    env.pop("DOTA_FAULTS", None)
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "scripts", "chaos_run.py"),
            "--scenario", "outcome",
            "--workdir", str(tmp_path / "chaos"),
            "--seed", "0",
            "--timeout", "900",
        ],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=960,
    )
    summary_lines = [
        line for line in proc.stdout.splitlines()
        if line.startswith("CHAOS_SUMMARY ")
    ]
    assert summary_lines, (
        f"no CHAOS_SUMMARY emitted\nstdout:\n{proc.stdout[-3000:]}\n"
        f"stderr:\n{proc.stderr[-3000:]}"
    )
    summary = json.loads(summary_lines[-1][len("CHAOS_SUMMARY "):])
    assert proc.returncode == 0 and summary.get("ok"), summary
    assert summary["learner_exit"] == 0
    assert summary["episodes_before_kill"] >= 1
    assert summary["stale_alert_fired"]["runbook"] == "rb:outcome-stale"
    assert summary["stale_alert_resolved_after_s"] > 0
    assert summary["outcome_status"]["ok"] is True
    assert summary["outcome_status"]["episodes_total"] >= 1
