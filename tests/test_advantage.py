"""One-pass advantage plane tests (ISSUE 14, train/advantage.py).

Pins: the pass is bitwise-equal to the in-step recompute at f32 (and
within bf16 tolerance when stored narrow), the one-pass train step
matches the recompute step to float-ulp XLA-fusion rounding, the staged
and fused epoch paths agree on one-pass batches at E×M = 4, the learner
wires/gates/reports the plane, a divergence rollback discards staged
advantages with the flushed prefetch lane, and the telemetry tier +
lint coverage hold.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dotaclient_tpu.config import RunConfig
from dotaclient_tpu.models import init_params, make_policy
from dotaclient_tpu.parallel import make_mesh
from dotaclient_tpu.train import (
    example_batch,
    init_train_state,
    make_epoch_step,
    make_train_step,
)
from dotaclient_tpu.train.advantage import (
    advantages_and_returns,
    make_advantage_pass,
    one_pass_enabled,
    store_dtype,
)
from dotaclient_tpu.utils import telemetry


def small_cfg(**ppo) -> RunConfig:
    cfg = RunConfig()
    return dataclasses.replace(
        cfg,
        env=dataclasses.replace(cfg.env, n_envs=4, max_dota_time=30.0),
        ppo=dataclasses.replace(
            cfg.ppo, **{"rollout_len": 8, "batch_rollouts": 8, **ppo}
        ),
        buffer=dataclasses.replace(
            cfg.buffer, capacity_rollouts=32, min_fill=8
        ),
        log_every=1000,
        checkpoint_every=1000,
    )


def random_batch(cfg: RunConfig, batch: int, seed: int):
    rng = np.random.default_rng(seed)
    B, T = batch, cfg.ppo.rollout_len
    out = example_batch(cfg, batch=B)
    out["obs"] = dict(out["obs"])
    out["obs"]["units"] = jnp.asarray(
        rng.normal(size=out["obs"]["units"].shape).astype(np.float32)
    )
    out["rewards"] = jnp.asarray(
        rng.normal(size=(B, T)).astype(np.float32) * 0.1
    )
    out["behavior_logp"] = jnp.asarray(
        -np.abs(rng.normal(size=(B, T))).astype(np.float32)
    )
    out["dones"] = jnp.asarray(
        (rng.random((B, T)) < 0.1).astype(np.float32)
    )
    return out


@pytest.fixture(scope="module")
def setup():
    cfg = small_cfg()
    policy = make_policy(cfg.model, cfg.obs, cfg.actions)
    params = init_params(policy, jax.random.PRNGKey(0))
    return cfg, policy, params


class TestPassParity:
    def test_gating(self):
        # E×M = 1: the in-step estimator already runs once per batch —
        # the plane only engages when it can amortize
        assert not one_pass_enabled(small_cfg())
        assert one_pass_enabled(small_cfg(epochs_per_batch=2))
        assert one_pass_enabled(small_cfg(minibatches=2, batch_rollouts=16))
        assert not one_pass_enabled(
            small_cfg(epochs_per_batch=2, one_pass_advantage=False)
        )
        assert not one_pass_enabled(
            small_cfg(epochs_per_batch=2, advantage="vtrace")
        )
        assert store_dtype(small_cfg()) == jnp.bfloat16
        assert (
            store_dtype(small_cfg(advantage_dtype="float32")) == jnp.float32
        )
        with pytest.raises(ValueError, match="advantage_dtype"):
            store_dtype(small_cfg(advantage_dtype="fp8"))

    def test_vtrace_pass_rejected(self, setup):
        cfg, policy, _ = setup
        vcfg = dataclasses.replace(
            cfg, ppo=dataclasses.replace(cfg.ppo, advantage="vtrace")
        )
        with pytest.raises(ValueError, match="vtrace"):
            make_advantage_pass(policy, vcfg, make_mesh(cfg.mesh))

    def test_pass_bitwise_equals_in_step_recompute_at_f32(self, setup):
        """The pinned contract: the pass's f32 output IS the in-step
        estimator — same apply, same scan, compiled standalone."""
        cfg, policy, params = setup
        mesh = make_mesh(cfg.mesh)
        batch = random_batch(cfg, batch=8, seed=1)
        f32 = dataclasses.replace(
            cfg, ppo=dataclasses.replace(cfg.ppo, advantage_dtype="float32")
        )
        adv, ret = make_advantage_pass(policy, f32, mesh)(params, batch)
        ref = jax.jit(
            lambda p, b: advantages_and_returns(policy, p, b, cfg.ppo)
        )
        adv_ref, ret_ref = ref(params, batch)
        assert adv.dtype == jnp.float32
        assert np.array_equal(np.asarray(adv), np.asarray(adv_ref))
        assert np.array_equal(np.asarray(ret), np.asarray(ret_ref))

    @pytest.mark.slow   # tier-1 duration audit: two train-step traces, ~6s
    def test_one_pass_step_matches_recompute_step(self, setup):
        """A train step consuming the f32 pass output must match the
        in-step-recompute step on the same params/batch — to the
        float-ulp rounding of the T-vs-T+1 forward fusion (the only
        difference between the two compiled programs)."""
        cfg, policy, params = setup
        mesh = make_mesh(cfg.mesh)
        batch = random_batch(cfg, batch=8, seed=2)
        f32 = dataclasses.replace(
            cfg, ppo=dataclasses.replace(cfg.ppo, advantage_dtype="float32")
        )
        adv, ret = make_advantage_pass(policy, f32, mesh)(params, batch)
        step = make_train_step(policy, cfg, mesh)
        s_re, m_re = step(init_train_state(params, cfg.ppo), batch)
        s_op, m_op = step(
            init_train_state(params, cfg.ppo),
            {**batch, "advantages": adv, "returns": ret},
        )
        for k in ("loss", "policy_loss", "value_loss", "entropy"):
            np.testing.assert_allclose(
                np.asarray(m_re[k]), np.asarray(m_op[k]),
                rtol=1e-5, atol=1e-7,
            )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
            ),
            s_re.params,
            s_op.params,
        )

    def test_bf16_storage_within_tolerance(self, setup):
        cfg, policy, params = setup
        mesh = make_mesh(cfg.mesh)
        batch = random_batch(cfg, batch=8, seed=3)
        f32 = dataclasses.replace(
            cfg, ppo=dataclasses.replace(cfg.ppo, advantage_dtype="float32")
        )
        adv32, ret32 = make_advantage_pass(policy, f32, mesh)(params, batch)
        adv16, ret16 = make_advantage_pass(policy, cfg, mesh)(params, batch)
        assert adv16.dtype == jnp.bfloat16 and ret16.dtype == jnp.bfloat16
        # bf16 has 8 mantissa bits: relative error ≤ 2^-8 per element
        np.testing.assert_allclose(
            np.asarray(adv16, np.float32), np.asarray(adv32),
            rtol=2 ** -7, atol=1e-3,
        )
        np.testing.assert_allclose(
            np.asarray(ret16, np.float32), np.asarray(ret32),
            rtol=2 ** -7, atol=1e-3,
        )


class TestEpochParity:
    @pytest.mark.slow   # tier-1 duration audit: epoch-step + staged traces, ~6s
    def test_staged_equals_fused_on_one_pass_batches_at_exm4(self, setup):
        """End-to-end epoch parity at E×M = 4 on PRECOMPUTED advantages:
        the staged gather+step loop and the fused epoch scan consume the
        same staged leaves and must produce the same updates (the
        float-ulp XLA-fusion bound of tests/test_train.py's recompute
        parity test)."""
        cfg, policy, params = setup
        # tests run at 8 forced host devices (conftest): minibatch size
        # B/M must divide the batch shard count, so B=16 with M=2
        E, M, B = 2, 2, 16
        ecfg = dataclasses.replace(
            cfg,
            ppo=dataclasses.replace(
                cfg.ppo, epochs_per_batch=E, minibatches=M, batch_rollouts=B
            ),
        )
        mesh = make_mesh(ecfg.mesh)
        batch = random_batch(ecfg, batch=B, seed=4)
        adv, ret = make_advantage_pass(policy, ecfg, mesh)(params, batch)
        aug = {**batch, "advantages": adv, "returns": ret}
        perms = np.stack(
            [np.random.default_rng(41).permutation(B) for _ in range(E)]
        ).astype(np.int32)

        from dotaclient_tpu.parallel import data_sharding

        gather = jax.jit(
            lambda b, idx: jax.tree.map(lambda x: x[idx], b),
            out_shardings=data_sharding(mesh, ecfg.mesh),
        )
        step = make_train_step(policy, ecfg, mesh)
        staged = init_train_state(params, ecfg.ppo)
        mb = B // M
        for e in range(E):
            for i in range(M):
                idx = jnp.asarray(perms[e, i * mb:(i + 1) * mb], jnp.int32)
                staged, _ = step(staged, gather(aug, idx))

        epoch_step = make_epoch_step(policy, ecfg, mesh)
        fused = init_train_state(params, ecfg.ppo)
        fused, _ = epoch_step(fused, aug, jnp.asarray(perms))
        assert int(fused.step) == int(staged.step) == E * M
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-7
            ),
            fused.params,
            staged.params,
        )


class TestLearnerIntegration:
    @pytest.mark.slow   # tier-1 duration audit: full learner construction, ~14s
    def test_learner_runs_one_pass_at_exm4_and_reports(self):
        """Device-mode learner at E×M = 4: the plane is live, batches
        train through the fused epoch step on precomputed advantages,
        and every advantage/ key reports."""
        from dotaclient_tpu.train.learner import Learner

        cfg = small_cfg(
            epochs_per_batch=2, minibatches=2, batch_rollouts=16
        )
        cfg = dataclasses.replace(
            cfg,
            env=dataclasses.replace(cfg.env, n_envs=8),
            buffer=dataclasses.replace(
                cfg.buffer, capacity_rollouts=32, min_fill=16
            ),
        )
        learner = Learner(cfg, actor="device")
        try:
            assert learner.advantage_pass is not None
            stats = learner.train(4)   # one consumed batch = 4 steps
            assert stats["optimizer_steps"] == 4
            assert int(learner.state.step) == 4
            snap = telemetry.get_registry().snapshot()
            assert snap["advantage/one_pass"] == 1.0
            assert snap["advantage/passes_total"] >= 1.0
            assert snap["advantage/pass_ms"] >= 0.0
            assert 0.0 <= snap.get("advantage/overlap_fraction", 0.0) <= 1.0
        finally:
            if learner._snap_engine is not None:
                learner._snap_engine.stop()

    def test_opt_out_and_vtrace_keep_recompute(self):
        from dotaclient_tpu.train.learner import Learner

        # epochs_per_batch=2 so the KNOB (not the E×M = 1 gate) is what
        # disables the plane in each case
        for ppo in (
            {"one_pass_advantage": False, "epochs_per_batch": 2},
            {"advantage": "vtrace", "epochs_per_batch": 2},
        ):
            learner = Learner(small_cfg(**ppo), actor="device")
            try:
                assert learner.advantage_pass is None
                assert (
                    telemetry.get_registry().snapshot()["advantage/one_pass"]
                    == 0.0
                )
            finally:
                if learner._snap_engine is not None:
                    learner._snap_engine.stop()

    def test_fused_mode_has_no_pass(self):
        from dotaclient_tpu.train.learner import Learner

        cfg = small_cfg(epochs_per_batch=2)
        cfg = dataclasses.replace(
            cfg, env=dataclasses.replace(cfg.env, n_envs=8)
        )
        learner = Learner(cfg, actor="fused")
        try:
            assert learner.advantage_pass is None
        finally:
            if learner._snap_engine is not None:
                learner._snap_engine.stop()


class TestRollbackHygiene:
    @pytest.mark.slow   # tier-1 duration audit: learner + checkpoint round trip, ~16s
    def test_rollback_discards_staged_advantages(self, tmp_path):
        """The pin: a divergence rollback flushes the prefetch lane, and
        with it every advantage staged by the (possibly poisoned) params
        — the requeued slots re-gather and re-pass under the restored
        params on the next take."""
        from dotaclient_tpu.train.learner import Learner

        cfg = small_cfg(epochs_per_batch=2)   # E×M > 1: the plane is live
        cfg = dataclasses.replace(
            cfg, env=dataclasses.replace(cfg.env, n_envs=8)
        )
        learner = Learner(
            cfg, actor="device", checkpoint_dir=str(tmp_path / "ck")
        )
        try:
            # train(2)'s end-of-run forced save is verdict-clean → it
            # earns the last_good mark the rollback restores
            learner.train(2)
            # refill the ring and stage a prefetched batch + advantages
            chunk, _ = learner.device_actor.collect(learner.state.params)
            learner.buffer.add_device(chunk, learner._host_version)
            learner._prefetch_next(drain_transport=False)
            assert learner._prefetched is not None
            assert "advantages" in learner._prefetched
            size_before = learner.buffer.size
            # latch divergence (the sync fold path: NaN loss verdict)
            learner._health.fold_host(
                learner._host_step,
                learner._host_version,
                {"loss": float("nan"), "grad_norm": 1.0, "health_ok": 0.0},
            )
            assert learner._health.unhealthy is not None
            rewound = learner._maybe_rollback()
            assert rewound >= 0
            # the staged batch (and its advantages) are GONE; its slots
            # folded back into the ring for the retrained timeline
            assert learner._prefetched is None
            assert learner._prefetch_ticket is None
            assert learner.buffer.size == size_before + cfg.ppo.batch_rollouts
            assert learner._health.unhealthy is None
            # the next take re-runs the pass under the restored params
            batch = learner._next_batch(drain_transport=False)
            assert batch is not None and "advantages" in batch
            assert np.isfinite(
                np.asarray(batch["advantages"], np.float32)
            ).all()
        finally:
            if learner._snap_engine is not None:
                learner._snap_engine.stop()
            if learner.ckpt is not None:
                learner.ckpt.wait()
                learner.ckpt.close()


class TestSchemaAndLint:
    def test_advantage_tier_round_trip(self):
        """--require-advantage: a line carrying the tier validates; a
        line missing any advantage/ key fails with the tier named."""
        import scripts.check_telemetry_schema as mod

        keys = set(mod.ADVANTAGE_KEYS)
        for k in mod.REQUIRED_KEYS:
            if k.startswith("span/"):
                root = k.rsplit("/", 1)[0]
                keys.update(f"{root}/{leaf}" for leaf in mod.TIMER_LEAVES)
            else:
                keys.add(k)
        import json

        ok_line = json.dumps(
            {"ts": 1.0, "step": 1, "scalars": {k: 0.0 for k in sorted(keys)}}
        )
        assert not mod.validate_lines(
            [ok_line], extra_required=mod.ADVANTAGE_KEYS
        )
        bare = json.dumps(
            {
                "ts": 1.0,
                "step": 1,
                "scalars": {
                    k: 0.0 for k in sorted(keys - set(mod.ADVANTAGE_KEYS))
                },
            }
        )
        errors = mod.validate_lines([bare], extra_required=mod.ADVANTAGE_KEYS)
        assert errors and "advantage/one_pass" in errors[0]

    def test_host_sync_scans_advantage_module(self):
        """The pass must stay dispatch-only: the host-sync lint scans
        train/advantage.py whole (no allowed functions) and finds it
        clean today."""
        import os

        from dotaclient_tpu.lint.core import REPO_ROOT
        from dotaclient_tpu.lint.host_sync import ALLOWED_FUNCS, check_source

        rel = "dotaclient_tpu/train/advantage.py"
        assert ALLOWED_FUNCS[rel] == set()
        with open(os.path.join(REPO_ROOT, rel)) as f:
            assert check_source(f.read(), set(), rel) == []

    def test_donation_registry_would_track_a_donating_pass(self):
        """make_advantage_pass deliberately donates nothing (params are
        live, the batch is consumed next) — but if it ever grows a
        donate_argnums, the use-after-donate factory registry must pick
        it up package-wide, exactly like make_train_step."""
        from dotaclient_tpu.lint.core import FileCtx
        from dotaclient_tpu.lint.donation import build_factory_registry

        donating = (
            "import jax\n"
            "def make_advantage_pass(policy, config, mesh):\n"
            "    def _pass(params, batch):\n"
            "        return batch\n"
            "    return jax.jit(_pass, donate_argnums=(1,))\n"
        )
        ctx = FileCtx("x.py", donating)
        registry = build_factory_registry({"x.py": ctx})
        assert registry.get("make_advantage_pass") == (1,)
        # and the real one is donation-free by design
        import inspect

        from dotaclient_tpu.train import advantage

        src = inspect.getsource(advantage)
        assert "donate_argnums" not in src
