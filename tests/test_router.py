"""Serve-fleet failover tests (ISSUE 19).

Pins the fleet contracts: the client's bounded failure budget (deadline
expiry mid-window, retry-then-succeed, retry-exhausted — all surfacing as
the typed ``ServeDeadlineError``, never a hang), the router's control
plane (attach/where/detach/status), death declaration with hot-spare
promotion and session re-homing, the honest re-home state contract
(default: explicit counted carry reset; carry-shadow: bit-exact resume,
pinned by the parity digest), quarantine composing with the recovery path
(slot reclaimed, fresh slot, NOT a re-home), and the ``--require-router``
telemetry tier.

The fast tests run against a wire-accurate fake backend (attach frame +
scripted behaviors, no jit); the end-to-end failover paths ride real
engines and are slow-marked.
"""

import dataclasses
import socket
import threading
import time

import numpy as np
import pytest

from dotaclient_tpu.models.distributions import HEADS
from dotaclient_tpu.serve import (
    PolicyServer,
    ServeClient,
    ServeDeadlineError,
    SessionRouter,
    route_call,
)
from dotaclient_tpu.serve.server import (
    ATTACH_REQUEST_ID,
    KIND_SERVE_REPLY,
    KIND_SERVE_REQUEST,
    encode_reply,
)
from dotaclient_tpu.transport.socket_transport import (
    FrameCorrupt,
    FramingLost,
    _recv_frame,
    _send_frame,
)
from dotaclient_tpu.transport.serialize import decode_rollout_bytes
from dotaclient_tpu.utils import telemetry
from tests.test_serve import make_engine, one_obs, tiny_config, wait_until


class FakeBackend:
    """A wire-accurate, policy-free serve backend: accepts connections,
    sends the attach frame, then applies one scripted behavior to request
    frames. Heartbeat (probe) frames are read and ignored, so a
    ``SessionRouter`` sees it as a live peer.

    behaviors:
      * ``"echo"``       — reply to every request (fixed action row)
      * ``"blackhole"``  — read requests, never reply (a stuck window)
      * ``"close_first"``— close the connection on the first N requests
                           it ever sees, then echo (transient failure)
    """

    def __init__(self, behavior="echo", close_first=0):
        self.behavior = behavior
        self.close_remaining = [close_first]
        self.requests_seen = [0]
        self._lock = threading.Lock()
        self._next_slot = [0]
        self._conns = []
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(32)
        self.address = self._listener.getsockname()
        self._closed = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="fake-accept", daemon=True
        )
        self._accept_thread.start()

    def _accept_loop(self):
        while not self._closed.is_set():
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return
            with self._lock:
                self._conns.append(sock)
                slot = self._next_slot[0]
                self._next_slot[0] += 1
            threading.Thread(
                target=self._conn_loop, args=(sock, slot),
                name="fake-conn", daemon=True,
            ).start()

    def _conn_loop(self, sock, slot):
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            _send_frame(
                sock,
                KIND_SERVE_REPLY,
                encode_reply(
                    np.zeros((len(HEADS),), np.int32), 0.0, 1, slot,
                    ATTACH_REQUEST_ID,
                ),
            )
            while not self._closed.is_set():
                frame = _recv_frame(sock)
                if frame is None:
                    return
                kind, payload = frame
                if kind != KIND_SERVE_REQUEST:
                    continue  # probe heartbeats: read and ignore
                with self._lock:
                    self.requests_seen[0] += 1
                    must_close = self.close_remaining[0] > 0
                    if must_close:
                        self.close_remaining[0] -= 1
                if self.behavior == "blackhole":
                    continue
                if self.behavior == "close_first" and must_close:
                    return
                meta, _arrays = decode_rollout_bytes(
                    bytes(payload), upcast=True
                )
                _send_frame(
                    sock,
                    KIND_SERVE_REPLY,
                    encode_reply(
                        np.array([1, 2, 3, 0, 4], np.int32), 0.25, 1,
                        slot, meta["rollout_id"],
                        dispatch_idx=self.requests_seen[0],
                    ),
                )
        except (OSError, FrameCorrupt, FramingLost):
            pass
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def close(self):
        self._closed.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            conns, self._conns = self._conns, []
        for sock in conns:
            try:
                sock.close()
            except OSError:
                pass


# -- client failure budget (deadline/retry matrix, fake backend) -------------


def test_deadline_expiry_mid_window_is_typed_and_bounded():
    """A backend that accepts the request and never replies (the stuck-
    window shape) must surface as ServeDeadlineError WITHIN the budget —
    not a hang on the socket timeout."""
    backend = FakeBackend("blackhole")
    config = tiny_config(request_deadline_s=0.6, request_retries=2)
    try:
        client = ServeClient(*backend.address, config, timeout_s=5.0)
        t0 = time.monotonic()
        with pytest.raises(ServeDeadlineError):
            client.step(one_obs(config), reset=True)
        elapsed = time.monotonic() - t0
        # budget + one bounded backoff segment of slack, nowhere near the
        # 5 s socket timeout
        assert elapsed < 3.0, f"deadline not honored: {elapsed:.1f}s"
        client.close()
    finally:
        backend.close()


def test_retry_then_succeed_counts_the_discontinuity():
    """A transient connection drop rides the retry path transparently —
    and the fresh slot's carry reset is explicit and counted, never
    silent."""
    backend = FakeBackend("close_first", close_first=1)
    config = tiny_config(request_deadline_s=10.0, request_retries=4)
    try:
        client = ServeClient(*backend.address, config, timeout_s=5.0)
        actions = client.step(one_obs(config), reset=True)
        assert client.retries_total >= 1
        assert actions["action_type"] == 1
        assert np.array_equal(
            client.last_packed, np.array([1, 2, 3, 0, 4], np.int32)
        )
        # the reconnect landed on a fresh slot: the restore path made the
        # reset explicit (default mode) and counted it
        assert client.carry_resets == 1
        # no router in play: a plain reconnect is NOT a re-home
        assert client.rehomed_count == 0
        client.close()
    finally:
        backend.close()


def test_retry_exhausted_raises_typed_error_with_bounded_attempts():
    config = tiny_config(request_deadline_s=30.0, request_retries=1)
    backend = FakeBackend("close_first", close_first=100)
    try:
        client = ServeClient(*backend.address, config, timeout_s=5.0)
        with pytest.raises(ServeDeadlineError) as exc:
            client.step(one_obs(config), reset=True)
        # attempts = retries + 1, spelled out in the error
        assert "2 attempt(s)" in str(exc.value)
        assert client.retries_total == 2
        client.close()
    finally:
        backend.close()


# -- router control plane (fake backends) ------------------------------------


def router_stack(n_backends=2, n_spares=0, **serve_over):
    serve_over.setdefault("router_probe_s", 0.1)
    serve_over.setdefault("router_dead_after_s", 0.4)
    config = tiny_config(**serve_over)
    backends = [
        FakeBackend("echo") for _ in range(n_backends + n_spares)
    ]
    reg = telemetry.Registry()
    router = SessionRouter(
        config,
        [b.address for b in backends[:n_backends]],
        spares=[b.address for b in backends[n_backends:]],
        registry=reg,
    )
    return config, backends, reg, router


def _route(router, request):
    sock = socket.create_connection(router.address, timeout=5.0)
    try:
        return route_call(sock, request, timeout=5.0)
    finally:
        sock.close()


def test_router_attach_where_detach_status():
    config, backends, reg, router = router_stack(n_backends=2)
    try:
        assert wait_until(
            lambda: reg.snapshot().get("router/backends_live") == 2.0
        )
        a = _route(router, {"op": "attach"})
        b = _route(router, {"op": "attach"})
        assert a["session"] != b["session"]
        addrs = {tuple(x.address) for x in backends}
        assert (a["addr"][0], a["addr"][1]) in addrs
        # least-loaded assignment spreads the two sessions
        assert a["addr"] != b["addr"]
        w = _route(router, {"op": "where", "session": a["session"]})
        assert w["addr"] == a["addr"] and w["epoch"] == 0
        assert not w["rehomed"]
        status = _route(router, {"op": "status"})
        assert len(status["backends"]) == 2
        assert _route(
            router, {"op": "detach", "session": a["session"]}
        )["detached"]
        assert not _route(
            router, {"op": "detach", "session": a["session"]}
        )["detached"]
        assert _route(router, {"op": "nonsense"}).get("error")
        snap = reg.snapshot()
        assert snap["router/sessions_attached_total"] == 2.0
        assert snap["router/sessions_detached_total"] == 1.0
        assert snap["router/sessions_active"] == 1.0
        assert snap["router/route_errors_total"] == 1.0
    finally:
        router.close()
        for b in backends:
            b.close()


def test_router_death_promotes_spare_and_rehomes_sessions():
    config, backends, reg, router = router_stack(n_backends=2, n_spares=1)
    try:
        assert wait_until(
            lambda: reg.snapshot().get("router/backends_live") == 2.0
            and reg.snapshot().get("router/spares_available") == 1.0
        )
        sessions = [_route(router, {"op": "attach"}) for _ in range(4)]
        dead_addr = list(backends[0].address)
        doomed = [s for s in sessions if s["addr"] == dead_addr]
        assert doomed, "least-loaded attach must have used backend 0"
        backends[0].close()
        assert wait_until(
            lambda: reg.snapshot().get("router/backends_dead") == 1.0,
            timeout=10.0,
        )
        snap = reg.snapshot()
        # promotion is a routing change only: the spare joined the pool
        assert snap["router/spares_promoted_total"] == 1.0
        assert snap["router/spares_available"] == 0.0
        assert snap["router/backends_live"] == 2.0
        assert snap["router/sessions_rehomed_total"] == float(len(doomed))
        for s in doomed:
            w = _route(router, {"op": "where", "session": s["session"]})
            assert w["addr"] != dead_addr
            assert w["epoch"] == 1 and w["rehomed"]
        # survivors kept their home and epoch
        for s in sessions:
            if s in doomed:
                continue
            w = _route(router, {"op": "where", "session": s["session"]})
            assert w["addr"] == s["addr"] and w["epoch"] == 0
    finally:
        router.close()
        for b in backends:
            b.close()


def test_client_follows_router_redirect_after_backend_death():
    """Fleet-mode client vs fake backends: the backend dies mid-game, the
    next step rides the router's redirect to the survivor — one re-home,
    one counted carry reset, zero client-visible errors."""
    config, backends, reg, router = router_stack(
        n_backends=2, request_deadline_s=10.0, request_retries=8
    )
    try:
        assert wait_until(
            lambda: reg.snapshot().get("router/backends_live") == 2.0
        )
        client = ServeClient(
            *router.address, config, timeout_s=5.0, router=True
        )
        client.step(one_obs(config), reset=True)
        home = list(client.backend_addr)
        victim = next(
            b for b in backends if list(b.address) == home
        )
        victim.close()
        client.step(one_obs(config, seed=1))
        assert client.rehomed_count == 1 and client.last_rehomed
        assert list(client.backend_addr) != home
        assert client.carry_resets == 1   # default mode: explicit reset
        client.close()
        assert wait_until(
            lambda: reg.snapshot().get("router/sessions_rehomed_total")
            >= 1.0
        )
    finally:
        router.close()
        for b in backends:
            b.close()


# -- quarantine composing with recovery (real serve stack) -------------------


@pytest.mark.slow
def test_quarantine_reclaims_slot_and_recovery_is_not_a_rehome():
    """A quarantined client's slot is reclaimed; its retry path lands on a
    fresh slot of the SAME (live) backend through the router — a counted
    carry reset, but NOT a re-home (epoch unchanged)."""
    config = tiny_config(
        max_batch=1, batch_window_ms=0.0, max_slots=2,
        request_deadline_s=30.0, request_retries=8,
        router_probe_s=0.1, router_dead_after_s=0.4,
    )
    config = dataclasses.replace(
        config,
        transport=dataclasses.replace(
            config.transport, poison_frame_limit=1
        ),
    )
    reg = telemetry.Registry()
    engine = make_engine(config, registry=reg)
    server = PolicyServer(engine, config, port=0, registry=reg)
    rreg = telemetry.Registry()
    router = SessionRouter(config, [server.address], registry=rreg)
    try:
        assert wait_until(
            lambda: rreg.snapshot().get("router/backends_live") == 1.0
        )
        client = ServeClient(
            *router.address, config, timeout_s=5.0, router=True
        )
        client.step(one_obs(config), reset=True)
        # poison the lane: one corrupt frame trips the limit and the
        # server quarantines this connection (cut + slot reclaim)
        client._sock.sendall(b"\xde\xad\xbe\xef" * 4)
        assert wait_until(
            lambda: reg.snapshot().get("transport/peers_quarantined")
            == 1.0
        )
        # the probe conn holds one slot; ours was reclaimed — the next
        # step reconnects onto a fresh slot and succeeds
        client.step(one_obs(config, seed=1))
        assert client.retries_total >= 1
        assert client.carry_resets == 1
        assert client.rehomed_count == 0   # same live backend: no re-home
        snap = reg.snapshot()
        assert snap["serve/slots_in_use"] == 2.0  # probe + this client
        client.close()
    finally:
        router.close()
        server.close()
        engine.stop()


# -- end-to-end failover on real engines -------------------------------------


@pytest.mark.slow
def test_rehome_on_real_backend_death_default_mode():
    """Two real backends + spare behind the router; the client's home dies
    mid-game. Default (no shadow) mode: the session re-homes onto the
    promoted spare and resumes on an explicit counted carry reset."""
    from dotaclient_tpu.models.policy import init_params as _init
    import jax

    from dotaclient_tpu.serve import make_inference_policy, ServeEngine

    config = tiny_config(
        max_batch=1, batch_window_ms=0.0, max_slots=4,
        request_deadline_s=30.0, request_retries=16,
        router_probe_s=0.1, router_dead_after_s=0.4,
    )
    policy = make_inference_policy(config)
    params = _init(policy, jax.random.PRNGKey(0))
    stacks = []
    for _ in range(2):
        reg = telemetry.Registry()
        engine = ServeEngine(config, policy, params, registry=reg)
        server = PolicyServer(engine, config, port=0, registry=reg)
        stacks.append((reg, engine, server))
    rreg = telemetry.Registry()
    router = SessionRouter(
        config, [stacks[0][2].address], spares=[stacks[1][2].address],
        registry=rreg,
    )
    try:
        assert wait_until(
            lambda: rreg.snapshot().get("router/backends_live") == 1.0
            and rreg.snapshot().get("router/spares_available") == 1.0
        )
        client = ServeClient(
            *router.address, config, timeout_s=10.0, router=True
        )
        for i in range(3):
            client.step(one_obs(config, seed=i), reset=(i == 0))
        stacks[0][2].close()
        stacks[0][1].stop()
        for i in range(3, 6):
            client.step(one_obs(config, seed=i))
        assert client.rehomed_count == 1
        assert client.carry_resets == 1
        assert list(client.backend_addr) == list(stacks[1][2].address)
        client.close()
        snap = rreg.snapshot()
        assert snap["router/spares_promoted_total"] == 1.0
        assert snap["router/backend_deaths_total"] == 1.0
        assert snap["router/sessions_rehomed_total"] >= 1.0
    finally:
        router.close()
        for _reg, engine, server in stacks:
            server.close()
            engine.stop()


@pytest.mark.slow
def test_rehome_parity_digest_is_bitwise():
    """The acceptance pin: the carry-shadow re-home resumes bit-exact,
    proven by reference_step replay across the kill boundary, with the
    teeth check keeping the proof honest."""
    from scripts.serve_loadgen import run_rehome_parity

    digest = run_rehome_parity(seed=0)
    assert digest["parity"] == "bitwise", digest
    assert digest["teeth"] is True
    assert digest["mismatches"] == 0
    assert digest["rehomed_sessions"] >= 1
    assert digest["rehomed_to_spare"] is True


# -- telemetry contract -------------------------------------------------------


def test_require_router_schema_tier(tmp_path):
    """A router process's JSONL satisfies --require-router at
    construction — every key is eager-created, a zero-traffic router
    still validates."""
    from scripts.check_telemetry_schema import ROUTER_KEYS, validate_lines

    config, backends, reg, router = router_stack(n_backends=1, n_spares=1)
    try:
        path = tmp_path / "router.jsonl"
        sink = telemetry.JsonlSink(str(path))
        sink.emit(0, reg.snapshot())
        sink.close()
        lines = path.read_text().splitlines()
        errors = validate_lines(
            lines, extra_required=ROUTER_KEYS, base_required=()
        )
        assert errors == [], errors
    finally:
        router.close()
        for b in backends:
            b.close()
