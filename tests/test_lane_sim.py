"""Lane-sim behavior: determinism, economy, combat, win conditions."""

import copy

from dotaclient_tpu.envs import lane_sim
from dotaclient_tpu.envs.env_api import LocalDotaEnv
from dotaclient_tpu.protos import dota_pb2 as pb


def config_1v1(agent_mode=pb.CONTROL_SCRIPTED_EASY, opp=pb.CONTROL_SCRIPTED_EASY,
               seed=0, max_time=600.0):
    return pb.GameConfig(
        ticks_per_observation=6,
        max_dota_time=max_time,
        seed=seed,
        hero_picks=[
            pb.HeroPick(team_id=lane_sim.TEAM_RADIANT, hero_id=1, control_mode=agent_mode),
            pb.HeroPick(team_id=lane_sim.TEAM_DIRE, hero_id=1, control_mode=opp),
        ],
    )


def run_scripted(config, max_steps=10_000):
    sim = lane_sim.LaneSim(config)
    for _ in range(max_steps):
        if sim.done:
            break
        sim.step({})
    return sim


def test_determinism_same_seed():
    a = run_scripted(config_1v1(seed=7), max_steps=300)
    b = run_scripted(config_1v1(seed=7), max_steps=300)
    assert a.world_state(2).SerializeToString() == b.world_state(2).SerializeToString()


def test_creep_waves_spawn_and_march():
    sim = lane_sim.LaneSim(config_1v1())
    creeps0 = [u for u in sim.units.values() if u.unit_type == pb.UNIT_LANE_CREEP]
    assert len(creeps0) == 2 * lane_sim.CREEPS_PER_WAVE
    x0 = {c.handle: c.x for c in creeps0}
    for _ in range(10):
        sim.step({})
    moved = [c for c in creeps0 if c.handle in sim.units and sim.units[c.handle].x != x0[c.handle]]
    assert moved, "creeps should march"
    # second wave arrives by t=30
    while sim.dota_time < 31.0:
        sim.step({})
    ws = sim.world_state(2)
    assert ws.tick > 0 and ws.dota_time > 30.0


def test_game_reaches_terminal_state():
    sim = run_scripted(config_1v1(max_time=240.0))
    assert sim.done
    assert sim.game_state == pb.GAME_STATE_POST_GAME
    assert sim.winning_team in (0, lane_sim.TEAM_RADIANT, lane_sim.TEAM_DIRE)


def test_scripted_bots_accumulate_economy():
    sim = run_scripted(config_1v1(
        agent_mode=pb.CONTROL_SCRIPTED_HARD, opp=pb.CONTROL_SCRIPTED_HARD,
        max_time=180.0))
    players = sim.world_state(2).players
    assert any(p.gold > 100.0 for p in players)
    assert any(p.xp > 0.0 for p in players)
    hard_hero = sim.hero_for_player(0)
    assert hard_hero.last_hits > 0, "hard bot should secure last hits"


def test_hard_beats_easy_on_average():
    wins = 0
    n = 5
    for seed in range(n):
        sim = run_scripted(config_1v1(
            agent_mode=pb.CONTROL_SCRIPTED_HARD, opp=pb.CONTROL_SCRIPTED_EASY,
            seed=seed, max_time=300.0))
        if sim.winning_team == lane_sim.TEAM_RADIANT:
            wins += 1
    assert wins >= n - 1, f"hard bot won only {wins}/{n} vs easy"


def test_nuke_respects_mana_and_cooldown():
    sim = lane_sim.LaneSim(config_1v1(agent_mode=pb.CONTROL_AGENT))
    hero = sim.hero_for_player(0)
    enemy = sim.hero_for_player(1)
    hero.x, hero.y = enemy.x - 100.0, enemy.y  # walk into nuke range
    hp0 = enemy.health
    cast = pb.Action(player_id=0, type=pb.ACTION_CAST,
                     target_handle=enemy.handle, ability_slot=lane_sim.NUKE_SLOT)
    sim.step({0: cast})
    assert enemy.health < hp0, "nuke should damage"
    assert hero.ability_cooldown > 0.0
    hp1 = enemy.health
    sim.step({0: cast})  # on cooldown: no second hit
    regen = 2.0
    assert enemy.health >= hp1 - 1e-6 and enemy.health <= hp1 + regen


def test_local_env_api_multi_team_step_gating():
    env = LocalDotaEnv()
    cfg = config_1v1(agent_mode=pb.CONTROL_AGENT, opp=pb.CONTROL_AGENT)
    init = env.reset(cfg)
    assert init.status == pb.STATUS_OK
    assert len(init.world_states) == 2  # both teams agent-controlled
    t0 = env.observe(lane_sim.TEAM_RADIANT).world_state.tick
    env.act(pb.Actions(team_id=lane_sim.TEAM_RADIANT))  # only one team acted
    assert env.observe(lane_sim.TEAM_RADIANT).world_state.tick == t0
    env.act(pb.Actions(team_id=lane_sim.TEAM_DIRE))  # now both -> sim steps
    assert env.observe(lane_sim.TEAM_RADIANT).world_state.tick > t0


def test_observe_reports_episode_done():
    env = LocalDotaEnv()
    env.reset(config_1v1(max_time=1.0))
    for _ in range(20):
        env.act(pb.Actions(team_id=lane_sim.TEAM_RADIANT))
    resp = env.observe(lane_sim.TEAM_RADIANT)
    assert resp.status == pb.STATUS_EPISODE_DONE
    assert resp.world_state.game_state == pb.GAME_STATE_POST_GAME


def test_act_rejects_bad_and_cross_team_player_ids():
    env = LocalDotaEnv()
    env.reset(config_1v1(agent_mode=pb.CONTROL_AGENT, opp=pb.CONTROL_AGENT))
    sim = env._core.sim
    dire_x0 = sim.hero_for_player(1).x
    env.act(pb.Actions(team_id=lane_sim.TEAM_RADIANT, actions=[
        pb.Action(player_id=5, type=pb.ACTION_MOVE, move_x=0, move_y=4),
        pb.Action(player_id=-1, type=pb.ACTION_MOVE, move_x=0, move_y=4),
        pb.Action(player_id=1, type=pb.ACTION_MOVE, move_x=0, move_y=4),  # dire hero
    ]))
    env.act(pb.Actions(team_id=lane_sim.TEAM_DIRE))
    assert sim.hero_for_player(1).x == dire_x0


def test_unacted_agent_hero_noops_not_scripted():
    env = LocalDotaEnv()
    env.reset(config_1v1(agent_mode=pb.CONTROL_AGENT, opp=pb.CONTROL_AGENT))
    sim = env._core.sim
    x0, y0 = sim.hero_for_player(0).x, sim.hero_for_player(0).y
    for _ in range(5):
        env.act(pb.Actions(team_id=lane_sim.TEAM_RADIANT))
        env.act(pb.Actions(team_id=lane_sim.TEAM_DIRE))
    assert (sim.hero_for_player(0).x, sim.hero_for_player(0).y) == (x0, y0)


def test_move_bins_from_game_config():
    cfg = config_1v1(agent_mode=pb.CONTROL_AGENT)
    cfg.move_bins = 5
    env = LocalDotaEnv()
    env.reset(cfg)
    sim = env._core.sim
    assert sim.move_bins == 5
    x0 = sim.hero_for_player(0).x
    env.act(pb.Actions(team_id=lane_sim.TEAM_RADIANT, actions=[
        pb.Action(player_id=0, type=pb.ACTION_MOVE, move_x=2, move_y=2)]))
    assert sim.hero_for_player(0).x == x0  # center bin: no motion
    env.act(pb.Actions(team_id=lane_sim.TEAM_RADIANT, actions=[
        pb.Action(player_id=0, type=pb.ACTION_MOVE, move_x=4, move_y=2)]))
    assert sim.hero_for_player(0).x > x0  # edge bin: +x


def test_dead_hero_stays_in_worldstate():
    sim = lane_sim.LaneSim(config_1v1(agent_mode=pb.CONTROL_AGENT))
    hero = sim.hero_for_player(0)
    hero.health = 1.0
    enemy = sim.hero_for_player(1)
    sim._deal_damage(enemy, hero, 100.0)
    assert not hero.alive
    rows = [u for u in sim.world_state(lane_sim.TEAM_RADIANT).units
            if u.player_id == 0]
    assert len(rows) == 1 and not rows[0].is_alive
