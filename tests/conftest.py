"""Test harness configuration.

Forces JAX onto the host CPU platform with 8 virtual devices, so every
sharding/collective test runs the same way the driver's multi-chip dry-run
does (SURVEY.md §4 "Distributed-without-a-cluster") and the real TPU chip is
never contended by the test suite.

Note: this sandbox's sitecustomize pre-imports jax (axon PJRT registration)
before any conftest runs, so setting JAX_PLATFORMS via os.environ here is too
late. Backends initialize lazily, so `jax.config.update` still redirects, and
XLA_FLAGS is read at first backend init — set both before any test touches a
device.
"""

import os
import time

import jax
import pytest

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
jax.config.update("jax_platforms", "cpu")


# -- tier-1 duration guard ---------------------------------------------------
# The tier-1 budget is one 870s pytest run for the WHOLE suite; a single
# slow unmarked test eats everyone else's budget. Any test whose call phase
# exceeds TIER1_TEST_BUDGET_S (default 5s) without a @pytest.mark.slow is
# reported in a terminal summary section; TIER1_DURATION_STRICT=1 turns the
# report into a failing exit status (opt-in — this container's wall clock
# swings with neighbor load, so the default guard names offenders without
# flaking the suite).

_DURATION_BUDGET_S = float(os.environ.get("TIER1_TEST_BUDGET_S", "5"))
_duration_offenders = []


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from tier-1 (-m 'not slow'); required on any test "
        f"whose call phase exceeds the {_DURATION_BUDGET_S:.0f}s duration "
        "budget (tests/conftest.py tier-1 duration guard)",
    )


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_call(item):
    t0 = time.perf_counter()
    yield
    elapsed = time.perf_counter() - t0
    if (
        elapsed > _DURATION_BUDGET_S
        and item.get_closest_marker("slow") is None
    ):
        _duration_offenders.append((item.nodeid, elapsed))


def pytest_terminal_summary(terminalreporter):
    if not _duration_offenders:
        return
    terminalreporter.section("tier-1 duration guard")
    terminalreporter.write_line(
        f"{len(_duration_offenders)} test(s) exceeded the "
        f"{_DURATION_BUDGET_S:.0f}s budget without @pytest.mark.slow "
        f"(the 870s tier-1 budget must cover the whole suite):"
    )
    for nodeid, elapsed in sorted(
        _duration_offenders, key=lambda kv: -kv[1]
    ):
        terminalreporter.write_line(f"  {elapsed:7.1f}s  {nodeid}")


def pytest_sessionfinish(session, exitstatus):
    if _duration_offenders and os.environ.get("TIER1_DURATION_STRICT"):
        session.exitstatus = 1
