"""Test harness configuration.

Forces JAX onto the host CPU platform with 8 virtual devices, so every
sharding/collective test runs the same way the driver's multi-chip dry-run
does (SURVEY.md §4 "Distributed-without-a-cluster") and the real TPU chip is
never contended by the test suite.

Note: this sandbox's sitecustomize pre-imports jax (axon PJRT registration)
before any conftest runs, so setting JAX_PLATFORMS via os.environ here is too
late. Backends initialize lazily, so `jax.config.update` still redirects, and
XLA_FLAGS is read at first backend init — set both before any test touches a
device.
"""

import os

import jax

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
jax.config.update("jax_platforms", "cpu")
