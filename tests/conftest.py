"""Test harness configuration.

Forces JAX onto the host CPU platform with 8 virtual devices *before* jax is
imported anywhere, so every sharding/collective test runs the same way the
driver's multi-chip dry-run does (SURVEY.md §4 "Distributed-without-a-
cluster") and the real TPU chip is never contended by the test suite.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
