"""Telemetry-core + pipeline-wiring tests (ISSUE 1: unified telemetry).

Covers the registry primitives (counter/gauge/timer semantics, span
nesting), the JSONL sink round-trip, MetricsLogger's graceful degrade
without tensorboardX, the learner smoke run's staleness/queue-depth
gauges, the documented JSONL schema (via scripts/check_telemetry_schema),
and the sync discipline: telemetry must add ZERO host↔device syncs to the
train loop (device fetches happen only at log_every boundaries).
"""

import dataclasses
import importlib.util
import json
import os
import sys
import time

import jax
import numpy as np
import pytest

from dotaclient_tpu.config import RunConfig
from dotaclient_tpu.utils import telemetry
from dotaclient_tpu.utils.metrics import MetricsLogger


def tiny_config(**over) -> RunConfig:
    cfg = RunConfig()
    return dataclasses.replace(
        cfg,
        env=dataclasses.replace(cfg.env, n_envs=2, max_dota_time=30.0),
        ppo=dataclasses.replace(cfg.ppo, rollout_len=8, batch_rollouts=8),
        buffer=dataclasses.replace(cfg.buffer, capacity_rollouts=32, min_fill=8),
        checkpoint_every=10_000,
        **over,
    )


class TestRegistry:
    def test_counter_semantics(self):
        r = telemetry.Registry()
        r.counter("x").inc()
        r.counter("x").inc(2.5)
        assert r.snapshot()["x"] == pytest.approx(3.5)
        # create-or-get: same object by name
        assert r.counter("x") is r.counter("x")

    def test_gauge_last_write_wins(self):
        r = telemetry.Registry()
        r.gauge("g").set(1.0)
        r.gauge("g").set(7.0)
        assert r.snapshot()["g"] == 7.0

    def test_timer_stats(self):
        r = telemetry.Registry()
        t = r.timer("t")
        t.observe(0.1)
        t.observe(0.3)
        snap = r.snapshot()
        assert snap["t/count"] == 2
        assert snap["t/total_s"] == pytest.approx(0.4)
        assert snap["t/last_s"] == pytest.approx(0.3)
        assert snap["t/mean_s"] == pytest.approx(0.2)
        # EMA moves toward the last observation
        assert 0.1 < snap["t/ema_s"] < 0.3
        # approximate histogram quantile: within its 2x bucket bound
        assert 0.15 <= snap["t/p95_s"] <= 0.8

    def test_timer_time_contextmanager(self):
        r = telemetry.Registry()
        with r.timer("slept").time():
            time.sleep(0.01)
        assert r.snapshot()["slept/last_s"] >= 0.01

    def test_span_records_and_nests(self):
        r = telemetry.Registry()
        with r.span("outer"):
            time.sleep(0.002)
            with r.span("inner"):
                time.sleep(0.002)
        snap = r.snapshot()
        assert snap["span/outer/count"] == 1
        assert snap["span/outer/inner/count"] == 1
        # the outer span encloses the inner one
        assert snap["span/outer/last_s"] >= snap["span/outer/inner/last_s"]

    def test_span_nesting_depth_three(self):
        """Regression: stack entries are full names — joining the whole
        stack once duplicated prefixes ('span/a/a/b/c') at depth >= 3."""
        r = telemetry.Registry()
        with r.span("a"):
            with r.span("b"):
                with r.span("c"):
                    pass
        snap = r.snapshot()
        assert snap["span/a/b/c/count"] == 1
        assert "span/a/a/b/c/count" not in snap

    def test_span_absolute_names_do_not_nest(self):
        """Documented pipeline stages ('x/y' names) keep stable keys no
        matter which enclosing span is active."""
        r = telemetry.Registry()
        with r.span("learner/step"):
            with r.span("buffer/sample"):
                pass
        snap = r.snapshot()
        assert "span/buffer/sample/count" in snap
        assert "span/learner/step/buffer/sample/count" not in snap

    def test_span_stack_unwinds_on_exception(self):
        r = telemetry.Registry()
        with pytest.raises(RuntimeError):
            with r.span("boom"):
                raise RuntimeError()
        with r.span("after"):
            pass
        snap = r.snapshot()
        assert snap["span/boom/count"] == 1
        assert "span/after/count" in snap          # not nested under "boom"
        assert "span/boom/after/count" not in snap

    def test_clear(self):
        r = telemetry.Registry()
        r.counter("c").inc()
        r.clear()
        assert r.snapshot() == {}


class TestSinks:
    def test_jsonl_round_trip(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        r = telemetry.Registry()
        r.gauge("depth").set(3.0)
        with r.span("stage/one"):
            pass
        logger = MetricsLogger(console=False, jsonl=path, registry=r)
        logger.log(1, {"loss": 0.25})
        logger.log(2, {"loss": float("nan")})
        logger.close()

        lines = [json.loads(l) for l in open(path)]
        assert len(lines) == 2
        for ln in lines:
            assert isinstance(ln["ts"], float)
            assert isinstance(ln["step"], int)
            assert isinstance(ln["scalars"], dict)
        assert lines[0]["step"] == 1
        assert lines[0]["scalars"]["loss"] == 0.25
        assert lines[0]["scalars"]["depth"] == 3.0
        assert lines[0]["scalars"]["span/stage/one/count"] == 1
        # non-finite values must not corrupt the stream: encoded as null
        assert lines[1]["scalars"]["loss"] is None

    def test_console_elides_telemetry_keys(self, capsys):
        r = telemetry.Registry()
        r.gauge("transport/queue_depth").set(5.0)
        logger = MetricsLogger(console=True, registry=r)
        logger.log(3, {"loss": 0.5})
        out = capsys.readouterr().out
        assert "loss=0.5" in out
        assert "queue_depth" not in out   # slashed keys are file-sink-only

    def test_log_returns_merged_dict(self):
        r = telemetry.Registry()
        r.gauge("buffer/occupancy").set(9.0)
        logger = MetricsLogger(console=False, registry=r)
        flat = logger.log(0, {"loss": 1.0})
        assert flat["loss"] == 1.0
        assert flat["buffer/occupancy"] == 9.0

    def test_metrics_logger_degrades_without_tensorboardx(self, monkeypatch, capsys):
        """logdir=... must warn and continue when tensorboardX is missing —
        never crash the run (ISSUE 1 satellite)."""
        monkeypatch.setitem(sys.modules, "tensorboardX", None)
        r = telemetry.Registry()
        logger = MetricsLogger(logdir="/tmp/never_created_tb", console=False, registry=r)
        assert "tensorboardX not installed" in capsys.readouterr().out
        logger.log(1, {"loss": 0.1})   # still works through remaining sinks
        logger.close()


class TestLearnerTelemetry:
    @pytest.mark.slow   # tier-1 duration audit (ISSUE 6): ~40s on the reference container
    def test_smoke_run_emits_pipeline_gauges_and_spans(self, tmp_path):
        """The acceptance contract: a tiny run's drained scalars carry the
        staleness/queue-depth/occupancy gauges, and the JSONL record carries
        per-stage span timings for every pipeline layer."""
        from dotaclient_tpu.train.learner import Learner

        path = str(tmp_path / "telemetry.jsonl")
        learner = Learner(
            tiny_config(log_every=1), metrics_jsonl=path
        )  # vec actor (host pool): staleness accounting does real work
        learner.train(2)

        scalars = learner._last_metrics
        assert "actor/weight_staleness" in scalars
        assert "transport/queue_depth" in scalars
        assert "buffer/occupancy" in scalars

        lines = [json.loads(l) for l in open(path)]
        assert lines, "no JSONL lines emitted"
        union = {}
        for ln in lines:
            union.update(ln["scalars"])
        for key in (
            "span/actor/step/mean_s",
            "span/actor/infer/mean_s",
            "span/buffer/insert/mean_s",
            "span/buffer/sample/mean_s",
            "span/learner/consume/mean_s",
            "span/learner/dispatch/mean_s",
            "span/learner/metrics_fetch/mean_s",
            "span/transport/publish_weights/mean_s",
            "actor/weight_refresh_lag",
            "buffer/batch_staleness",
            "actor/frames_shipped",
            "actor/rollouts_shipped",
        ):
            assert key in union, f"missing telemetry key {key}"
        # dispatch timings are real (the train step ran)
        assert union["span/learner/dispatch/count"] >= 2

    @pytest.mark.slow   # tier-1 duration audit (ISSUE 6): ~175s on the reference container
    def test_no_added_device_syncs_in_train_loop(self, monkeypatch):
        """Telemetry must not break the sync discipline: with no log
        boundary in range, the number of device fetches is INDEPENDENT of
        how many optimizer steps run (fetches happen only at log_every
        boundaries and at end-of-run drain)."""
        from dotaclient_tpu.train.learner import Learner

        learner = Learner(tiny_config(log_every=100_000), actor="device")
        learner.train(1)   # compile + warm the pipeline

        calls = {"n": 0}
        real_device_get = jax.device_get

        def counting_device_get(x):
            calls["n"] += 1
            return real_device_get(x)

        monkeypatch.setattr(jax, "device_get", counting_device_get)
        learner.train(2)
        first = calls["n"]
        calls["n"] = 0
        learner.train(6)
        second = calls["n"]
        assert first == second, (
            f"device fetches scale with steps ({first} vs {second}) — "
            f"something inside the train loop is syncing"
        )

    @pytest.mark.slow   # tier-1 duration audit (ISSUE 6): ~143s on the reference container
    def test_fetches_only_at_log_boundaries(self, monkeypatch):
        """With log_every=1 every step is a boundary: fetch count grows by
        exactly the per-boundary cost, pinning fetches TO the boundaries.
        Pinned on the SYNC snapshot path (--sync-snapshots): the async
        engine coalesces boundary jobs when it falls behind, so its fetch
        count is deliberately not per-boundary-deterministic —
        tests/test_snapshot.py covers that mode (the train thread performs
        no boundary fetches at all there)."""
        from dotaclient_tpu.config import LearnerConfig
        from dotaclient_tpu.train.learner import Learner

        learner = Learner(
            tiny_config(
                log_every=1, learner=LearnerConfig(async_snapshots=False)
            ),
            actor="device",
        )
        learner.train(1)

        calls = {"n": 0}
        real_device_get = jax.device_get

        def counting_device_get(x):
            calls["n"] += 1
            return real_device_get(x)

        monkeypatch.setattr(jax, "device_get", counting_device_get)
        learner.train(2)
        base = calls["n"]
        calls["n"] = 0
        learner.train(4)
        assert calls["n"] - base == 2 * 2, (
            "each extra optimizer step at log_every=1 should cost exactly "
            "two fetches (metrics dict + stats drain)"
        )


class TestHostSyncGuard:
    @pytest.fixture()
    def guard(self):
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "check_host_sync",
            os.path.join(root, "scripts", "check_host_sync.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_hot_path_modules_are_clean(self, guard, capsys):
        """The CI tripwire end-to-end: the learner and buffer hot paths
        carry no unannotated host↔device sync patterns (ISSUE 2 satellite:
        the dispatch-only discipline cannot silently regress)."""
        assert guard.main([]) == 0
        assert "host-sync discipline OK" in capsys.readouterr().out

    def test_flags_unannotated_sync_patterns(self, guard):
        src = (
            "def hot(m):\n"
            "    a = float(m['loss'])\n"
            "    b = np.asarray(m['x'])\n"
            "    c = jax.device_get(m)\n"
            "    d = m['y'].item()\n"
            "    m['z'].block_until_ready()\n"
            "    return a, b, c, d\n"
        )
        violations = guard.check_source(src, set(), "x.py")
        assert len(violations) == 5
        assert any("float()" in v for v in violations)
        assert any(".item()" in v for v in violations)

    def test_annotation_and_allowlist_suppress(self, guard):
        src = (
            "def boundary(m):\n"
            "    return float(m)\n"
            "def hot(m):\n"
            "    # host-sync-ok: host integer\n"
            "    return float(m)\n"
        )
        assert guard.check_source(src, {"boundary"}, "x.py") == []
        # ... but only for the named function / annotated line
        assert len(guard.check_source(src, set(), "x.py")) == 1

    def test_closures_get_own_identity(self, guard):
        """A sync inside a closure of an allowed function is still flagged:
        the innermost named def is the unit of allowance."""
        src = (
            "def train():\n"
            "    def after_step(m):\n"
            "        return float(m)\n"
            "    return after_step\n"
        )
        violations = guard.check_source(src, {"train"}, "x.py")
        assert len(violations) == 1 and "after_step" in violations[0]


class TestSchemaChecker:
    @pytest.fixture()
    def checker(self):
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "check_telemetry_schema",
            os.path.join(root, "scripts", "check_telemetry_schema.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_rejects_malformed_lines(self, checker):
        errors = checker.validate_lines(["not json"])
        assert errors and "not valid JSON" in errors[0]
        errors = checker.validate_lines(['{"ts": 1.0, "scalars": {}}'])
        assert any("step" in e for e in errors)
        errors = checker.validate_lines(
            ['{"ts": 1.0, "step": 0, "scalars": {"x": "oops"}}']
        )
        assert any("'x'" in e for e in errors)

    def test_rejects_missing_required_keys(self, checker):
        errors = checker.validate_lines(['{"ts": 1.0, "step": 0, "scalars": {}}'])
        assert any("required telemetry keys" in e for e in errors)

    def test_transport_keys_required_only_on_request(self, checker):
        """ISSUE 3: the socket/shm transport metrics are a separate
        requirement tier — absent from a smoke (in-proc) run's contract,
        enforced via extra_required for socket/shm runs — and the servers
        eager-create every one of them, so a real transport run always
        carries the full set."""
        base = {k: 1.0 for k in checker.REQUIRED_KEYS}
        # span roots spot-checked via /mean_s need the full leaf set
        for k in list(base):
            if k.startswith("span/"):
                root = k.rsplit("/", 1)[0]
                for leaf in checker.TIMER_LEAVES:
                    base[f"{root}/{leaf}"] = 1.0
        line = json.dumps({"ts": 1.0, "step": 0, "scalars": base})
        assert checker.validate_lines([line]) == []
        errors = checker.validate_lines(
            [line], extra_required=checker.SOCKET_TRANSPORT_KEYS
        )
        assert any("transport/fanout_lag_max" in e for e in errors)
        full = dict(base)
        for k in (*checker.SOCKET_TRANSPORT_KEYS, *checker.SHM_TRANSPORT_KEYS):
            full[k] = 0.0
        line2 = json.dumps({"ts": 1.0, "step": 0, "scalars": full})
        assert checker.validate_lines(
            [line2],
            extra_required=(
                *checker.SOCKET_TRANSPORT_KEYS, *checker.SHM_TRANSPORT_KEYS
            ),
        ) == []

    def test_transport_servers_emit_their_schema_keys(self):
        """Constructing the servers alone populates every pinned transport
        metric (eager creation — schema presence is deterministic)."""
        import importlib.util

        from dotaclient_tpu.transport import ShmTransportServer, TransportServer

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "cts", os.path.join(root, "scripts", "check_telemetry_schema.py")
        )
        checker = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(checker)
        reg = telemetry.get_registry()
        srv = TransportServer(port=0)
        shm = ShmTransportServer(
            name=f"tel-{os.getpid()}", slots=1, ring_bytes=1 << 14,
            weights_bytes=1 << 14,
        )
        try:
            snap = reg.snapshot()
            for key in (
                *checker.SOCKET_TRANSPORT_KEYS, *checker.SHM_TRANSPORT_KEYS
            ):
                assert key in snap, f"missing transport metric {key}"
        finally:
            srv.close()
            shm.close()

    @pytest.mark.slow   # tier-1 duration audit (ISSUE 6): ~62s on the reference container
    def test_smoke_run_passes_schema(self, checker, capsys):
        """The CI guard end-to-end: a --smoke learner run with the JSONL
        sink validates cleanly against the documented schema (tier-1
        coverage for the acceptance criterion)."""
        assert checker.main([]) == 0
        assert "telemetry schema OK" in capsys.readouterr().out
