"""Policy-serving plane tests (ISSUE 11).

Pins the serving contracts: the inference-only param tree (slice ==
value_head=False init; training checkpoint and published weights frame
restore bit-identically), the continuous-batching edge cases (deadline
fires with a partial batch, max_batch fires before the deadline, one
request per slot per dispatch, weight hot-swap lands between — never
within — dispatches, carry slots reclaim and zero on disconnect and on
quarantine), the wire lane's poison discipline, the league eval's
bit-identity through the slim path, and the --require-serve telemetry
tier.
"""

import dataclasses
import socket
import threading
import time

import jax
import numpy as np
import pytest

from dotaclient_tpu.config import ModelConfig, RunConfig
from dotaclient_tpu.models import make_policy
from dotaclient_tpu.models.policy import dummy_obs_batch, init_params
from dotaclient_tpu.serve import (
    PolicyServer,
    ServeClient,
    ServeEngine,
    load_inference_params,
    make_inference_policy,
    slice_train_params,
    weights_frame_to_params,
)
from dotaclient_tpu.utils import telemetry


def tiny_config(**serve_over) -> RunConfig:
    cfg = RunConfig()
    return dataclasses.replace(
        cfg,
        model=ModelConfig(unit_embed_dim=8, hidden_dim=8, hero_embed_dim=4),
        env=dataclasses.replace(cfg.env, n_envs=2, max_dota_time=30.0),
        ppo=dataclasses.replace(cfg.ppo, rollout_len=8, batch_rollouts=8),
        serve=dataclasses.replace(cfg.serve, **serve_over),
    )


def full_params(config, seed=0):
    policy = make_policy(config.model, config.obs, config.actions)
    return init_params(policy, jax.random.PRNGKey(seed))


def one_obs(config, seed=0):
    """One deterministic synthetic observation (unbatched leaves)."""
    from scripts.serve_loadgen import synthetic_obs

    return synthetic_obs(config, np.random.default_rng(seed))


class ReplyCollector:
    """Thread-safe sink for engine replies."""

    def __init__(self):
        self.cond = threading.Condition()
        self.replies = []

    def __call__(self, actions, logp, version, request_id, dispatch_idx):
        with self.cond:
            self.replies.append(
                dict(
                    actions=np.asarray(actions).copy(),
                    logp=logp,
                    version=version,
                    request_id=request_id,
                    dispatch_idx=dispatch_idx,
                )
            )
            self.cond.notify_all()

    def wait(self, n, timeout=60.0):
        with self.cond:
            ok = self.cond.wait_for(
                lambda: len(self.replies) >= n, timeout=timeout
            )
        assert ok, f"only {len(self.replies)}/{n} replies arrived"
        return sorted(self.replies, key=lambda r: r["request_id"])


def wait_until(pred, timeout=30.0, poll=0.01):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(poll)
    return pred()


# -- inference-only policy path ----------------------------------------------


def test_slice_matches_slim_init_structure():
    config = tiny_config()
    params = full_params(config)
    slim = slice_train_params(params)
    assert "head_value" in params["params"]
    assert "head_value" not in slim["params"]
    ref = init_params(make_inference_policy(config), jax.random.PRNGKey(0))
    assert jax.tree_util.tree_structure(slim) == jax.tree_util.tree_structure(ref)
    # slicing an already-slim tree is the identity (eval may hand either)
    assert jax.tree_util.tree_structure(
        slice_train_params(slim)
    ) == jax.tree_util.tree_structure(slim)


def test_slim_policy_logits_bit_identical_value_zero():
    config = tiny_config()
    params = full_params(config)
    full = make_policy(config.model, config.obs, config.actions)
    slim_policy = make_inference_policy(config)
    obs = dummy_obs_batch(3, config.obs, config.actions)
    obs["units"] = jax.numpy.asarray(
        np.random.default_rng(0).normal(size=obs["units"].shape), jax.numpy.float32
    )
    carry = full.initial_state(3)
    logits_f, value_f, carry_f = full.apply(params, obs, carry, method="step")
    logits_s, value_s, carry_s = slim_policy.apply(
        slice_train_params(params), obs, carry, method="step"
    )
    for h in logits_f:
        np.testing.assert_array_equal(
            np.asarray(logits_f[h]), np.asarray(logits_s[h])
        )
    np.testing.assert_array_equal(np.asarray(value_s), 0.0)
    for a, b in zip(jax.tree.leaves(carry_f), jax.tree.leaves(carry_s)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_restore_roundtrip_checkpoint_vs_weights_frame(tmp_path):
    """A training checkpoint and a published weights frame load into the
    SAME slim tree and produce identical actions (acceptance criterion)."""
    from dotaclient_tpu.train.ppo import init_train_state
    from dotaclient_tpu.transport.serialize import encode_weights
    from dotaclient_tpu.utils.checkpoint import CheckpointManager

    config = tiny_config(max_batch=2, max_slots=4, batch_window_ms=0.0)
    params = full_params(config, seed=3)
    state = init_train_state(params, config.ppo)
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    assert mgr.save(state, config, force=True)
    mgr.close()

    ck_config, ck_params, ck_step = load_inference_params(
        str(tmp_path / "ckpt")
    )
    assert ck_config.model == config.model
    # the fanout path: encode at the default f32 wire (bit-exact; the
    # bf16 fanout knob deliberately trades exactness for bytes and is
    # out of scope for the identity pin) then decode+slice
    msg = encode_weights(params, version=7)
    fr_version, fr_params = weights_frame_to_params(msg)
    assert fr_version == 7

    flat_ck = jax.tree_util.tree_leaves_with_path(ck_params)
    flat_fr = jax.tree_util.tree_leaves_with_path(fr_params)
    assert [p for p, _ in flat_ck] == [p for p, _ in flat_fr]
    for (path, a), (_, b) in zip(flat_ck, flat_fr):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=str(path)
        )

    # identical trees ⇒ identical actions through the SAME compiled dispatch
    policy = make_inference_policy(config)
    engine = ServeEngine(config, policy, ck_params)
    try:
        obs = one_obs(config)
        carries0 = jax.tree.map(
            jax.numpy.asarray, policy.initial_state(config.serve.max_slots + 1)
        )
        a_ck, logp_ck, _ = engine.reference_step(
            [obs], [0], [1.0], carries0, 0
        )
        carries1 = jax.tree.map(
            jax.numpy.asarray, policy.initial_state(config.serve.max_slots + 1)
        )
        a_fr, logp_fr, _ = engine.reference_step(
            [obs], [0], [1.0], carries1, 0, params=fr_params
        )
        np.testing.assert_array_equal(a_ck, a_fr)
        np.testing.assert_array_equal(logp_ck, logp_fr)
    finally:
        engine.stop()


# -- continuous-batching edge cases -------------------------------------------


def make_engine(config, params=None, registry=None):
    params = params if params is not None else slice_train_params(
        full_params(config)
    )
    return ServeEngine(
        config, make_inference_policy(config), params, registry=registry
    )


def test_deadline_fires_with_partial_batch():
    reg = telemetry.Registry()
    config = tiny_config(max_batch=8, batch_window_ms=60.0, max_slots=8)
    engine = make_engine(config, registry=reg)
    try:
        sink = ReplyCollector()
        obs = one_obs(config)
        for slot in range(3):
            engine.submit(slot, obs, reset=True, reply=sink, request_id=slot + 1)
        replies = sink.wait(3)
        # all three rode ONE deadline-closed window, batch 3/8 full
        assert {r["dispatch_idx"] for r in replies} == {replies[0]["dispatch_idx"]}
        snap = reg.snapshot()
        assert snap["serve/batch_window_hits"] == 1.0
        assert snap["serve/max_batch_hits"] == 0.0
        assert snap["serve/batch_fill"] == pytest.approx(3 / 8)
        assert snap["serve/dispatches_total"] == 1.0
    finally:
        engine.stop()


def test_max_batch_fires_before_deadline():
    reg = telemetry.Registry()
    # a 30 s window that must NOT be waited out: max_batch closes it
    config = tiny_config(max_batch=2, batch_window_ms=30_000.0, max_slots=8)
    engine = make_engine(config, registry=reg)
    try:
        sink = ReplyCollector()
        obs = one_obs(config)
        t0 = time.perf_counter()
        for slot in range(4):
            engine.submit(slot, obs, reset=True, reply=sink, request_id=slot + 1)
        replies = sink.wait(4, timeout=20.0)
        assert time.perf_counter() - t0 < 20.0  # nobody waited out 30 s
        # two full windows of two
        by_dispatch = {}
        for r in replies:
            by_dispatch.setdefault(r["dispatch_idx"], []).append(r)
        assert sorted(len(v) for v in by_dispatch.values()) == [2, 2]
        snap = reg.snapshot()
        assert snap["serve/max_batch_hits"] == 2.0
        assert snap["serve/batch_fill"] == 1.0
    finally:
        engine.stop()


def test_one_request_per_slot_per_dispatch():
    """A pipelining client's second request defers to the NEXT window —
    duplicate carry-scatter indices can never occur, and per-slot request
    order is preserved."""
    config = tiny_config(max_batch=4, batch_window_ms=40.0, max_slots=4)
    engine = make_engine(config)
    try:
        sink = ReplyCollector()
        obs = one_obs(config)
        engine.submit(0, obs, reset=True, reply=sink, request_id=1)
        engine.submit(0, obs, reset=False, reply=sink, request_id=2)
        replies = sink.wait(2)
        assert replies[0]["dispatch_idx"] < replies[1]["dispatch_idx"]
    finally:
        engine.stop()


def test_weight_hot_swap_between_dispatches():
    config = tiny_config(max_batch=2, batch_window_ms=0.0, max_slots=4)
    p1 = slice_train_params(full_params(config, seed=0))
    p2 = slice_train_params(full_params(config, seed=1))
    engine = make_engine(config, params=p1)
    try:
        sink = ReplyCollector()
        obs = one_obs(config)
        engine.submit(0, obs, reset=True, reply=sink, request_id=1)
        r1 = sink.wait(1)[0]
        assert r1["version"] == 0
        engine.submit_weights(5, p2)
        # the swap lands between dispatches: the next request serves v5
        assert wait_until(lambda: engine.version == 5)
        engine.submit(0, obs, reset=False, reply=sink, request_id=2)
        r2 = sink.wait(2)[1]
        assert r2["version"] == 5
        # stale re-submit (an out-of-order fanout frame) is a no-op
        engine.submit_weights(3, p1)
        engine.submit(0, obs, reset=False, reply=sink, request_id=3)
        r3 = sink.wait(3)[2]
        assert r3["version"] == 5
        # never WITHIN a dispatch: every reply of one dispatch shares its
        # version (structural here — version is read once per dispatch —
        # but pin it against a refactor)
        by_dispatch = {}
        for r in sink.replies:
            by_dispatch.setdefault(r["dispatch_idx"], set()).add(r["version"])
        assert all(len(v) == 1 for v in by_dispatch.values())
    finally:
        engine.stop()


def test_hot_swap_changes_actions_deterministically():
    """Same obs + same rng stream index, different weights ⇒ the swap is
    real (logp moves), and replays of each version reproduce exactly."""
    config = tiny_config(max_batch=1, batch_window_ms=0.0, max_slots=2)
    p1 = slice_train_params(full_params(config, seed=0))
    p2 = slice_train_params(full_params(config, seed=1))
    policy = make_inference_policy(config)
    engine = ServeEngine(config, policy, p1)
    try:
        obs = one_obs(config)

        def probe(params):
            carries = jax.tree.map(
                jax.numpy.asarray,
                policy.initial_state(config.serve.max_slots + 1),
            )
            _, logp, _ = engine.reference_step(
                [obs], [0], [1.0], carries, 0, params=params
            )
            return float(logp[0])

        l1, l1_again, l2 = probe(p1), probe(p1), probe(p2)
        assert l1 == l1_again
        assert l1 != l2
    finally:
        engine.stop()


# -- wire lane: slots, quarantine, reclamation --------------------------------


def serve_stack(config, registry=None):
    reg = registry if registry is not None else telemetry.Registry()
    engine = make_engine(config, registry=reg)
    server = PolicyServer(engine, config, port=0, registry=reg)
    return reg, engine, server


@pytest.mark.slow
def test_carry_slot_reuse_after_disconnect_starts_fresh():
    config = tiny_config(max_batch=1, batch_window_ms=0.0, max_slots=2)
    reg, engine, server = serve_stack(config)
    host, port = server.address
    try:
        obs_warm = one_obs(config, seed=1)
        obs_probe = one_obs(config, seed=2)
        a = ServeClient(host, port, config)
        assert a.slot == 0
        a.step(obs_warm, reset=True)   # drive slot 0's carry off zero
        a.step(obs_warm)
        a.close()
        assert wait_until(lambda: server.n_connected == 0)
        b = ServeClient(host, port, config)
        assert b.slot == 0             # lowest free slot: reclaimed
        idx_before = reg.snapshot()["serve/dispatches_total"]
        # NO reset flag: only the release-time zeroing can make this fresh
        b.step(obs_probe, reset=False)
        served_packed = b.last_packed.copy()
        served_logp = b.last_logp
        b.close()
        # reference: a fresh carry at the SAME dispatch index
        carries = jax.tree.map(
            jax.numpy.asarray,
            make_inference_policy(config).initial_state(
                config.serve.max_slots + 1
            ),
        )
        packed, logp, _ = engine.reference_step(
            [obs_probe], [0], [0.0], carries, int(idx_before)
        )
        np.testing.assert_array_equal(packed[0], served_packed)
        assert float(logp[0]) == served_logp
    finally:
        server.close()
        engine.stop()


@pytest.mark.slow
def test_quarantined_client_slot_reclaimed():
    from dotaclient_tpu.serve.server import KIND_SERVE_REQUEST
    from dotaclient_tpu.transport.serialize import frame_crc32
    from dotaclient_tpu.transport.socket_transport import _send_frame

    config = dataclasses.replace(
        tiny_config(max_batch=1, batch_window_ms=0.0, max_slots=1),
    )
    config = dataclasses.replace(
        config,
        transport=dataclasses.replace(config.transport, poison_frame_limit=2),
    )
    reg, engine, server = serve_stack(config)
    host, port = server.address
    try:
        a = ServeClient(host, port, config)
        assert a.slot == 0
        # with max_slots=1 every slot is taken: a joiner is shed (counted)
        with pytest.raises((ConnectionError, socket.timeout, OSError)):
            ServeClient(host, port, config, timeout_s=2.0)
        assert reg.snapshot()["serve/conns_rejected_total"] == 1.0
        # ship poison_frame_limit corrupt frames: CRC trailer deliberately
        # wrong (the chaos harness's corrupt_frame shape)
        payload = b"not a rollout"
        bad_crc = frame_crc32(payload) ^ 0xDEADBEEF
        for _ in range(2):
            _send_frame(a._sock, KIND_SERVE_REQUEST, payload, crc=bad_crc)
        assert wait_until(lambda: server.n_connected == 0)
        snap = reg.snapshot()
        assert snap["transport/frames_corrupt_total"] >= 2.0
        assert snap["transport/peers_quarantined"] == 1.0
        a.close()
        # the quarantined client's slot is reclaimed: a new game attaches
        b = ServeClient(host, port, config)
        assert b.slot == 0
        b.step(one_obs(config), reset=True)
        b.close()
    finally:
        server.close()
        engine.stop()


def test_release_slot_purges_pending_requests():
    """A dead game's queued requests are discarded at release — a stale
    request dispatched after the slot's zero would scatter the old game's
    carry back into the reclaimed row."""
    reg = telemetry.Registry()
    config = tiny_config(max_batch=2, batch_window_ms=30_000.0, max_slots=4)
    engine = make_engine(config, registry=reg)
    try:
        sink = ReplyCollector()
        obs = one_obs(config)
        engine.submit(0, obs, reset=True, reply=sink, request_id=1)
        # the batcher collects req 1 into the (still-open) window...
        assert wait_until(lambda: engine.pending == 0)
        # ...so this dup slot is held back in pending for the NEXT window
        engine.submit(0, obs, reset=False, reply=sink, request_id=2)
        assert wait_until(lambda: engine.pending == 1)
        engine.release_slot(0)   # the game died: its queued request dies too
        # a second slot closes the 2-wide window → one dispatch
        engine.submit(1, obs, reset=True, reply=sink, request_id=3)
        replies = sink.wait(2)
        assert [r["request_id"] for r in replies] == [1, 3]
        assert wait_until(lambda: engine.pending == 0)
        time.sleep(0.1)   # the purged request must never dispatch late
        assert len(sink.replies) == 2
        assert reg.snapshot()["serve/dispatches_total"] == 1.0
    finally:
        engine.stop()


@pytest.mark.slow
def test_shape_skewed_request_poisons_not_crashes():
    """A CRC-valid, decodable request whose obs tree does not fit the
    serving lanes (config-skewed client) rides the poison path; the
    batcher survives and keeps serving everyone else."""
    import dataclasses as dc

    from dotaclient_tpu.serve.server import KIND_SERVE_REQUEST
    from dotaclient_tpu.transport.serialize import encode_rollout_bytes
    from dotaclient_tpu.transport.socket_transport import _send_frame

    config = tiny_config(max_batch=2, batch_window_ms=0.0, max_slots=4)
    config = dc.replace(
        config,
        transport=dc.replace(config.transport, poison_frame_limit=2),
    )
    reg, engine, server = serve_stack(config)
    host, port = server.address
    try:
        skewed = ServeClient(host, port, config)
        good = ServeClient(host, port, config)
        bad_obs = one_obs(config)
        bad_obs["units"] = np.zeros((64, 7), np.float32)   # wrong ObsSpec
        payload = encode_rollout_bytes(
            {"obs": bad_obs, "reset": np.asarray(1.0, np.float32)},
            model_version=0, env_id=skewed.slot, rollout_id=1,
            length=1, total_reward=0.0,
        )
        for _ in range(2):
            _send_frame(skewed._sock, KIND_SERVE_REQUEST, payload)
        assert wait_until(lambda: server.n_connected == 1)   # quarantined
        snap = reg.snapshot()
        assert snap["transport/peers_quarantined"] == 1.0
        assert snap["serve/dispatch_errors_total"] == 0.0   # never dispatched
        # the well-configured client is unaffected
        good.step(one_obs(config), reset=True)
        assert reg.snapshot()["serve/replies_total"] == 1.0
        good.close()
        skewed.close()
    finally:
        server.close()
        engine.stop()


def test_weights_subscription_slices_and_swaps():
    """attach_weights_source: a fanout frame (the snapshot engine's
    publish format) is polled, sliced into the slim tree, and hot-swapped
    — monotonic, between dispatches."""
    from dotaclient_tpu.transport.serialize import encode_weights

    config = tiny_config(
        max_batch=1, batch_window_ms=0.0, max_slots=2, weights_poll_s=0.02
    )
    full = full_params(config, seed=0)
    reg, engine, server = serve_stack(config)

    class StubFanout:
        def __init__(self):
            self.msg = None

        def latest_weights(self):
            return self.msg

    source = StubFanout()
    try:
        server.attach_weights_source(source)
        source.msg = encode_weights(full_params(config, seed=2), version=9)
        assert wait_until(lambda: engine.version == 9)
        snap = reg.snapshot()
        assert snap["serve/weights_version"] == 9.0
        assert snap["serve/weight_swaps_total"] == 1.0
        # an older frame left in the slot is never applied backwards
        source.msg = encode_weights(full, version=4)
        time.sleep(0.1)
        assert engine.version == 9
    finally:
        server.close()
        engine.stop()


# -- league eval through the serving plane ------------------------------------


@pytest.mark.slow
def test_evaluate_bit_identical_to_full_policy_path():
    """The eval satellite's pin: routing evaluate() through the
    inference-only path changes NOTHING — win rate, episode count, and
    reward mean are bit-identical to the training-shaped policy driving
    the same eval loop (eval discards values; sampling is untouched)."""
    from dotaclient_tpu.actor.device_rollout import DeviceActor
    from dotaclient_tpu.league import evaluate

    config = tiny_config()
    params = full_params(config, seed=5)
    policy = make_policy(config.model, config.obs, config.actions)
    n_games, seed = 4, 11
    out = evaluate(
        config, policy, params, "scripted_easy", n_games=n_games, seed=seed
    )

    # reference: the pre-ISSUE-11 behavior — the FULL training-shaped
    # policy on the same eval loop (mirrors evaluate()'s body exactly)
    eval_cfg = dataclasses.replace(
        config,
        env=dataclasses.replace(
            config.env, n_envs=n_games, opponent="scripted_easy"
        ),
        league=dataclasses.replace(config.league, anchor_prob=0.0),
        transport=dataclasses.replace(
            config.transport, rollout_wire_dtype="float32"
        ),
    )
    actor = DeviceActor(
        eval_cfg, policy, seed=seed, registry=telemetry.Registry()
    )
    steps_per_episode = eval_cfg.env.max_dota_time / (
        eval_cfg.env.ticks_per_observation / 30.0
    )
    max_chunks = int(2 * steps_per_episode / config.ppo.rollout_len + 2)
    for i in range(max_chunks):
        actor.collect(params)
        if i % 8 == 7:
            if actor.drain_stats()["episodes_done"] >= n_games:
                break
    stats = actor.drain_stats()
    assert out["win_rate"] == stats["win_rate"]
    assert out["episodes"] == stats["episodes_done"]
    assert out["episode_reward_mean"] == stats["episode_reward_mean"]


@pytest.mark.slow
def test_evaluate_served_plays_full_games():
    """The serving plane's first client: full eval games over the wire."""
    from dotaclient_tpu.league import evaluate_served

    config = tiny_config(max_batch=4, batch_window_ms=1.0, max_slots=8)
    reg, engine, server = serve_stack(config)
    host, port = server.address
    try:
        out = evaluate_served(
            config, (host, port), opponent="scripted_easy", n_games=2,
            seed=3,
        )
        assert out["episodes"] >= 2
        assert 0.0 <= out["win_rate"] <= 1.0
        snap = reg.snapshot()
        assert snap["serve/requests_total"] > 0
        assert snap["serve/dispatches_total"] > 0
    finally:
        server.close()
        engine.stop()


# -- telemetry tier ------------------------------------------------------------


def test_require_serve_schema_tier(tmp_path):
    """A serve process's JSONL satisfies --require-serve at construction —
    every key is eager-created, a zero-traffic server still validates."""
    import sys

    sys.path.insert(0, str(tmp_path))  # no-op; keeps import order explicit
    from scripts.check_telemetry_schema import SERVE_KEYS, validate_lines

    reg = telemetry.Registry()
    config = tiny_config(max_batch=1, batch_window_ms=0.0, max_slots=2)
    engine = make_engine(config, registry=reg)
    server = PolicyServer(engine, config, port=0, registry=reg)
    try:
        path = tmp_path / "serve.jsonl"
        sink = telemetry.JsonlSink(str(path))
        sink.emit(0, reg.snapshot())
        sink.close()
        lines = path.read_text().splitlines()
        errors = validate_lines(
            lines, extra_required=SERVE_KEYS, base_required=()
        )
        assert errors == [], errors
    finally:
        server.close()
        engine.stop()
