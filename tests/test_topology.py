"""Multi-process topology tests (SURVEY.md §1: N actor processes → transport
→ one learner; §5.3: actors are stateless and disposable).

Covers the socket transport's two channels, the AMQP transport against a
faithful in-memory fake of pika (broker semantics: work queue + fanout
exchange), and a real two-OS-process integration run with an actor killed
mid-training — the learner must keep making progress (fault injection the
reference delegated to k8s restart policies).
"""

import dataclasses
import os
import signal
import subprocess
import sys
import threading
import time
import types

import numpy as np
import pytest

from dotaclient_tpu.protos import dota_pb2 as pb
from dotaclient_tpu.transport import (
    SocketTransport,
    TransportServer,
    encode_rollout,
    encode_weights,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def tiny_rollout(version=0, rid=0):
    arrays = {"rewards": np.arange(4, dtype=np.float32) + rid}
    return encode_rollout(
        arrays, model_version=version, env_id=0, rollout_id=rid,
        length=4, total_reward=1.0,
    )


def tiny_weights(version):
    return encode_weights({"w": np.full((3,), float(version), np.float32)}, version)


class TestSocketTransport:
    def test_rollout_roundtrip_and_weights_fanout(self):
        server = TransportServer(port=0)
        try:
            host, port = server.address
            a1 = SocketTransport(host, port)
            a2 = SocketTransport(host, port)
            for i in range(5):
                a1.publish_rollout(tiny_rollout(rid=i))
            deadline = time.time() + 5
            got = []
            while len(got) < 5 and time.time() < deadline:
                got.extend(server.consume_rollouts(16, timeout=0.2))
            assert sorted(r.rollout_id for r in got) == list(range(5))

            server.publish_weights(tiny_weights(3))
            deadline = time.time() + 5
            while time.time() < deadline:
                w1, w2 = a1.latest_weights(), a2.latest_weights()
                if w1 is not None and w2 is not None:
                    break
                time.sleep(0.02)
            assert w1.version == 3 and w2.version == 3
            a1.close(), a2.close()
        finally:
            server.close()

    def test_late_joiner_gets_current_weights(self):
        server = TransportServer(port=0)
        try:
            server.publish_weights(tiny_weights(7))
            host, port = server.address
            late = SocketTransport(host, port)
            deadline = time.time() + 5
            w = None
            while w is None and time.time() < deadline:
                w = late.latest_weights()
                time.sleep(0.02)
            assert w is not None and w.version == 7
            late.close()
        finally:
            server.close()

    def test_dead_actor_does_not_break_server(self):
        server = TransportServer(port=0)
        try:
            host, port = server.address
            doomed = SocketTransport(host, port)
            doomed.publish_rollout(tiny_rollout(rid=1))
            doomed._sock.close()  # simulate actor crash mid-connection
            survivor = SocketTransport(host, port)
            survivor.publish_rollout(tiny_rollout(rid=2))
            deadline = time.time() + 5
            ids = set()
            while len(ids) < 2 and time.time() < deadline:
                ids |= {
                    r.rollout_id
                    for r in server.consume_rollouts(8, timeout=0.2)
                }
            assert 2 in ids  # survivor's experience flows after the crash
            server.publish_weights(tiny_weights(1))  # must not raise
            survivor.close()
        finally:
            server.close()

    def test_stalled_consumer_does_not_block_fanout(self):
        """ISSUE 3 acceptance: publish_weights is a non-blocking enqueue. A
        consumer that never reads its socket must not delay publish_weights
        returning, must not delay a healthy actor receiving new versions,
        and is eventually dropped (counted) once it exceeds the lag
        budget."""
        import socket as socket_mod

        from dotaclient_tpu.utils import telemetry

        reg = telemetry.get_registry()
        dropped_before = reg.counter("transport/fanout_conns_dropped").value
        server = TransportServer(port=0, fanout_max_lag=4)
        try:
            host, port = server.address
            stalled = socket_mod.create_connection((host, port))
            healthy = SocketTransport(host, port)
            deadline = time.time() + 5
            while server.n_connected < 2 and time.time() < deadline:
                time.sleep(0.02)
            assert server.n_connected == 2
            # ~4 MB payload: far beyond the socket buffers, so the stalled
            # connection's writer blocks in its first send and stays there
            big = {"w": np.zeros(1_000_000, np.float32)}
            worst = 0.0
            n_publishes = 7
            for v in range(1, n_publishes + 1):
                t0 = time.perf_counter()
                server.publish_weights(encode_weights(big, v))
                worst = max(worst, time.perf_counter() - t0)
                time.sleep(0.05)
            # non-blocking: each publish is serialize + per-conn enqueue; a
            # blocking fanout would sit in sendall on the stalled socket
            # until its TCP buffers drain (i.e. forever)
            assert worst < 5.0, f"publish_weights blocked for {worst:.1f}s"
            # the healthy actor still receives the latest version
            deadline = time.time() + 20
            got = None
            while time.time() < deadline:
                msg = healthy.latest_weights()
                if msg is not None and msg.version == n_publishes:
                    got = msg.version
                    break
                time.sleep(0.05)
            assert got == n_publishes, "healthy actor starved by stalled peer"
            # the stalled connection blew the lag budget: dropped + counted
            deadline = time.time() + 10
            while server.n_connected > 1 and time.time() < deadline:
                time.sleep(0.05)
            assert server.n_connected == 1
            assert (
                reg.counter("transport/fanout_conns_dropped").value
                > dropped_before
            )
            stalled.close()
            healthy.close()
        finally:
            server.close()

    def test_weights_coalesce_to_latest(self):
        """Back-to-back publishes while a consumer is mid-send must
        coalesce: the actor applies the LATEST version without needing
        every intermediate frame (IMPACT's bounded-staleness license)."""
        from dotaclient_tpu.utils import telemetry

        reg = telemetry.get_registry()
        before = reg.counter("transport/weights_coalesced").value
        server = TransportServer(port=0, fanout_max_lag=1_000_000)
        try:
            host, port = server.address
            actor = SocketTransport(host, port)
            deadline = time.time() + 5
            while server.n_connected < 1 and time.time() < deadline:
                time.sleep(0.02)
            # 4 MB frames: the actor's reader parses slower than the
            # learner serializes, so its TCP buffers fill and the writer
            # reliably falls behind → coalescing must kick in
            big = {"w": np.zeros(1_000_000, np.float32)}
            final = 10
            for v in range(1, final + 1):   # no pacing: force coalescing
                server.publish_weights(encode_weights(big, v))
            deadline = time.time() + 20
            while time.time() < deadline:
                msg = actor.latest_weights()
                if msg is not None and msg.version == final:
                    break
                time.sleep(0.05)
            assert actor.latest_weights().version == final
            assert reg.counter("transport/weights_coalesced").value > before
            # fewer wire sends than publishes is the whole point
            actor.close()
        finally:
            server.close()

    def test_actor_side_detects_learner_loss(self):
        server = TransportServer(port=0)
        host, port = server.address
        actor = SocketTransport(host, port)
        server.close()
        deadline = time.time() + 5
        with pytest.raises(ConnectionError):
            while time.time() < deadline:
                actor.publish_rollout(tiny_rollout())
                time.sleep(0.05)
        actor.close()


# ---------------------------------------------------------------------------
# fake pika: in-memory broker with RMQ work-queue + fanout semantics
# ---------------------------------------------------------------------------


class _FakeBroker:
    def __init__(self):
        self.queues = {}
        self.bindings = {}  # exchange -> [queue names]
        self._anon = 0


class _FakeMethod:
    def __init__(self, tag, queue=""):
        self.delivery_tag = tag
        self.queue = queue


class _FakeChannel:
    def __init__(self, broker):
        self.b = broker
        self._tag = 0

    def queue_declare(self, queue="", durable=False, exclusive=False):
        if not queue:
            self.b._anon += 1
            queue = f"amq.gen-{self.b._anon}"
        self.b.queues.setdefault(queue, [])
        return types.SimpleNamespace(method=_FakeMethod(0, queue=queue))

    def exchange_declare(self, exchange, exchange_type):
        self.b.bindings.setdefault(exchange, [])

    def queue_bind(self, exchange, queue):
        self.b.bindings.setdefault(exchange, []).append(queue)

    def basic_publish(self, exchange, routing_key, body):
        if exchange:
            for q in self.b.bindings.get(exchange, []):
                self.b.queues.setdefault(q, []).append(body)
        else:
            self.b.queues.setdefault(routing_key, []).append(body)

    def consume(self, queue, inactivity_timeout=None):
        while True:
            q = self.b.queues.get(queue, [])
            if q:
                self._tag += 1
                yield _FakeMethod(self._tag), None, q.pop(0)
            else:
                yield None, None, None  # inactivity marker

    def basic_ack(self, delivery_tag):
        pass

    def cancel(self):
        pass

    def basic_get(self, queue, auto_ack=False):
        q = self.b.queues.get(queue, [])
        if not q:
            return None, None, None
        self._tag += 1
        return _FakeMethod(self._tag), None, q.pop(0)


def _install_fake_pika(monkeypatch, broker):
    fake = types.ModuleType("pika")
    fake.ConnectionParameters = lambda host, port: (host, port)
    fake.BlockingConnection = lambda params: types.SimpleNamespace(
        channel=lambda: _FakeChannel(broker)
    )
    monkeypatch.setitem(sys.modules, "pika", fake)


class TestAmqpTransportContract:
    """AmqpTransport against an in-memory broker with pika's call surface —
    the reference's RMQ topology (work queue + fanout) exercised end to end
    without a broker (the sandbox has none)."""

    def test_experience_work_queue(self, monkeypatch):
        from dotaclient_tpu.transport.queues import AmqpTransport

        broker = _FakeBroker()
        _install_fake_pika(monkeypatch, broker)
        actor = AmqpTransport("localhost")
        learner = AmqpTransport("localhost")
        for i in range(4):
            actor.publish_rollout(tiny_rollout(rid=i))
        got = learner.consume_rollouts(10, timeout=0.01)
        assert sorted(r.rollout_id for r in got) == list(range(4))
        # work-queue: consumed exactly once
        assert learner.consume_rollouts(10, timeout=0.01) == []

    def test_weights_fanout_latest_wins(self, monkeypatch):
        from dotaclient_tpu.transport.queues import AmqpTransport

        broker = _FakeBroker()
        _install_fake_pika(monkeypatch, broker)
        a1 = AmqpTransport("localhost")
        a2 = AmqpTransport("localhost")
        learner = AmqpTransport("localhost")
        learner.publish_weights(tiny_weights(1))
        learner.publish_weights(tiny_weights(2))
        assert a1.latest_weights().version == 2  # drained to latest
        assert a2.latest_weights().version == 2  # fanout: every consumer
        assert a1.latest_weights() is None       # nothing new


# ---------------------------------------------------------------------------
# two-OS-process integration with actor kill
# ---------------------------------------------------------------------------


class TestMultiProcessTopology:
    @pytest.mark.slow   # tier-1 duration audit (ISSUE 6): ~69s on the reference container
    def test_learner_survives_actor_kill(self):
        """Two standalone actor processes feed a socket-transport learner;
        one is SIGKILLed mid-run; the learner still reaches its step target
        (stateless-actor fault model, SURVEY.md §5.3)."""
        from dotaclient_tpu.config import default_config
        from dotaclient_tpu.train.learner import Learner

        server = TransportServer(port=0)
        host, port = server.address
        procs = []
        try:
            env = dict(os.environ)
            env.pop("JAX_PLATFORMS", None)  # actor pins cpu itself
            for seed in (0, 1):
                procs.append(
                    subprocess.Popen(
                        [
                            sys.executable, "-m", "dotaclient_tpu.actor",
                            "--connect", f"{host}:{port}",
                            "--n-envs", "4", "--seed", str(seed),
                        ],
                        cwd=REPO, env=env,
                        stdout=subprocess.DEVNULL,
                        stderr=subprocess.DEVNULL,
                    )
                )

            config = default_config()
            config = dataclasses.replace(
                config,
                env=dataclasses.replace(config.env, n_envs=4),
                ppo=dataclasses.replace(
                    config.ppo, batch_rollouts=8, max_staleness=1_000_000
                ),
                buffer=dataclasses.replace(
                    config.buffer, capacity_rollouts=64, min_fill=8
                ),
                log_every=1_000,
            )
            learner = Learner(config, transport=server, actor="external")

            result = {}

            def run():
                result["stats"] = learner.train(8, refresh_every=2)

            t = threading.Thread(target=run, daemon=True)
            t.start()
            # wait until some progress, then kill one actor
            deadline = time.time() + 240
            while learner._host_step < 2 and time.time() < deadline:
                time.sleep(0.5)
            assert learner._host_step >= 2, "learner never started stepping"
            procs[0].kill()
            t.join(timeout=240)
            assert not t.is_alive(), "learner stalled after actor kill"
            assert result["stats"]["optimizer_steps"] >= 8
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            server.close()
