"""League tests: opponent pool mechanics, eval harness, learner wiring.

The *strength* claim (a trained agent beats its frozen past / the scripted
bots) is demonstrated by the committed training demo (``scripts/train_demo.py``,
numbers in BASELINE.md) — these tests pin the mechanics: snapshot cadence,
frozen-copy isolation, opponent sampling, eval bookkeeping, and that league
mode can never silently degrade to mirror self-play (round-1 ADVICE item).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dotaclient_tpu.config import LeagueConfig, default_config
from dotaclient_tpu.league import OpponentPool, evaluate
from dotaclient_tpu.models import init_params, make_policy


def small_config(**env_kw):
    cfg = default_config()
    return dataclasses.replace(
        cfg,
        env=dataclasses.replace(
            cfg.env, n_envs=4, max_dota_time=30.0, **env_kw
        ),
        ppo=dataclasses.replace(cfg.ppo, rollout_len=8, batch_rollouts=8),
        buffer=dataclasses.replace(cfg.buffer, capacity_rollouts=32, min_fill=8),
        log_every=1000,
    )


class TestPfsp:
    def _pool(self, **kw):
        cfg = LeagueConfig(
            pool_size=4, snapshot_every=1, selfplay_prob=0.0,
            matchmaking="pfsp", **kw,
        )
        pool = OpponentPool(cfg, seed=0)
        for i in range(3):
            pool.maybe_snapshot({"w": jnp.full((2,), float(i))}, i, i)
        return pool

    def test_report_attributes_outcomes(self):
        pool = self._pool()
        pool.report(0, wins=9, games=10)
        pool.report(2, wins=1, games=10)
        assert pool.win_rates() == pytest.approx([0.9, 0.5, 0.1])
        # LIVE draws and evicted indices are no-ops, never errors
        from dotaclient_tpu.league.pool import LIVE

        pool.report(LIVE, 5, 5)
        pool.report(99, 5, 5)
        assert pool.win_rates() == pytest.approx([0.9, 0.5, 0.1])

    def test_pfsp_prefers_hard_opponents(self):
        pool = self._pool()
        pool.report(0, wins=98, games=100)   # beaten → rarely drawn
        pool.report(2, wins=2, games=100)    # hard → drawn often
        counts = [0, 0, 0]
        for _ in range(600):
            _, _, idx = pool.sample_indexed({"w": jnp.zeros(2)}, 0)
            counts[idx] += 1
        assert counts[2] > counts[1] > counts[0]
        # starvation floor: the beaten snapshot still appears (forgetting
        # detection)
        assert counts[0] > 0

    def test_uniform_matchmaking_ignores_outcomes(self):
        cfg = LeagueConfig(
            pool_size=4, snapshot_every=1, selfplay_prob=0.0,
            matchmaking="uniform",
        )
        pool = OpponentPool(cfg, seed=0)
        for i in range(3):
            pool.maybe_snapshot({"w": jnp.full((2,), float(i))}, i, i)
        pool.report(0, wins=100, games=100)
        counts = [0, 0, 0]
        for _ in range(900):
            _, _, idx = pool.sample_indexed({"w": jnp.zeros(2)}, 0)
            counts[idx] += 1
        for c in counts:
            assert 200 < c < 400   # ~uniform thirds


class TestOpponentPool:
    def _params(self, val=0.0):
        return {"w": jnp.full((4,), val, jnp.float32)}

    def test_snapshot_cadence_and_ring_bound(self):
        pool = OpponentPool(LeagueConfig(pool_size=3, snapshot_every=100))
        assert pool.maybe_snapshot(self._params(0), 0, 0)
        assert not pool.maybe_snapshot(self._params(1), 1, 50)   # too soon
        assert pool.maybe_snapshot(self._params(2), 2, 100)
        assert pool.maybe_snapshot(self._params(3), 3, 250)
        assert pool.maybe_snapshot(self._params(4), 4, 350)
        assert len(pool) == 3                                     # ring bound
        assert [s.version for s in pool.snapshots] == [2, 3, 4]   # oldest out

    def test_snapshots_are_frozen_copies(self):
        pool = OpponentPool(LeagueConfig(snapshot_every=1))
        live = {"w": jnp.zeros((4,), jnp.float32)}
        pool.maybe_snapshot(live, 0, 0)
        live["w"] = live["w"] + 100.0  # "training" moves the live params
        assert float(pool.snapshots[0].params["w"].sum()) == 0.0

    def test_sampling_mix(self):
        pool = OpponentPool(
            LeagueConfig(snapshot_every=1, selfplay_prob=0.0), seed=0
        )
        live = self._params(7)
        # empty pool: must return live even with selfplay_prob=0
        p, v = pool.sample(live, 42)
        assert v == 42
        pool.maybe_snapshot(self._params(1), 1, 0)
        pool.maybe_snapshot(self._params(2), 2, 1)
        versions = {pool.sample(live, 42)[1] for _ in range(20)}
        assert versions <= {1, 2} and versions  # never live at prob 0
        pool_live = OpponentPool(
            LeagueConfig(snapshot_every=1, selfplay_prob=1.0), seed=0
        )
        pool_live.maybe_snapshot(self._params(1), 1, 0)
        assert all(
            pool_live.sample(live, 42)[1] == 42 for _ in range(10)
        )


class TestEvaluate:
    def test_eval_counts_full_games(self):
        cfg = small_config(opponent="scripted_easy")
        policy = make_policy(cfg.model, cfg.obs, cfg.actions)
        params = init_params(policy, jax.random.PRNGKey(0))
        out = evaluate(
            cfg, policy, params, opponent="scripted_easy", n_games=4, seed=1
        )
        assert out["episodes"] >= 4
        assert 0.0 <= out["win_rate"] <= 1.0
        assert out["episode_reward_mean"] != 0.0

    def test_eval_league_opponent(self):
        cfg = small_config(opponent="league")
        policy = make_policy(cfg.model, cfg.obs, cfg.actions)
        params = init_params(policy, jax.random.PRNGKey(0))
        frozen = init_params(policy, jax.random.PRNGKey(9))
        out = evaluate(
            cfg, policy, params, opponent="league",
            opponent_params=frozen, n_games=4, seed=1,
        )
        assert out["episodes"] >= 4


class TestLeagueAnchors:
    def test_anchor_games_pin_scripted_control(self):
        """anchor_prob pins the opponent side of the first K games to the
        scripted bot (control-mode override) while the rest stay
        snapshot-controlled; PFSP attribution counts only the latter."""
        import numpy as np

        from dotaclient_tpu.actor.device_rollout import DeviceActor
        from dotaclient_tpu.models import init_params, make_policy
        from dotaclient_tpu.protos import dota_pb2 as pb

        cfg = small_config(opponent="league")
        cfg = dataclasses.replace(
            cfg,
            league=dataclasses.replace(
                cfg.league, enabled=True, anchor_prob=0.5,
                anchor_opponent="scripted_hard",
            ),
        )
        policy = make_policy(cfg.model, cfg.obs, cfg.actions)
        da = DeviceActor(cfg, policy, seed=0)
        assert da.n_anchor_games == 2
        control = np.asarray(da.state.sim.control_modes)
        ts = cfg.env.team_size
        assert (control[:2, ts:] == pb.CONTROL_SCRIPTED_HARD).all()
        assert (control[2:, ts:] == pb.CONTROL_AGENT).all()
        assert (control[:, :ts] == pb.CONTROL_AGENT).all()

        params = init_params(policy, jax.random.PRNGKey(0))
        frozen = init_params(policy, jax.random.PRNGKey(9))
        _, stats = da.collect(params, opp_params=frozen)
        # chunk stats are per-game partials (ISSUE 18): anchor games must
        # contribute episodes but never league-attributed ones
        s = jax.device_get(stats)
        assert (s["league_episodes"] <= s["episodes"]).all()
        assert (s["league_wins"] <= s["wins"]).all()
        assert s["league_episodes"][: da.n_anchor_games].sum() == 0.0

    def test_vec_pool_anchor_games_pin_scripted_control(self):
        """The host vec pool honors anchor_prob the same way the device
        actor does: the first K games' opponent side is scripted via the
        sim's control-mode override, and the pool still steps."""
        import numpy as np

        from dotaclient_tpu.actor.vec_runtime import VecActorPool
        from dotaclient_tpu.models import init_params, make_policy
        from dotaclient_tpu.protos import dota_pb2 as pb

        cfg = small_config(opponent="league")
        cfg = dataclasses.replace(
            cfg,
            league=dataclasses.replace(
                cfg.league, enabled=True, anchor_prob=0.5,
                anchor_opponent="scripted_hard",
            ),
        )
        policy = make_policy(cfg.model, cfg.obs, cfg.actions)
        params = init_params(policy, jax.random.PRNGKey(0))
        out: list = []
        pool = VecActorPool(cfg, policy, params, seed=0, rollout_sink=out.extend)
        assert pool.n_anchor_games == 2
        control = np.asarray(pool.sim.control_modes)
        ts = cfg.env.team_size
        assert (control[:2, ts:] == pb.CONTROL_SCRIPTED_HARD).all()
        assert (control[2:, ts:] == pb.CONTROL_AGENT).all()
        assert (control[:, :ts] == pb.CONTROL_AGENT).all()
        pool.set_opponent(init_params(policy, jax.random.PRNGKey(9)), 0)
        for _ in range(cfg.ppo.rollout_len):
            pool.step()
        assert out, "anchored vec pool must still ship rollouts"

    def test_mixed_anchors_split_between_both_bots(self):
        import numpy as np

        from dotaclient_tpu.envs.vec_lane_sim import (
            apply_anchor_games, draft_games,
        )
        from dotaclient_tpu.protos import dota_pb2 as pb

        cfg = small_config(opponent="league")
        league = dataclasses.replace(
            cfg.league, enabled=True, anchor_prob=1.0,
            anchor_opponent="mixed",
        )
        _, control = draft_games(4, cfg.env.team_size, (1,), "league", 0)
        k = apply_anchor_games(control, cfg.env.team_size, "league", league)
        assert k == 4
        ts = cfg.env.team_size
        assert (control[:2, ts:] == pb.CONTROL_SCRIPTED_EASY).all()
        assert (control[2:4, ts:] == pb.CONTROL_SCRIPTED_HARD).all()
        # odd count: easy takes the extra game
        _, control = draft_games(3, cfg.env.team_size, (1,), "league", 0)
        k = apply_anchor_games(control, cfg.env.team_size, "league", league)
        assert k == 3
        assert (control[:2, ts:] == pb.CONTROL_SCRIPTED_EASY).all()
        assert (control[2:3, ts:] == pb.CONTROL_SCRIPTED_HARD).all()

    def test_anchor_easy_share_shifts_the_mix(self):
        from dotaclient_tpu.envs.vec_lane_sim import (
            apply_anchor_games, draft_games,
        )
        from dotaclient_tpu.protos import dota_pb2 as pb

        cfg = small_config(opponent="league")
        ts = cfg.env.team_size
        # 0.9: ceil(3.6)=4 would erase the hard anchor — capped at k-1
        for share, n_easy in ((0.75, 3), (0.0, 0), (1.0, 4), (0.9, 3),
                              (0.01, 1)):
            league = dataclasses.replace(
                cfg.league, enabled=True, anchor_prob=1.0,
                anchor_opponent="mixed", anchor_easy_share=share,
            )
            _, control = draft_games(4, ts, (1,), "league", 0)
            k = apply_anchor_games(control, ts, "league", league)
            assert k == 4
            easy = (
                control[:, ts:] == pb.CONTROL_SCRIPTED_EASY
            ).all(axis=1)
            assert easy.sum() == n_easy, (share, easy)
            assert (
                control[n_easy:, ts:] == pb.CONTROL_SCRIPTED_HARD
            ).all()
        # k=1: the single anchor goes to the MAJORITY bot (round-up-to-
        # easy would invert a mostly-hard share)
        for share, bot in ((0.1, pb.CONTROL_SCRIPTED_HARD),
                           (0.9, pb.CONTROL_SCRIPTED_EASY)):
            league = dataclasses.replace(
                cfg.league, enabled=True, anchor_prob=0.25,
                anchor_opponent="mixed", anchor_easy_share=share,
            )
            _, control = draft_games(4, ts, (1,), "league", 0)
            k = apply_anchor_games(control, ts, "league", league)
            assert k == 1
            assert (control[0, ts:] == bot).all()

    def test_learner_league_with_anchors_trains(self):
        from dotaclient_tpu.train.learner import Learner

        cfg = small_config(opponent="league")
        cfg = dataclasses.replace(
            cfg,
            log_every=1,
            league=dataclasses.replace(
                cfg.league, enabled=True, snapshot_every=2, pool_size=2,
                selfplay_prob=0.0, anchor_prob=0.5,
            ),
        )
        learner = Learner(cfg, actor="fused", seed=2)
        out = learner.train(3)
        assert np.isfinite(out["loss"])
        assert out["optimizer_steps"] == 3.0


class TestEvalCli:
    @pytest.mark.slow   # tier-1 duration audit (ISSUE 6): ~92s on the reference container
    def test_eval_from_checkpoint_and_vs_checkpoint(self, tmp_path, capsys):
        """`python -m dotaclient_tpu.league`: restore a run's checkpoint by
        its OWN stored config and play eval games — the reference's
        watch-TensorBoard eval as one command (SURVEY.md §4)."""
        import json

        from dotaclient_tpu.league.__main__ import main
        from dotaclient_tpu.train.learner import Learner

        cfg = small_config(opponent="scripted_easy")
        ckpt = str(tmp_path / "run_a")
        learner = Learner(cfg, actor="device", seed=3, checkpoint_dir=ckpt)
        learner.train(2)   # end-of-run save included

        rc = main(["--checkpoint", ckpt, "--opponent", "scripted_easy",
                   "--games", "2", "--seed", "1"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out["opponent"] == "scripted_easy"
        assert out["games"] >= 2
        assert 0.0 <= out["win_rate"] <= 1.0

        rc = main(["--checkpoint", ckpt, "--vs", ckpt, "--games", "2",
                   "--seed", "1"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out["opponent"].startswith("checkpoint:")


class TestLearnerLeagueWiring:
    @pytest.mark.slow   # tier-1 duration audit (ISSUE 6): ~90s on the reference container
    def test_device_league_trains_and_snapshots(self):
        from dotaclient_tpu.train.learner import Learner

        cfg = small_config(opponent="league")
        cfg = dataclasses.replace(
            cfg, league=dataclasses.replace(cfg.league, snapshot_every=2)
        )
        lrn = Learner(cfg, actor="device")
        assert lrn.league is not None and len(lrn.league) == 1  # seeded
        stats = lrn.train(6)
        assert stats["optimizer_steps"] >= 6
        assert len(lrn.league) > 1  # snapshots accumulated during training

    def test_device_league_requires_opponent_params(self):
        from dotaclient_tpu.actor.device_rollout import DeviceActor

        cfg = small_config(opponent="league")
        policy = make_policy(cfg.model, cfg.obs, cfg.actions)
        params = init_params(policy, jax.random.PRNGKey(0))
        da = DeviceActor(cfg, policy, seed=0)
        with pytest.raises(ValueError, match="opp_params"):
            da.collect(params)

    def test_vec_league_gets_frozen_opponent(self):
        from dotaclient_tpu.train.learner import Learner

        cfg = small_config(opponent="league")
        lrn = Learner(cfg, actor="vec")
        assert lrn.pool._opponent is not None
        stats = lrn.train(2)
        assert stats["optimizer_steps"] >= 2
